"""Real-chip cost-model validation ladder (run on the TPU; reference:
Galvatron validates its cost model against measured per-config times).

Runs remat on/off x 2 model sizes single-chip, prints predicted vs
measured step times and the rank-order agreement (Kendall tau).  The CPU
test suite validates the size/seq dimensions (tests/test_search.py); the
remat dimension only means anything on the MXU, so it lives here.

Usage: python tools_validate_cost.py [--profile hardware_profile_v5e.json]
"""
from __future__ import annotations

import dataclasses
import json
import sys


def main():
    import jax
    import numpy as np

    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.parallel import ParallelStrategy
    from hetu_tpu.search.calibrate import rank_order_agreement, validate
    from hetu_tpu.search.cost_model import CostModel, StrategyCandidate
    from hetu_tpu.search.profiler import HardwareProfile

    prof_path = None
    if "--profile" in sys.argv:
        prof_path = sys.argv[sys.argv.index("--profile") + 1]
    if prof_path:
        hw = HardwareProfile.load(prof_path)
    else:
        hw = HardwareProfile.preset("v5e")

    sizes = {
        "350m": dict(hidden_size=1024, intermediate_size=2816,
                     num_hidden_layers=12, num_attention_heads=16,
                     num_key_value_heads=16),
        "750m": dict(hidden_size=1536, intermediate_size=4096,
                     num_hidden_layers=16, num_attention_heads=12,
                     num_key_value_heads=12),
    }
    batch, seq = 4, 2048
    cands = [StrategyCandidate(dp=1, tp=1, remat=r, zero=False)
             for r in (False, True)]

    rows_all = []
    for name, kw in sizes.items():
        cfg0 = LlamaConfig(vocab_size=32000, max_position_embeddings=seq,
                           remat=True, remat_policy="dots_attn",
                           use_scan=True, **kw)
        cost = CostModel(hw=hw, num_layers=cfg0.num_hidden_layers,
                         hidden=cfg0.hidden_size,
                         intermediate=cfg0.intermediate_size,
                         vocab=cfg0.vocab_size, num_params=cfg0.num_params(),
                         global_batch=batch, seq_len=seq)

        def build(c, cfg0=cfg0):
            cfg = dataclasses.replace(cfg0, remat=c.remat)
            tc = TrainingConfig(global_batch_size=batch, micro_batch_size=batch,
                                seq_len=seq, lr=1e-4, warmup_steps=2,
                                total_steps=10, log_every=10 ** 9)
            return Trainer(LlamaLMHeadModel(cfg), tc,
                           ParallelStrategy()).build()

        rows = validate(cost, cands, build, steps=4)
        for r in rows:
            r["model"] = name
        rows_all.extend(rows)

    ok, tau = rank_order_agreement(rows_all, tie_rtol=0.05)
    print(json.dumps({"rows": rows_all, "rank_order_ok": ok,
                      "kendall_tau": round(tau, 3)}, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
