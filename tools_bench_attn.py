"""Micro-benchmark the flash-attention kernels on the real chip.

Chains REPS dependent kernel calls inside one jit so device time dominates
the axon tunnel's per-dispatch latency. Used to A/B grid designs
(rectangular + pl.when skip vs compressed pair tables)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

REPS = 16


def timeit(f, *args, iters=5):
    o = f(*args)
    np.asarray(jax.tree_util.tree_leaves(o)[0][0, 0])  # axon-reliable sync
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        o = f(*args)
        np.asarray(jax.tree_util.tree_leaves(o)[0][0, 0])
        ts.append(time.perf_counter() - t0)
    return min(ts) / REPS


def main():
    from hetu_tpu.ops.pallas.flash_attention import flash_attention
    b, s, h, dh = 8, 2048, 12, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.bfloat16)

    @jax.jit
    def fwd(q, k, v):
        def body(qq, _):
            o = flash_attention(qq, k, v, causal=True)
            return o, ()
        o, _ = jax.lax.scan(body, q, None, length=REPS)
        return o

    t_fwd = timeit(fwd, q, k, v)

    @jax.jit
    def fb(q, k, v):
        def body(qq, _):
            g = jax.grad(lambda x: flash_attention(
                x, k, v, causal=True).astype(jnp.float32).sum())(qq)
            return g.astype(qq.dtype), ()
        g, _ = jax.lax.scan(body, q, None, length=REPS)
        return g

    t_fb = timeit(fb, q, k, v)

    # causal attention matmul FLOPs: qk + pv fwd (x2 ops each), bwd adds
    # dv, dp, ds->dq, ds->dk (4 tile matmuls) => bwd = 2x fwd
    f_fwd = b * h * (2 * 2 * s * s * dh) / 2
    f_fb = f_fwd * 3
    peak = 197e12
    print(f"fwd  {t_fwd*1e3:8.2f} ms  {f_fwd/t_fwd/peak:.3f} of peak")
    print(f"f+b  {t_fb*1e3:8.2f} ms  {f_fb/t_fb/peak:.3f} of peak")


if __name__ == "__main__":
    sys.exit(main())
