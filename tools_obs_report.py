"""Summarize a RunLog JSONL for BENCH records.

Reads the structured run-event log a training run leaves next to its
checkpoints (hetu_tpu.obs.RunLog, see docs/observability.md) and prints
one JSON summary: step count, median/p95 step time, aggregate tokens/s,
compile stats, hot-switch/elastic counts, and the hardware-free
estimated MFU recorded at compile time — the numbers a BENCH record
wants, without re-running anything.

    python tools_obs_report.py /ckpts/runlog.jsonl
    python tools_obs_report.py runlog.jsonl --trace timeline.json

--trace additionally renders the run as a Chrome-trace timeline
(open at https://ui.perfetto.dev).  Pure host-side file munging: no jax,
no device contact, safe when the TPU tunnel is down.
"""
from __future__ import annotations

import argparse
import json
import sys


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize(records) -> dict:
    """Aggregate RunLog records (any iterable of dicts) into the BENCH
    summary shape.  Tolerates partial logs: a preempted run still reports
    everything up to its last completed step."""
    records = list(records)
    steps = [r for r in records if r.get("kind") == "step"]
    compiles = [r for r in records if r.get("kind") == "compile"]
    switches = [r for r in records if r.get("kind") == "switch"]
    epochs = [r for r in records if r.get("kind") == "elastic_epoch"]
    faults = [r for r in records if r.get("kind") == "fault"]
    anomalies = [r for r in records if r.get("kind") == "anomaly"]
    stragglers = [r for r in records if r.get("kind") == "straggler"]
    serves = [r for r in records if r.get("kind") == "serve"]

    out: dict = {"steps": len(steps), "compiles": len(compiles),
                 "switches": len(switches), "elastic_epochs": len(epochs)}
    if faults:
        by_kind: dict = {}
        for r in faults:
            k = str(r.get("fault", "unknown"))
            by_kind[k] = by_kind.get(k, 0) + 1
        out["faults"] = by_kind

    # health-monitor anomalies (obs.health): counts by kind + the span a
    # BENCH regression hunt needs (when did it start, did it recover)
    if anomalies:
        by_kind = {}
        for r in anomalies:
            k = str(r.get("anomaly", "unknown"))
            by_kind[k] = by_kind.get(k, 0) + 1
        out["anomalies"] = {
            "total": len(anomalies), "by_kind": by_kind,
            "first": {k: anomalies[0].get(k)
                      for k in ("anomaly", "step", "t")},
            "last": {k: anomalies[-1].get(k)
                     for k in ("anomaly", "step", "t")},
        }

    # cluster straggler reports (obs.aggregate): flag-transition events —
    # counts per worker plus the worst observed ratio
    if stragglers:
        by_rank: dict = {}
        top_ratio, top_rank = None, None
        for r in stragglers:
            for rank in r.get("stragglers") or []:
                by_rank[str(rank)] = by_rank.get(str(rank), 0) + 1
            for rank_s, w in (r.get("workers") or {}).items():
                ratio = w.get("ratio")
                if ratio is not None and (top_ratio is None
                                          or ratio > top_ratio):
                    top_ratio, top_rank = ratio, rank_s
        out["stragglers"] = {"events": len(stragglers),
                             "flagged_by_rank": by_rank}
        if top_ratio is not None:
            out["stragglers"]["top_ratio"] = top_ratio
            out["stragglers"]["top_rank"] = top_rank

    # serving runs (hetu_tpu/serving `serve` events + `span` records):
    # per-request SLO percentiles, the per-class attainment/goodput
    # table and stall attribution — all read through the ONE serving
    # RunLog reader (hetu_tpu/serving/slo_report.py; no second parser)
    if serves:
        from hetu_tpu.serving import slo_report as _slo
        collected = _slo.collect(records)
        dones = collected["dones"]
        reshards = collected["reshards"]
        reports = collected["reports"]
        srv: dict = {"events": len(serves), "requests_done": len(dones)}
        ttfts = sorted(float(r["ttft_s"]) for r in dones
                       if r.get("ttft_s") is not None)
        if ttfts:
            srv["ttft_s"] = {"median": _percentile(ttfts, 50),
                             "p95": _percentile(ttfts, 95)}
        e2es = sorted(float(r["e2e_s"]) for r in dones
                      if r.get("e2e_s") is not None)
        if e2es:
            srv["e2e_s"] = {"median": _percentile(e2es, 50),
                            "p95": _percentile(e2es, 95)}
        toks = [int(r["tokens"]) for r in dones if r.get("tokens")]
        if toks:
            srv["tokens_out"] = sum(toks)
        if reports:
            last = reports[-1]
            for k in ("tokens_per_s", "elapsed_s", "requests"):
                if last.get(k) is not None:
                    srv[k] = last[k]
        if reshards:
            srv["reshards"] = len(reshards)
            srv["final_tier"] = reshards[-1].get("tier")
        reasons: dict = {}
        for r in dones:
            k = str(r.get("reason", "unknown"))
            reasons[k] = reasons.get(k, 0) + 1
        if reasons:
            srv["finished_by"] = reasons
        if dones:
            rep = _slo.serving_report(records, collected=collected)
            srv["classes"] = rep["classes"]
            srv["slo_attainment"] = rep["slo_attainment"]
            for k in ("goodput_tokens_per_s", "stall_breakdown",
                      "reconciliation", "critical_path", "spec_decode",
                      "prefix_cache", "preemptions", "tenants", "costs",
                      "failover", "deadline", "brownout",
                      "disagg", "frontend"):
                if rep.get(k) is not None:
                    srv[k] = rep[k]
        out["serving"] = srv

    # numerics observatory (obs/numerics.py, HETU_TPU_NUMERICS=1): the
    # per-scope tensor/SNR summary + scaler dynamics, read through THE
    # one numerics reader shared with tools_numerics.py (no second
    # parser)
    if any(r.get("kind") == "numerics" for r in records):
        from hetu_tpu.obs.numerics import summarize_numerics
        from tools_numerics import numerics_anomalies
        num = summarize_numerics(records)
        num_out: dict = {"records": num["records"], "worst": num["worst"],
                         "scopes": num["scopes"]}
        anom = numerics_anomalies(records)
        if anom:
            num_out["anomalies"] = anom
        out["numerics"] = num_out
    if any(r.get("kind") == "scaler" for r in records):
        from tools_numerics import scaler_section
        out["scaler"] = scaler_section(records)

    # analytic step profiles (obs.hlo_profile, HETU_TPU_PROFILE=1): the
    # newest profile record matches the plan the run actually stepped
    # with — top-k layers by predicted time + peak HBM vs the chip
    profiles = [r for r in records if r.get("kind") == "profile"]
    budgets = [r for r in records if r.get("kind") == "budget"]
    if profiles:
        last = profiles[-1]
        prof: dict = {"records": len(profiles)}
        for k in ("estimated_step_s", "total_flops", "total_wire_bytes",
                  "peak_hbm_bytes", "peak_hbm_vs_xla",
                  "hbm_headroom_frac"):
            if last.get(k) is not None:
                prof[k] = last[k]
        top = last.get("top") or []
        if top:
            prof["top_layers"] = [
                {"group": t.get("group"), "time_s": t.get("time_s"),
                 "bound": t.get("bound")} for t in top[:5]]
        # peak-HBM vs the chip: hbm_headroom_frac was stamped at RECORD
        # time against the profile the run actually used — re-deriving
        # it from the report machine's hardware profile would let two
        # keys for one quantity disagree
        out["profile"] = prof
    if budgets:
        fails = [r for r in budgets if not r.get("ok")]
        out["budget"] = {"checks": len(budgets), "failed": len(fails),
                         "ok": not fails}
        if fails:
            last_breaches = fails[-1].get("breaches") or []
            out["budget"]["last_breaches"] = [
                b.get("metric") for b in last_breaches]

    # per-compile graph-contract lints (hetu_tpu/analysis,
    # HETU_TPU_LINT=1): totals across the run + the latest record's
    # per-lint counts and first messages — a run that compiled a plan
    # with an error-severity finding is visible from the summary alone
    lints = [r for r in records if r.get("kind") == "lint"]
    if lints:
        last = lints[-1]
        lint_sec: dict = {
            "records": len(lints),
            "findings": sum(int(r.get("findings") or 0) for r in lints),
            "errors": sum(int(r.get("errors") or 0) for r in lints),
            "warnings": sum(int(r.get("warnings") or 0) for r in lints),
        }
        if last.get("lints"):
            lint_sec["last_by_lint"] = last["lints"]
        if last.get("messages"):
            lint_sec["last_messages"] = last["messages"][:5]
        out["lint"] = lint_sec

    times = sorted(float(r["step_time_s"]) for r in steps
                   if r.get("step_time_s"))
    if times:
        out["step_time_s"] = {
            "median": _percentile(times, 50),
            "p95": _percentile(times, 95),
            "min": times[0], "max": times[-1],
        }
    tps = [float(r["tokens_per_s"]) for r in steps if r.get("tokens_per_s")]
    if tps:
        out["tokens_per_s_median"] = _percentile(sorted(tps), 50)
    losses = [float(r["loss"]) for r in steps if r.get("loss") is not None]
    if losses:
        out["loss_first"], out["loss_last"] = losses[0], losses[-1]
    mems = [int(r["device_mem_bytes"]) for r in steps
            if r.get("device_mem_bytes")]
    if mems:
        out["device_mem_bytes_max"] = max(mems)

    # the hardware-free perf signal: estimated MFU stamped per compile
    # (obs.mfu roofline) — report the latest, which matches the plan the
    # run actually stepped with
    est = [r for r in compiles if r.get("estimated_mfu")]
    if est:
        last = est[-1]
        out["estimated_mfu"] = float(last["estimated_mfu"])
        if last.get("flops"):
            out["flops_per_step"] = float(last["flops"])
    compile_s = sorted(float(r["compile_s"]) for r in compiles
                       if r.get("compile_s"))
    if compile_s:
        out["compile_s_total"] = sum(compile_s)

    plans = {r.get("plan") for r in steps if r.get("plan")}
    if plans:
        out["plans"] = sorted(plans)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a RunLog JSONL (steps, step-time "
                    "percentiles, tokens/s, estimated MFU) for BENCH.")
    ap.add_argument("runlog", help="path to a runlog.jsonl")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="also render the run as Chrome-trace JSON "
                         "(open in Perfetto / chrome://tracing)")
    ap.add_argument("--kernels", action="store_true",
                    help="attach the analytic Pallas fused-kernel "
                         "traffic section (tools_bench_kernels.py's "
                         "byte model — the bench detail.kernels record)")
    args = ap.parse_args(argv)

    from hetu_tpu.obs.runlog import RunLog
    records = RunLog.read(args.runlog)
    if not records:
        print(f"no records in {args.runlog}", file=sys.stderr)
        return 1
    out = summarize(records)
    if args.kernels:
        from tools_bench_kernels import kernel_section
        out["kernels"] = kernel_section()
    print(json.dumps(out, indent=2))

    if args.trace:
        from hetu_tpu.obs.trace import trace_from_runlog
        trace_from_runlog(records).save(args.trace)
        print(f"# timeline written to {args.trace}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
