// Host-side LRU embedding cache.
//
// Rebuild of the reference's client-side embedding caches (reference:
// hetu/v1/src/hetu_cache/include/{lru_cache.h,lfu_cache.h} — the HET-paper
// caches that keep hot embedding rows near the worker, with pulls for
// misses).  C ABI for ctypes (no pybind11 in the image).
//
// The cache maps int64 embedding ids -> fixed slots in a caller-owned host
// buffer; lookup assigns slots for misses by evicting the least-recently-used
// id and reports which rows must be fetched from the parameter server /
// KV store (hetu_tpu.rpc) by the caller.
//
// Build: make -C csrc

#include <cstdint>
#include <list>
#include <unordered_map>
#include <vector>

namespace {

struct LruCache {
  int64_t capacity;
  // recency list: front = most recent; entries are ids
  std::list<int64_t> order;
  struct Entry {
    int64_t slot;
    std::list<int64_t>::iterator pos;
  };
  std::unordered_map<int64_t, Entry> map;
  std::vector<int64_t> free_slots;
  int64_t hits = 0, misses = 0, evictions = 0;

  explicit LruCache(int64_t cap) : capacity(cap) {
    free_slots.reserve(cap);
    for (int64_t i = cap - 1; i >= 0; --i) free_slots.push_back(i);
    map.reserve(cap * 2);
  }
};

}  // namespace

extern "C" {

void* lru_create(int64_t capacity) { return new LruCache(capacity); }

void lru_destroy(void* h) { delete static_cast<LruCache*>(h); }

// For each key: out_slots[i] = buffer slot; out_hit[i] = 1 if resident.
// On miss, a slot is assigned (evicting the LRU id if full) and
// out_evicted[i] = the evicted id (or -1).  The caller must fill the slot
// for every miss before using it.
void lru_lookup(void* h, const int64_t* keys, int64_t n, int64_t* out_slots,
                int8_t* out_hit, int64_t* out_evicted) {
  auto* c = static_cast<LruCache*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t key = keys[i];
    out_evicted[i] = -1;
    auto it = c->map.find(key);
    if (it != c->map.end()) {
      // hit: refresh recency
      c->order.erase(it->second.pos);
      c->order.push_front(key);
      it->second.pos = c->order.begin();
      out_slots[i] = it->second.slot;
      out_hit[i] = 1;
      ++c->hits;
      continue;
    }
    ++c->misses;
    out_hit[i] = 0;
    int64_t slot;
    if (!c->free_slots.empty()) {
      slot = c->free_slots.back();
      c->free_slots.pop_back();
    } else {
      int64_t victim = c->order.back();
      c->order.pop_back();
      auto vit = c->map.find(victim);
      slot = vit->second.slot;
      c->map.erase(vit);
      out_evicted[i] = victim;
      ++c->evictions;
    }
    c->order.push_front(key);
    c->map[key] = {slot, c->order.begin()};
    out_slots[i] = slot;
  }
}

void lru_stats(void* h, int64_t* out) {  // [hits, misses, evictions, size]
  auto* c = static_cast<LruCache*>(h);
  out[0] = c->hits;
  out[1] = c->misses;
  out[2] = c->evictions;
  out[3] = static_cast<int64_t>(c->map.size());
}

}  // extern "C"
