// Dynamic-programming core for the auto-parallel strategy search.
//
// Rebuild of the reference's C++ search kernel (reference:
// tools/Galvatron/csrc/dp_core.cpp:22 dynamic_programming_core — per-layer
// strategy DP with a device-memory cap, pybind11-bound there).  Here the
// binding is ctypes (no pybind11 in the image): plain C ABI.
//
// Problem: L homogeneous layer slots, S candidate strategies per layer.
//   time[s]        — per-layer step-time contribution of strategy s
//   mem[s]         — per-layer memory units of strategy s
//   trans[s*S+s2]  — transition cost between consecutive layers s -> s2
//                    (activation resharding between per-layer strategies)
//   budget         — total memory units available per device
// Minimize total time subject to sum(mem) <= budget.
// DP over (layer, mem_used, last_strategy); O(L * budget * S^2).
//
// Build: make -C csrc   (produces libdp_core.so; loaded via ctypes with a
// pure-python fallback in hetu_tpu/search/dp.py)

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <limits>
#include <vector>

extern "C" {

// Returns 0 on success, -1 if infeasible. Writes the chosen strategy per
// layer into out_choice[L] and the total time into *out_time.
int dynamic_programming_core(
    int32_t L, int32_t S, const double* time, const int32_t* mem,
    const double* trans, int32_t budget, int32_t* out_choice,
    double* out_time) {
  const double INF = std::numeric_limits<double>::infinity();
  // dp[m][s] = best time using exactly the first `layer` layers with m
  // memory units consumed and layer-1 assigned strategy s.
  std::vector<double> dp((budget + 1) * S, INF);
  std::vector<double> nxt((budget + 1) * S, INF);
  // parent pointers: layer * (budget+1) * S
  std::vector<int32_t> parent((std::size_t)L * (budget + 1) * S, -1);

  for (int32_t s = 0; s < S; ++s) {
    if (mem[s] <= budget) dp[mem[s] * S + s] = time[s];
  }

  for (int32_t layer = 1; layer < L; ++layer) {
    std::fill(nxt.begin(), nxt.end(), INF);
    for (int32_t m = 0; m <= budget; ++m) {
      for (int32_t s = 0; s < S; ++s) {
        double cur = dp[m * S + s];
        if (cur == INF) continue;
        for (int32_t s2 = 0; s2 < S; ++s2) {
          int32_t m2 = m + mem[s2];
          if (m2 > budget) continue;
          double cand = cur + time[s2] + trans[s * S + s2];
          double& slot = nxt[m2 * S + s2];
          if (cand < slot) {
            slot = cand;
            parent[((std::size_t)layer * (budget + 1) + m2) * S + s2] = s;
          }
        }
      }
    }
    dp.swap(nxt);
  }

  // best terminal state
  double best = INF;
  int32_t bm = -1, bs = -1;
  for (int32_t m = 0; m <= budget; ++m)
    for (int32_t s = 0; s < S; ++s)
      if (dp[m * S + s] < best) { best = dp[m * S + s]; bm = m; bs = s; }
  if (bs < 0) return -1;
  *out_time = best;

  // backtrack
  int32_t m = bm, s = bs;
  for (int32_t layer = L - 1; layer >= 0; --layer) {
    out_choice[layer] = s;
    if (layer == 0) break;
    int32_t ps = parent[((std::size_t)layer * (budget + 1) + m) * S + s];
    m -= mem[s];
    s = ps;
  }
  return 0;
}

// Hetero pipeline-stage partition: given per-device speed ratios (higher =
// faster) and L layers over P stages, assign layer counts proportional to
// speed (the Malleus planner's stage-balancing step, reference:
// python/hetu/engine/strategy.py StrategyModel).
int balance_stages(int32_t L, int32_t P, const double* speed,
                   int32_t* out_layers) {
  double total = 0;
  for (int32_t p = 0; p < P; ++p) total += speed[p];
  if (total <= 0) return -1;
  int32_t assigned = 0;
  for (int32_t p = 0; p < P; ++p) {
    int32_t n = (int32_t)(L * speed[p] / total + 0.5);
    if (n < 1) n = 1;
    out_layers[p] = n;
    assigned += n;
  }
  // fix rounding drift: add/remove from the fastest/slowest stages
  while (assigned != L) {
    int32_t idx = 0;
    if (assigned < L) {
      for (int32_t p = 1; p < P; ++p)
        if (speed[p] > speed[idx]) idx = p;
      out_layers[idx]++; assigned++;
    } else {
      for (int32_t p = 1; p < P; ++p)
        if (out_layers[p] > 1 &&
            (out_layers[idx] <= 1 || speed[p] < speed[idx])) idx = p;
      if (out_layers[idx] <= 1) return -1;
      out_layers[idx]--; assigned--;
    }
  }
  return 0;
}

}  // extern "C"
