// Host-side LFU embedding cache.
//
// Rebuild of the reference's frequency-based client cache (reference:
// hetu/v1/src/hetu_cache/include/lfu_cache.h — the HET-paper LFU variant;
// recommendation workloads follow a power law, so evict-least-frequent
// keeps the hot head resident better than recency alone).  C ABI for
// ctypes, drop-in alongside the LRU core (lru_cache.cpp).
//
// O(1) LFU: frequency buckets hold per-frequency recency lists; eviction
// pops the least-recent entry of the minimum-frequency bucket (LRU
// tie-break inside a bucket, the standard constant-time scheme).
//
// Build: make -C csrc

#include <cstdint>
#include <list>
#include <unordered_map>
#include <vector>

namespace {

struct LfuCache {
  struct Entry {
    int64_t slot;
    int64_t freq;
    std::list<int64_t>::iterator pos;  // position in freq bucket
  };
  int64_t capacity;
  std::unordered_map<int64_t, Entry> map;
  std::unordered_map<int64_t, std::list<int64_t>> buckets;  // freq -> keys
  int64_t min_freq = 0;
  std::vector<int64_t> free_slots;
  int64_t hits = 0, misses = 0, evictions = 0;

  explicit LfuCache(int64_t cap) : capacity(cap) {
    free_slots.reserve(cap);
    for (int64_t i = cap - 1; i >= 0; --i) free_slots.push_back(i);
    map.reserve(cap * 2);
  }

  void bump(Entry& e, int64_t key) {
    auto& from = buckets[e.freq];
    from.erase(e.pos);
    if (from.empty()) {
      buckets.erase(e.freq);
      if (min_freq == e.freq) min_freq = e.freq + 1;
    }
    e.freq += 1;
    auto& to = buckets[e.freq];
    to.push_front(key);
    e.pos = to.begin();
  }
};

}  // namespace

extern "C" {

void* lfu_create(int64_t capacity) { return new LfuCache(capacity); }

void lfu_destroy(void* h) { delete static_cast<LfuCache*>(h); }

// Same contract as lru_lookup: per key emit slot/hit/evicted-id(-1).
void lfu_lookup(void* h, const int64_t* keys, int64_t n, int64_t* out_slots,
                int8_t* out_hit, int64_t* out_evicted) {
  auto* c = static_cast<LfuCache*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t key = keys[i];
    out_evicted[i] = -1;
    auto it = c->map.find(key);
    if (it != c->map.end()) {
      c->bump(it->second, key);
      out_slots[i] = it->second.slot;
      out_hit[i] = 1;
      ++c->hits;
      continue;
    }
    ++c->misses;
    out_hit[i] = 0;
    int64_t slot;
    if (!c->free_slots.empty()) {
      slot = c->free_slots.back();
      c->free_slots.pop_back();
    } else {
      auto& bucket = c->buckets[c->min_freq];
      int64_t victim = bucket.back();  // least recent at min frequency
      bucket.pop_back();
      if (bucket.empty()) c->buckets.erase(c->min_freq);
      auto vit = c->map.find(victim);
      slot = vit->second.slot;
      c->map.erase(vit);
      out_evicted[i] = victim;
      ++c->evictions;
    }
    auto& b1 = c->buckets[1];
    b1.push_front(key);
    c->map[key] = {slot, 1, b1.begin()};
    c->min_freq = 1;
    out_slots[i] = slot;
  }
}

void lfu_stats(void* h, int64_t* out) {  // [hits, misses, evictions, size]
  auto* c = static_cast<LfuCache*>(h);
  out[0] = c->hits;
  out[1] = c->misses;
  out[2] = c->evictions;
  out[3] = static_cast<int64_t>(c->map.size());
}

}  // extern "C"
