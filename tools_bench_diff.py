"""Perf-regression sentinel: diff two BENCH records / profiles against
declared budgets.

Nothing watched the BENCH_r*.json trajectory: a change that quietly
regressed predicted step time or peak HBM shipped unless a human diffed
the JSON.  This tool is the watcher — point it at two consecutive
records and it compares every metric both carry (measured/estimated
MFU, step time, bytes-on-wire, peak HBM) against the relative
thresholds of the active perf budget (`obs/budget.py`; defaults +5%
step time, +10% comm bytes, +10% peak HBM, -5% MFU; override with
`--budgets file.json` or `HETU_TPU_BUDGETS`), checks the NEW record
against the budget's absolute ceilings, and **exits nonzero on any
breach**:

    python tools_bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools_bench_diff.py old_runlog.jsonl new_runlog.jsonl
    python tools_bench_diff.py r04.json r05.json --budgets budgets.json
    python tools_bench_diff.py r04.json r05.json --json   # machine report

Inputs may be driver-wrapped BENCH records ({"cmd", "rc", "tail"}), raw
bench metric lines, or RunLog JSONLs (the newest `profile` record wins,
falling back to the newest `compile` record — the per-compile numbers
`HETU_TPU_PROFILE=1` leaves).  Metrics present in only one record are
reported as skipped, never breached — two old-format records with
nothing comparable pass (exit 0) with a warning.

Exit codes: 0 = pass, 1 = budget/regression breach, 2 = unreadable
input.  Host-side file munging only — no device contact, safe when the
TPU tunnel is down.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional


def _load_record(path: str):
    """(record, source_kind) from `path`: a JSON object (BENCH record,
    kind "bench") or a RunLog JSONL — newest `profile` record, else
    newest `compile` record with an estimate.  (None, None) when
    nothing is parseable."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        print(f"# cannot read {path}: {e}", file=sys.stderr)
        return None, None
    try:
        rec = json.loads(text)
        if isinstance(rec, dict):
            # a one-record RunLog parses as whole-file JSON too —
            # classify by SHAPE, not by how many lines the file had
            if rec.get("kind") == "profile" or "profile_schema" in rec:
                return rec, "profile"
            if rec.get("kind") == "compile":
                return rec, "compile"
            return rec, "bench"
    except ValueError:
        pass
    # JSONL (RunLog): scan for the newest profile / compile record
    profile, compile_rec = None, None
    any_record = False
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        any_record = True
        if rec.get("kind") == "profile" or "profile_schema" in rec:
            profile = rec
        elif rec.get("kind") == "compile" and (
                rec.get("estimated_mfu") or rec.get("estimated_step_s")):
            compile_rec = rec
    if profile is not None:
        return profile, "profile"
    if compile_rec is not None:
        return compile_rec, "compile"
    if any_record:
        # a READABLE runlog that just carries nothing comparable (no
        # profile, no compile estimate) takes the skip-never-breach
        # path — an empty metric set passes with a warning, it must
        # not hard-fail the gate as "unreadable"
        return {}, "empty"
    return None, None


def _bench_detail(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The `detail` dict of a (possibly driver-wrapped) BENCH record."""
    from hetu_tpu.obs.budget import _bench_metric_record
    m = _bench_metric_record(rec)
    return (m or {}).get("detail")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH records / RunLog profiles against "
                    "declared perf budgets; exit nonzero on a breach.")
    ap.add_argument("old", help="baseline record (BENCH_r*.json or a "
                                "runlog.jsonl)")
    ap.add_argument("new", help="candidate record to gate")
    ap.add_argument("--budgets", default=None, metavar="FILE",
                    help="perf-budget JSON (default: HETU_TPU_BUDGETS "
                         "env, else built-in thresholds)")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable report")
    args = ap.parse_args(argv)

    from hetu_tpu.obs.budget import (PerfBudget, check_absolute,
                                     diff_metrics, extract_metrics,
                                     summarize_breaches)
    try:
        budget = PerfBudget.load(args.budgets)
    except (OSError, ValueError) as e:
        print(f"# budget load failed: {e}", file=sys.stderr)
        return 2

    old_rec, old_kind = _load_record(args.old)
    new_rec, new_kind = _load_record(args.new)
    if old_rec is None or new_rec is None:
        print(f"# unreadable record: "
              f"{args.old if old_rec is None else args.new}",
              file=sys.stderr)
        return 2

    old_m = extract_metrics(old_rec)
    new_m = extract_metrics(new_rec)
    if old_kind != new_kind:
        # metrics from DIFFERENT record kinds come from different
        # estimators (a profile's per-group roofline sum vs a compile's
        # whole-program roofline; a bench record's analytic dp=8 comm
        # model and config-twin peak HBM vs a profile's measured wire
        # bytes and liveness peak) — comparing them would flag
        # estimator skew as a regression (or mask a real one); drop
        # every skewed metric rather than fabricate a diff
        skewed = ("step_time_s", "comm_bytes", "peak_hbm_bytes")
        for m in (old_m, new_m):
            for k in skewed:
                m.pop(k, None)
        print(f"# records come from different estimators "
              f"({old_kind} vs {new_kind}); {', '.join(skewed)} "
              f"not compared", file=sys.stderr)

    def _analytic_profile(rec):
        detail = (_bench_detail(rec) or {})
        return bool((detail.get("profile") or {}).get("analytic"))

    def _step_time_kind(rec):
        detail = (_bench_detail(rec) or {})
        if detail.get("step_time_s"):
            return "measured"
        if (detail.get("predicted_step_s")
                or (detail.get("estimate") or {}).get("estimated_step_s")):
            return "analytic"
        return None
    if old_kind == new_kind == "bench":
        # estimator-skew guards for BENCH rounds that straddle a tunnel
        # flip: the analytic twins (config-model peak HBM, roofline
        # step time) legitimately differ from their measured
        # counterparts by more than any regression threshold
        if _analytic_profile(old_rec) != _analytic_profile(new_rec):
            for m in (old_m, new_m):
                m.pop("peak_hbm_bytes", None)
            print("# one record's profile is analytic, the other "
                  "measured; peak_hbm_bytes not compared",
                  file=sys.stderr)
        ok, nk = _step_time_kind(old_rec), _step_time_kind(new_rec)
        if ok and nk and ok != nk:
            for m in (old_m, new_m):
                m.pop("step_time_s", None)
            print(f"# step time is {ok} in one record, {nk} in the "
                  f"other; step_time_s not compared", file=sys.stderr)
    report = diff_metrics(old_m, new_m, budget)
    report["absolute_breaches"] = check_absolute(new_m, budget)
    breaches = report["breaches"] + report["absolute_breaches"]
    report.update(old=args.old, new=args.new, budget=budget.source,
                  metrics_old=old_m, metrics_new=new_m,
                  ok=not breaches)

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for k, d in sorted(report["deltas"].items()):
            print(f"{k:18s} {d['old']:.6g} -> {d['new']:.6g} "
                  f"({d['rel']:+.2%})")
        for k in report["skipped"]:
            print(f"{k:18s} (present on one side only — skipped)")
    if not report["compared"] and not breaches:
        print("# warning: no comparable metrics between the two records",
              file=sys.stderr)
    if breaches:
        print(summarize_breaches(breaches), file=sys.stderr)
        print(f"FAIL: {len(breaches)} budget breach(es) "
              f"({args.old} -> {args.new})", file=sys.stderr)
        return 1
    print(f"OK: no budget breaches ({args.old} -> {args.new})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
