"""Elastic training demo (reference: examples/hetero + the elastic server
flow): start the coordination server and N workers in one process tree;
kill a worker mid-run and watch the survivors re-plan and resume.

    python examples/elastic_train.py --kill-after 10
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import threading
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--kill-after", type=float, default=8.0)
    ap.add_argument("--ckpt-dir", default="/tmp/hetu_tpu_elastic_ck")
    args = ap.parse_args()

    from hetu_tpu.data import pad_batch
    from hetu_tpu.engine import ElasticController, Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.rpc import CoordinationClient, CoordinationServer
    from hetu_tpu.utils.parallel_config import (generate_ds_parallel_config,
                                                read_ds_parallel_config)

    server = CoordinationServer(world_size=2, heartbeat_timeout=1.0)
    me = CoordinationClient("127.0.0.1", server.port, heartbeat_interval=0.2)

    cfg = LlamaConfig.tiny(remat=False)
    rng = np.random.default_rng(0)
    batch = pad_batch([rng.integers(1, 250, size=60) for _ in range(8)], 64)

    def planner(alive):
        if len(alive) >= 2:
            return generate_ds_parallel_config(num_layers=2, dp=4, tp=2)
        return generate_ds_parallel_config(num_layers=2, dp=8)

    def factory(plan):
        st, _ = read_ds_parallel_config(plan)
        print(f"  -> building trainer on {st.describe()}")
        tc = TrainingConfig(global_batch_size=8, micro_batch_size=1,
                            seq_len=64, lr=3e-3, warmup_steps=2,
                            total_steps=1000, log_every=5,
                            ckpt_dir=args.ckpt_dir, ckpt_every=3)
        return Trainer(LlamaLMHeadModel(cfg, st), tc, st).build()

    # a second in-process 'worker' that participates in votes until killed
    class FakeTrainer:
        global_step = 0
        _ckpt = None

        def train_step(self, b):
            time.sleep(0.05)
            self.global_step += 1
            return {"loss": 0.0}

        def save(self, wait=False):
            pass

    peer_hb = CoordinationClient("127.0.0.1", server.port,
                                 heartbeat_interval=0.2)
    peer = ElasticController(peer_hb, lambda p: FakeTrainer(), planner)
    stop = threading.Event()
    threading.Thread(target=lambda: (peer._rebuild(), stop.wait()),
                     daemon=True).start()

    def kill():
        time.sleep(args.kill_after)
        print("  !! killing worker 1")
        stop.set()
        peer_hb._shutdown = True

    threading.Thread(target=kill, daemon=True).start()

    ctl = ElasticController(me, factory, planner)
    trainer = ctl.run([batch] * 200, num_steps=args.steps)
    print(f"done at step {trainer.global_step} after "
          f"{ctl.generation} generation(s)")
    me.exit()
    server.close()


if __name__ == "__main__":
    main()
