"""Auto-parallel search (reference: tools/Galvatron search flow):
profile -> cost model -> search -> ds-parallel JSON."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import json

from hetu_tpu.search import CostModel, HardwareProfile, profile_hardware, search_strategy
from hetu_tpu.search.searcher import emit_ds_config


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=64)
    ap.add_argument("--chip", default=None)
    ap.add_argument("--model", default="llama2_7b")
    ap.add_argument("--global-batch", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=4096)
    ap.add_argument("--out", default="ds_config.json")
    ap.add_argument("--no-measure", action="store_true",
                    help="skip device benchmarks; use chip presets")
    args = ap.parse_args()

    from hetu_tpu.models.llama import LlamaConfig
    cfg = getattr(LlamaConfig, args.model)()
    hw = profile_hardware(chip=args.chip, measure=not args.no_measure)
    print("hardware:", hw.chip, hw.measured)
    cost = CostModel(hw=hw, num_layers=cfg.num_hidden_layers,
                     hidden=cfg.hidden_size,
                     intermediate=cfg.intermediate_size,
                     vocab=cfg.vocab_size, num_params=cfg.num_params(),
                     global_batch=args.global_batch, seq_len=args.seq_len)
    results = search_strategy(cost, args.devices)
    for c, t, m in results:
        toks = args.global_batch * args.seq_len / t
        print(f"  {c.describe():28s} step {t:7.2f}s  mem {m/1e9:5.1f}GB  "
              f"tokens/s {toks:,.0f}")
    best = results[0][0]
    with open(args.out, "w") as f:
        json.dump(emit_ds_config(cost, best), f, indent=2)
    print("wrote", args.out)


if __name__ == "__main__":
    main()
