"""Chat SFT demo: message templates -> tokenizer -> masked-label training
(reference: the lobra/SFT pipeline over python/hetu/data/messages).

A tiny LLaMA fine-tunes on a toy instruction dataset: samples flow through
InputOutputTemplate (user turns masked), the runtime-free in-tree
SentencePiece tokenizer, and the trainer — only assistant tokens (plus the
turn-closing eos) contribute loss.

Run:  JAX_PLATFORMS=cpu python examples/sft_chat.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from hetu_tpu.utils.device import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    from hetu_tpu.data import ChatFormat, InputOutputTemplate, build_sft_example
    from hetu_tpu.data.tokenizers.sp_model import (SentencePieceTokenizer,
                                                   write_model_proto)
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel

    # byte-fallback sp model built in-process (a real run loads
    # tokenizer.model via SentencePieceTokenizer(path))
    pieces = [("<unk>", 0.0, 2), ("<s>", 0.0, 3), ("</s>", 0.0, 3)]
    pieces += [(f"<0x{b:02X}>", 0.0, 6) for b in range(256)]
    tok = SentencePieceTokenizer(model_bytes=write_model_proto(
        pieces, 1, byte_fallback=True))

    dataset = [
        {"input": "name a color", "output": "blue"},
        {"input": "name a number", "output": "seven"},
        {"input": "name a fruit", "output": "plum"},
        {"input": "name a metal", "output": "iron"},
    ]
    template = InputOutputTemplate()
    fmt = ChatFormat()   # llama-chat-like [INST] framing
    seq = 64
    rows = [build_sft_example(s, template, tok.encode, chat_format=fmt,
                              bos_id=tok.bos_id, eos_id=tok.eos_id,
                              max_len=seq) for s in dataset]
    ids = np.zeros((len(rows), seq), np.int32)
    labels = np.full((len(rows), seq), -100, np.int32)
    for i, (r_ids, r_lab) in enumerate(rows):
        ids[i, :len(r_ids)] = r_ids
        labels[i, :len(r_lab)] = r_lab
    masked = float((labels == -100).sum()) / labels.size
    print(f"{len(rows)} samples; {masked:.0%} of label positions masked")

    cfg = LlamaConfig.tiny(remat=False, vocab_size=512)
    tc = TrainingConfig(global_batch_size=len(rows), micro_batch_size=2,
                        seq_len=seq, lr=3e-3, warmup_steps=2,
                        total_steps=60, log_every=1000)
    trainer = Trainer(LlamaLMHeadModel(cfg), tc).build(jax.random.key(0))
    batch = {"input_ids": ids, "labels": labels}
    for step in range(12):
        m = trainer.train_step(batch)
        if step % 3 == 0:
            print(f"step {step}: assistant-token loss "
                  f"{float(m['loss']):.4f}")


if __name__ == "__main__":
    main()
