"""Budgeted embedding-compression scheduling demo (reference: tools/
EmbeddingMemoryCompression/methods/scheduler/ — method switching under a
target compress rate).

Sweeps a memory budget over a set of tables with skewed access
frequencies (hot tables resist compression), then trains a toy two-tower
objective across a MIGRATION: halfway through, the budget halves, tables
move to cheaper methods at the checkpoint boundary, and training
continues.

Run:  python examples/compression_scheduler.py   (CPU-friendly)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from hetu_tpu.utils.device import force_cpu_if_requested
    force_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from hetu_tpu.nn.compression_scheduler import (ScheduledEmbeddings,
                                                   TableSpec, plan_methods)

    tables = [
        TableSpec("user", 20000, 32, access_freq=0.6),
        TableSpec("item", 50000, 32, access_freq=0.3),
        TableSpec("context", 100000, 32, access_freq=0.1),
    ]
    dense_total = sum(t.num_embeddings * t.embedding_dim * 4
                      for t in tables)

    print("== budget sweep ==")
    for frac in (1.0, 0.5, 0.2, 0.05):
        plan = plan_methods(tables, dense_total * frac)
        total = sum(c.bytes for c in plan.values())
        mix = {n: c.method for n, c in plan.items()}
        print(f"budget {frac:4.0%}: {mix}  ({total / 1e6:.1f}MB)")

    print("\n== training across a migration ==")
    sched = ScheduledEmbeddings(tables, dense_total)
    key = jax.random.key(0)
    params = sched.init(key)
    w = jax.random.normal(jax.random.fold_in(key, 7), (64, 1)) * 0.1
    rng = np.random.default_rng(0)
    uids = jnp.asarray(rng.integers(0, 20000, 512))
    iids = jnp.asarray(rng.integers(0, 50000, 512))
    y = jnp.asarray(rng.normal(size=(512, 1)), jnp.float32)

    def loss_fn(params, w):
        f = jnp.concatenate([sched.lookup("user", params, uids),
                             sched.lookup("item", params, iids)], axis=-1)
        return jnp.mean((f @ w - y) ** 2)

    @jax.jit
    def step(params, w):
        l, g = jax.value_and_grad(loss_fn, argnums=(0, 1),
                                  allow_int=True)(params, w)
        params = jax.tree.map(
            lambda p, gr: p - 0.1 * gr.astype(p.dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params, g[0])
        return params, w - 0.1 * g[1], l

    for i in range(30):
        params, w, l = step(params, w)
        if i % 10 == 0:
            print(f"step {i:3d}  loss {float(l):.4f}  "
                  f"mem {sched.memory() / 1e6:.1f}MB")

    print("-- checkpoint boundary: budget halves; migrating --")
    params, migrations = sched.replan(params, budget_bytes=dense_total / 3,
                                      key=jax.random.fold_in(key, 1))
    for m in migrations:
        print(f"  {m['table']}: {m['from']} -> {m['to']}")

    for i in range(30, 60):
        params, w, l = step(params, w)
        if i % 10 == 0:
            print(f"step {i:3d}  loss {float(l):.4f}  "
                  f"mem {sched.memory() / 1e6:.1f}MB")
    print("done — training continued across the migration")


if __name__ == "__main__":
    main()
