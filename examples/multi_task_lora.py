"""Multi-task LoRA fine-tuning demo (the LoBRA flow; reference:
examples/lobra — multi-task adapters over one frozen base with a batch
scheduler and per-task resource planner).

Two tasks share one frozen tiny-LLaMA base; the quota planner splits each
round's token budget by task weight x backlog, the scheduler packs both
tasks' samples into static-shaped micros (cross-task fused leftovers), and
the engine updates only the owning task's adapters per micro.

Run:  python examples/multi_task_lora.py   (CPU-friendly)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from hetu_tpu.utils.device import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    from hetu_tpu import optim
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.peft.lora import LoRAConfig, MultiLoRAManager
    from hetu_tpu.peft.multi_task import (MultiTaskSFTEngine,
                                          TaskQuotaPlanner,
                                          schedule_micro_batches)

    cfg = LlamaConfig.tiny(remat=False)
    model = LlamaLMHeadModel(cfg)
    base = model.init(jax.random.key(0))
    mgr = MultiLoRAManager(model, base, LoRAConfig(rank=8),
                           tasks=["chat", "code"])
    engine = MultiTaskSFTEngine(mgr, optim.AdamW(lr=1e-3))

    rng = np.random.default_rng(0)
    datasets = {
        0: [rng.integers(1, cfg.vocab_size, size=rng.integers(16, 48))
            .astype(np.int32) for _ in range(24)],          # "chat"
        1: [rng.integers(1, cfg.vocab_size, size=rng.integers(16, 48))
            .astype(np.int32) for _ in range(12)],          # "code"
    }
    planner = TaskQuotaPlanner(weights={0: 2.0, 1: 1.0}, round_tokens=4096)
    backlog = {t: sum(len(s) for s in ss) for t, ss in datasets.items()}
    print("round quotas (tokens):", planner.plan(backlog))

    micros = schedule_micro_batches(datasets, max_tokens=256,
                                    train_task_num=2, bucket_sizes=(32, 64))
    print(f"{len(micros)} micros; fused:",
          sum(1 for m in micros if len(m.task_ids()) > 1))
    for epoch in range(3):
        hist = engine.train(micros)
        losses = {t: round(float(np.mean(v)), 4) for t, v in hist.items()}
        print(f"epoch {epoch}: per-task mean loss {losses}")


if __name__ == "__main__":
    main()
