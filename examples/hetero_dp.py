"""Heterogeneous data parallelism demo: a straggling half-cluster gets a
smaller batch share and a smaller tp degree, yet trains the SAME model in
lockstep with the fast half (reference: the Malleus workflow —
python/hetu/engine/strategy.py + hetero DS unions distributed_states.h:158).

Run (CPU virtual mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/hetero_dp.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# honor JAX_PLATFORMS=cpu even where a site plugin force-selects another
# backend (the axon sitecustomize overrides the env var; conftest.py does
# the same dance)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from hetu_tpu import optim
from hetu_tpu.core.mesh import MeshConfig
from hetu_tpu.engine.malleus import StragglerProfile, plan_hetero_dp_shares
from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
from hetu_tpu.parallel import HeteroDPEngine, HeteroDPGroup, ParallelStrategy


def main():
    devs = jax.devices()
    assert len(devs) >= 8, "run with an 8-device mesh (see module docstring)"

    # 1. measure (or inject) per-device speeds; devices 4-7 are 2x slower
    profile = StragglerProfile([1.0] * 4 + [0.5] * 4)

    # 2. plan per-group batch rows proportional to group throughput
    total_rows = 16
    shares = plan_hetero_dp_shares(
        profile, [[0, 1, 2, 3], [4, 5, 6, 7]], [2, 1], total_rows)
    print(f"batch shares (fast/slow): {shares}")

    # 3. per-group strategies: the fast half runs dp2xtp2, the slow half tp4
    cfg = LlamaConfig.tiny(remat=False, num_key_value_heads=4)
    engine = HeteroDPEngine(
        lambda st: LlamaLMHeadModel(cfg, st), optim.AdamW(lr=3e-3),
        [HeteroDPGroup(ParallelStrategy(mesh=MeshConfig(dp=2, tp=2),
                                        zero=False), devs[:4], shares[0]),
         HeteroDPGroup(ParallelStrategy(mesh=MeshConfig(tp=4),
                                        zero=False), devs[4:8], shares[1])])
    engine.build()

    ids = np.random.default_rng(0).integers(
        1, 250, size=(total_rows, 64)).astype(np.int32)
    for step in range(10):
        m = engine.train_step({"input_ids": ids})
        if step % 3 == 0:
            print(f"step {step}: loss {m['loss']:.4f} "
                  f"({int(m['tokens'])} tokens)")
    print("done — both groups hold identical updated params")


if __name__ == "__main__":
    main()
