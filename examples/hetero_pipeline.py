"""Heterogeneous pipeline demo: a Malleus straggler plan executed as ONE
program with per-stage TP degrees (reference: the Malleus/Ampelos line —
python/hetu/engine/strategy.py planners + distributed_states.h:158 unequal
stage groups).

Flow: measured per-device speeds -> AmpelosPlanner picks (tp, stage
layers) -> the plan becomes a ParallelStrategy with pp_tp_eff + uneven
pipeline_stage_layers -> validate() checks it against the engine envelope
-> Trainer runs it (GPipe or 1f1b; SP on).

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python examples/hetero_pipeline.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from hetu_tpu.utils.device import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    from hetu_tpu.core.mesh import MeshConfig
    from hetu_tpu.data import pad_batch
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.engine.ampelos import AmpelosPlanner
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.parallel import ParallelStrategy

    # measured relative speeds: devices 4-7 are straggling at 50%
    speeds = [1.0, 1.0, 1.0, 1.0, 0.5, 0.5, 0.5, 0.5]
    plan = AmpelosPlanner(num_layers=4, tp_candidates=(1, 2)).plan(speeds)
    stage_layers = tuple(s["layers"][1] - s["layers"][0]
                         for s in plan["stages"])
    tp = plan["strategy"]["tp"]
    pp = len(stage_layers)
    print(f"Ampelos plan: tp={tp} pp={pp} stage_layers={stage_layers} "
          f"(score {plan['score']})")

    # execute the plan: fast stages keep full TP, straggler stages run at
    # a reduced effective degree — read straight off the plan's per-stage
    # speeds (MalleusPlanner groups similar speeds into stages)
    pp_tp_eff = None
    if tp > 1:
        pp_tp_eff = tuple(tp if s["speed"] >= 1.0 else max(tp // 2, 1)
                          for s in plan["stages"])
    cfg = LlamaConfig.tiny(num_hidden_layers=sum(stage_layers),
                           pipeline_stage_layers=stage_layers, remat=True)
    st = ParallelStrategy(mesh=MeshConfig(dp=8 // (tp * pp), tp=tp, pp=pp),
                          pp_tp_eff=pp_tp_eff,
                          sequence_parallel=tp > 1, zero=True)
    tc = TrainingConfig(global_batch_size=8, micro_batch_size=2, seq_len=64,
                        lr=3e-3, warmup_steps=2, total_steps=20,
                        log_every=100)
    # the plan-time chokepoint: a plan outside the engine envelope fails
    # HERE with a named error, not at trace time
    st.validate(cfg, n_micro=tc.num_micro_batches(st.dp),
                global_batch=tc.global_batch_size, seq_len=tc.seq_len)

    model = LlamaLMHeadModel(cfg, st)
    tr = Trainer(model, tc, st).build()
    rng = np.random.default_rng(0)
    batch = pad_batch([rng.integers(1, 250, size=60) for _ in range(8)], 64)
    for i in range(6):
        m = tr.train_step(batch)
        if i % 2 == 0:
            print(f"step {i}  loss {float(m['loss']):.4f}  "
                  f"({st.describe()})")
    print("hetero pipeline trained — one program, per-stage TP degrees")


if __name__ == "__main__":
    main()
