"""Embedding-table compression demo (reference:
tools/EmbeddingMemoryCompression/run_compressed.py — train/infer CTR models
with compressed learnable vector storage).

Compares the method families on one table: storage, reconstruction error
(for post-hoc methods) and a short training run (for learnable methods) on
a toy two-tower CTR objective.

Run:  python examples/embedding_compression.py   (CPU-friendly)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from hetu_tpu.utils.device import force_cpu_if_requested
    force_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from hetu_tpu.nn.embedding_compression import (DedupEmbedding,
                                                   HashEmbedding, QREmbedding,
                                                   QuantizedEmbedding,
                                                   TTEmbedding)

    V, D = 5000, 32
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(0, 0.05, (V, D)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, V, 4096), jnp.int32)
    ref = jnp.take(table, ids, axis=0)

    print(f"dense table: {V}x{D} fp32 = {V * D * 4 / 1e6:.1f} MB")

    # --- post-hoc compression of a trained table --------------------------
    for bits in (8, 4):
        emb = QuantizedEmbedding(V, D, bits=bits)
        p = emb.compress(table)
        err = float(jnp.max(jnp.abs(emb.lookup(p, ids) - ref)))
        print(f"quantize int{bits}: {emb.compression():.1f}x, "
              f"max err {err:.4f}")

    dedup = DedupEmbedding(V, D)
    p = dedup.compress(np.asarray(table), atol=5e-2)
    err = float(jnp.max(jnp.abs(dedup.lookup(p, ids) - ref)))
    print(f"dedup (atol=5e-2): {dedup.compression_of(p):.1f}x, "
          f"max err {err:.4f}")

    # --- learnable compressed tables (train on a toy CTR objective) ------
    y = jnp.asarray(rng.integers(0, 2, ids.shape[0]), jnp.float32)

    def train(emb, params, steps=30, lr=0.5):
        def loss(p):
            z = jnp.mean(emb.lookup(p, ids), axis=-1)
            return jnp.mean((jax.nn.sigmoid(z * 20) - y) ** 2)

        g = jax.jit(jax.grad(loss))
        for _ in range(steps):
            params = jax.tree.map(lambda p, d: p - lr * d, params, g(params))
        return float(loss(params))

    for name, emb in [
            ("hash x2", HashEmbedding(V, D, compressed_rows=V // 16)),
            ("QR mult", QREmbedding(V, D)),
            ("TT rank8", TTEmbedding(V, D, vocab_factors=(18, 18, 18),
                                     dim_factors=(4, 4, 2), rank=8))]:
        params = emb.init(jax.random.key(1))
        final = train(emb, params)
        print(f"{name}: {emb.compression():.1f}x, toy-CTR loss {final:.4f}")


if __name__ == "__main__":
    main()
