"""Parameter-server embedding demo: a server-resident table, client LRU
caches of hot rows, sparse pulls per batch, and server-side SGD pushes —
the HET recommendation-model pattern (reference: hetu/v1 ps-lite +
hetu_cache; v1/examples/ctr).

Run:  python examples/ps_embedding.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from hetu_tpu.data.embedding_cache import ps_backed_cache
from hetu_tpu.rpc import CoordinationClient, CoordinationServer


def main():
    server = CoordinationServer(world_size=1)
    client = CoordinationClient("127.0.0.1", server.port,
                                auto_heartbeat=False)

    vocab, dim = 100_000, 32
    cache = ps_backed_cache(client, "ctr_emb", rows=vocab, dim=dim,
                            capacity=4096, init="normal", seed=0)

    rng = np.random.default_rng(0)
    # zipf-ish skewed id traffic: hot head + long tail, like CTR features
    probe = None
    for step in range(20):
        ids = np.unique((rng.zipf(1.3, size=512) - 1) % vocab)
        if probe is None:
            probe = ids[:8]
        rows = cache.lookup(ids)                   # pull-through cache
        # toy sparse update: nudge seen embeddings toward 1, WRITE BACK
        # through the cache (dirty rows reach the PS on eviction/flush)
        cache.write_back(ids, rows - 0.1 * (rows - 1.0))
    cache.flush_dirty()                            # checkpoint-time sync

    st = cache.stats()
    hit_rate = st["hits"] / max(st["hits"] + st["misses"], 1)
    print(f"cache: {st} (hit rate {hit_rate:.1%})")
    err = float(np.abs(client.ps_pull("ctr_emb", probe) - 1.0).mean())
    print(f"hot rows converged toward 1: mean |row-1| = {err:.3f}")
    client.exit()
    server.close()


if __name__ == "__main__":
    main()
