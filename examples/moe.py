"""MoE expert-parallel pretraining (reference: v1 MoE examples; BASELINE
config 3 'GPT-MoE expert parallel')."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    from hetu_tpu.core.mesh import MeshConfig
    from hetu_tpu.data import pad_batch
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.parallel import ParallelStrategy

    cfg = LlamaConfig.tiny(num_experts=args.experts, moe_top_k=args.top_k)
    st = ParallelStrategy(mesh=MeshConfig(dp=args.dp, ep=args.ep, tp=args.tp))
    tc = TrainingConfig(global_batch_size=8, micro_batch_size=2, seq_len=128,
                        lr=3e-3, warmup_steps=5, total_steps=args.steps,
                        log_every=5)
    trainer = Trainer(LlamaLMHeadModel(cfg, st), tc, st).build()
    print(f"MoE {args.experts}e top{args.top_k} on {st.describe()} "
          f"({trainer.model.num_params()/1e6:.0f}M params)")
    rng = np.random.default_rng(0)
    batch = pad_batch([rng.integers(1, 250, size=120) for _ in range(8)], 128)
    trainer.train([batch] * args.steps)


if __name__ == "__main__":
    main()
