"""MLP/CNN graph-executor smoke test (reference: tests/test_cifar10.py —
BASELINE.json config 1). Runs on synthetic 32x32x3 images when no data dir."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import hetu_tpu as ht
from hetu_tpu import nn, optim


def build_cnn(num_classes=10):
    return nn.Sequential([
        nn.Conv2d(3, 32, 3), nn.ReLU(), nn.MaxPool2d(2),
        nn.Conv2d(32, 64, 3), nn.ReLU(), nn.MaxPool2d(2),
    ]), nn.Sequential([
        nn.Linear(8 * 8 * 64, 256), nn.ReLU(), nn.Linear(256, num_classes),
    ])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    conv, head = build_cnn()
    key = jax.random.key(0)
    params = {"conv": conv.init(key), "head": head.init(jax.random.fold_in(key, 1))}
    opt = optim.AdamW(lr=args.lr)
    state = opt.init(params)

    rng = np.random.default_rng(0)
    # synthetic separable data: class k has a distinct mean pattern
    protos = rng.normal(size=(10, 32, 32, 3)).astype(np.float32)

    def sample(n):
        y = rng.integers(0, 10, n)
        x = protos[y] + 0.5 * rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
        return jnp.asarray(x), jnp.asarray(y)

    @jax.jit
    def step(params, state, x, y):
        def loss_fn(p):
            h = conv(p["conv"], x)
            logits = head(p["head"], h.reshape(h.shape[0], -1))
            onehot = jax.nn.one_hot(y, 10)
            loss = ht.ops.softmax_cross_entropy(logits, onehot)
            acc = jnp.mean((logits.argmax(-1) == y).astype(jnp.float32))
            return loss, acc
        (loss, acc), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, state = opt.update(g, state, params)
        return params, state, loss, acc

    for i in range(args.steps):
        x, y = sample(args.batch)
        params, state, loss, acc = step(params, state, x, y)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f} acc {float(acc):.3f}")


if __name__ == "__main__":
    main()
