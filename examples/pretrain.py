"""LLaMA pretraining (reference: examples/pretrain/train_hetu.py).

    python examples/pretrain.py --ds-config ds.json --steps 100
    python examples/pretrain.py --dp 2 --tp 2 --sp --packing
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import json

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", help="experiment YAML (parallel/model/trainer "
                    "sections; see examples/config/)")
    ap.add_argument("--ds-config", help="ds-parallel JSON (planner output)")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1)
    ap.add_argument("--sp", action="store_true")
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "llama2_7b", "llama2_13b", "llama3_8b"])
    ap.add_argument("--data", help=".jsonl with a 'text' field (synthetic "
                    "data when omitted)")
    ap.add_argument("--tokenizer", default="gpt2")
    ap.add_argument("--steps", type=int, default=None,
                help="override total steps (YAML/default 50)")
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--micro-batch", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--packing", action="store_true")
    ap.add_argument("--ckpt-dir")
    args = ap.parse_args()

    from hetu_tpu.core.mesh import MeshConfig
    from hetu_tpu.data import (DataCollatorForLanguageModel, DataLoader,
                               JsonDataset, TokenizedDataset)
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.parallel import ParallelStrategy
    from hetu_tpu.utils.parallel_config import read_ds_parallel_config

    if args.config:
        from hetu_tpu.utils.yaml_config import load_experiment
        model, tc, strategy, _raw = load_experiment(args.config)
        if args.steps is not None:
            tc.total_steps = args.steps
        cfg = model.config
        if args.packing:
            tc.packing = True
        if args.ckpt_dir:
            tc.ckpt_dir = args.ckpt_dir
    elif args.ds_config:
        strategy, _ = read_ds_parallel_config(args.ds_config)
    else:
        strategy = ParallelStrategy(
            mesh=MeshConfig(dp=args.dp, tp=args.tp, pp=args.pp, cp=args.cp),
            sequence_parallel=args.sp)

    cfg = getattr(LlamaConfig, args.model)() if args.model != "tiny" \
        else LlamaConfig.tiny(vocab_size=50304)  # padded (divisible by tp)
    model = LlamaLMHeadModel(cfg, strategy)
    tc = TrainingConfig(
        global_batch_size=args.global_batch, micro_batch_size=args.micro_batch,
        seq_len=args.seq_len, lr=args.lr, total_steps=args.steps,
        packing=args.packing, ckpt_dir=args.ckpt_dir, log_every=10)

    if args.data:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer)
        ds = JsonDataset(args.data, tok, max_seq_len=args.seq_len)
    else:
        ds = TokenizedDataset.synthetic(
            4096, vocab=cfg.vocab_size, min_len=args.seq_len // 4,
            max_len=args.seq_len, seed=0)
    coll = DataCollatorForLanguageModel(args.seq_len, packing=args.packing)
    dl = DataLoader(ds, tc.global_batch_size, coll)

    trainer = Trainer(model, tc, strategy).build()
    print(f"training {args.model} on {strategy.describe()} "
          f"({model.num_params()/1e6:.0f}M params)")

    def batches():
        epoch = 0
        while True:
            yield from dl.epoch(epoch)
            epoch += 1

    trainer.train(batches(), num_steps=args.steps)


if __name__ == "__main__":
    main()
