"""Parallelism hot-switching by sequence-length bucket
(reference: examples/hotspa/llama_hot_switch_trainer.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from hetu_tpu.core.mesh import MeshConfig
from hetu_tpu.data import pad_batch
from hetu_tpu.engine import HotSwitchTrainer, TrainingConfig
from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
from hetu_tpu.parallel import ParallelStrategy


def main():
    cfg = LlamaConfig.tiny()
    # short sequences -> DP-heavy; long sequences -> TP(+SP)
    strategies = [
        ParallelStrategy(mesh=MeshConfig(dp=8)),                        # bucket 0
        ParallelStrategy(mesh=MeshConfig(dp=4, tp=2),
                         sequence_parallel=True),                       # bucket 1
    ]
    tc = TrainingConfig(global_batch_size=8, micro_batch_size=1, seq_len=128,
                        lr=3e-4, total_steps=100, log_every=10)
    trainer = HotSwitchTrainer(lambda s: LlamaLMHeadModel(cfg, s), tc,
                               strategies).build()
    rng = np.random.default_rng(0)
    for step in range(40):
        seq = 64 if step % 4 < 2 else 128           # alternate buckets
        bucket = 0 if seq <= 64 else 1
        batch = pad_batch([rng.integers(1, 250, size=seq - 4)
                           for _ in range(8)], seq)
        trainer.train_step(batch, strategy_id=bucket)
    print("done; strategies used:", {i: h.strategy.describe()
                                     for i, h in trainer._handles.items()})


if __name__ == "__main__":
    main()
