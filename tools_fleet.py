"""Fleet observatory CLI: replay a seeded multi-tenant request trace
through the discrete-event fleet simulator and print the report.

    python tools_fleet.py                                  # 20k requests
    python tools_fleet.py --requests 1000000 --slots 256   # fleet scale
    python tools_fleet.py --tenants acme,bigco,free \
        --quotas free:2:32 --slo-class gold:0.2:0.05:2 --slo-class bulk \
        --preempt --json
    python tools_fleet.py --chrome-trace /tmp/fleet.trace.json --sample 100

The simulator (`hetu_tpu/serving/fleet.py`) drives the REAL serving
state machines — Scheduler admission/reserve-on-admit/preemption,
PagePool/RadixPrefixCache refcounts and eviction, tenant quotas,
RequestTracer span tiling — under a virtual clock priced by an analytic
roofline `ServiceModel`, so no device (and no jax math) is touched and
10^6 requests replay in about a minute on one CPU.  Accounting is exact
per request; the optional RunLog/chrome-trace stream is a deterministic
1-in-N request sample (``--sample`` / HETU_TPU_RUNLOG_SERVE_SAMPLE)
with ``sample_weight`` stamped so `slo_report.py` stays unbiased.

The report carries per-(tenant, class) SLO attainment/goodput/latency
reservoirs, stall attribution (including ``quota_exceeded``), quota
peak occupancy, the per-request cost ledger rolled up per tenant
(`serving/costs.py`), invariant-fuzz and span-reconciliation results,
and the ServiceModel constants used.  ``--json`` output is
byte-identical for a fixed seed + arguments (the determinism golden in
tests/test_fleet.py pins this); ``--chrome-trace`` renders the sampled
requests' per-slot timeline via `obs/trace.py serving_trace` PLUS the
stitched multi-tier view (`stitched_trace`): one lane per fleet hop
(prefill/decode) with every causal edge — dispatch, KV ship/adopt,
replay, fallback — drawn as a Perfetto flow arrow (open at
https://ui.perfetto.dev).  See docs/serving.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _pair(spec: str, name: str) -> tuple:
    lo, _, hi = spec.partition(",")
    lo, hi = int(lo), int(hi or lo)
    if lo <= 0 or hi < lo:
        raise SystemExit(f"--{name} must be LO[,HI] with 0 < LO <= HI, "
                         f"got {spec!r}")
    return lo, hi


def _fmt_hist(h) -> str:
    if not h:
        return f"{'-':>8} {'-':>8} {'-':>8}"
    return f"{h['p50']:>8.4f} {h['p95']:>8.4f} {h['p99']:>8.4f}"


def render_text(rep: dict) -> str:
    ln = []
    ln.append(f"fleet report (schema {rep['fleet_schema']}): "
              f"{rep['completed']}/{rep['requests']} requests, "
              f"{rep['tokens_out']} tokens in {rep['elapsed_s']:.3f} "
              f"simulated s ({rep['tokens_per_s']:.0f} tok/s)")
    ln.append(f"  steps: {rep['steps']}  prefill chunks: "
              f"{rep['prefill_chunks']}  preemptions: "
              f"{rep['preemptions']}  sample: 1-in-{rep['sample']}")
    inv, tc = rep["invariants"], rep["trace_check"]
    ln.append(f"  invariants: {inv['checks']} checks "
              f"{'ok' if inv['ok'] else 'FAILED'}  spans: "
              f"{tc['traces_checked']} traces, max residual "
              f"{tc['max_residual_s']:.3g}s")
    if rep.get("stall_breakdown"):
        parts = ", ".join(f"{k}={v}" for k, v in
                          sorted(rep["stall_breakdown"].items()))
        ln.append(f"  admission stalls: {parts}")
    hdr = (f"  {'tenant/class':>16} {'reqs':>8} {'tokens':>9} "
           f"{'attain':>7} {'goodput/s':>10} "
           f"{'ttft p50':>8} {'p95':>8} {'p99':>8}")
    for title, groups in (("tenant", rep.get("tenants") or {}),
                          ("class", rep.get("classes") or {})):
        if not groups:
            continue
        ln.append(f"per-{title}:")
        ln.append(hdr)
        ln.append("  " + "-" * (len(hdr) - 2))
        for name in sorted(groups):
            g = groups[name]
            ln.append(f"  {name:>16} {g['requests']:>8} "
                      f"{g['tokens_out']:>9} "
                      f"{g['slo_attainment']:>7.3f} "
                      f"{g['goodput_tokens_per_s']:>10.0f} "
                      f"{_fmt_hist(g.get('ttft_s'))}")
    for tenant, q in sorted((rep.get("quotas") or {}).items()):
        ln.append(f"  quota[{tenant}]: slots {q['peak_slots']}"
                  f"/{q['max_slots'] or '-'} peak, pages "
                  f"{q['peak_pages']}/{q['max_pages'] or '-'} peak")
    costs = rep.get("costs") or {}
    for tenant in sorted(costs.get("by_tenant") or {}):
        c = costs["by_tenant"][tenant]
        ln.append(f"  cost[{tenant}]: "
                  f"{c['cost_prefill_flops']:.3g} + "
                  f"{c['cost_decode_flops']:.3g} FLOPs (prefill+decode), "
                  f"{c['cost_page_s']:.3g} page-s, "
                  f"{c['cost_kv_byte_s']:.3g} KV byte-s, "
                  f"{c['cost_wire_bytes']:.0f} wire B")
    if costs.get("total"):
        c = costs["total"]
        ln.append(f"  cost[TOTAL]: {c['cost_prefill_flops']:.3g} + "
                  f"{c['cost_decode_flops']:.3g} FLOPs, "
                  f"{c['cost_page_s']:.3g} page-s, "
                  f"{c['cost_kv_byte_s']:.3g} KV byte-s, "
                  f"{c['cost_wire_bytes']:.0f} wire B")
    dg = rep.get("disagg")
    if dg:
        sh = dg["shipments"]
        ln.append(f"  disagg: {dg['adoptions']} adoptions over "
                  f"{dg['prefill_slots']} prefill slots, "
                  f"{dg['tier_prefill_chunks']} tier chunks; shipments "
                  f"{sh['sent']} sent / {sh['dropped']} dropped / "
                  f"{sh['duped']} duped / {sh['dedups']} deduped / "
                  f"{sh['resends']} resent")
        ln.append(f"  degraded: {dg['prefill_kills']} tier kills, "
                  f"{dg['degraded_steps']} steps "
                  f"({dg['degraded_s']:.3f}s), "
                  f"{dg['colocated_prefills']} colocated prefills, "
                  f"{dg['reprefills']} re-prefills, fallback "
                  f"{'on' if dg['fallback'] else 'OFF (naive)'}")
    pc = rep.get("prefix_cache")
    if pc:
        ln.append(f"  prefix cache: {pc['hits']}/"
                  f"{pc['hits'] + pc['misses']} hits, "
                  f"{pc['shared_tokens']} shared tokens")
    svc = rep["service_model"]
    ln.append(f"  service model: {svc['flops_per_token']:.3g} FLOPs/tok, "
              f"{svc['peak_flops']:.3g} peak FLOP/s, "
              f"{svc['hbm_bytes_per_s']:.3g} HBM B/s, "
              f"{svc['step_overhead_s']*1e6:.0f}us/step overhead")
    return "\n".join(ln)


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        description="million-request fleet simulation over the real "
                    "serving state machines (no device, no jax math)")
    # ---- workload
    ap.add_argument("--requests", type=int, default=20_000)
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="mean arrival rate, requests/s")
    ap.add_argument("--burst", type=int, default=0,
                    help="requests per burst (0 = Poisson arrivals)")
    ap.add_argument("--tenants", default="default",
                    help="comma-separated tenant names, assigned "
                         "round-robin")
    ap.add_argument("--slo-class", action="append", default=[],
                    metavar="NAME[:TTFT_S[:GAP_S[:PRIO]]]",
                    help="SLO class (repeatable), assigned round-robin")
    ap.add_argument("--prompt-lens", default="16,64", metavar="LO[,HI]")
    ap.add_argument("--max-new", default="4,16", metavar="LO[,HI]")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="tokens of shared prompt prefix (exercises the "
                         "radix cache)")
    ap.add_argument("--seed", type=int, default=0)
    # ---- fleet shape
    ap.add_argument("--slots", type=int, default=64)
    ap.add_argument("--pages", type=int, default=0,
                    help="KV pages (0 = full reservation per slot)")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--prefix-cache", action="store_true")
    ap.add_argument("--preempt", action="store_true",
                    help="arm SLO-priority preemptive admission")
    ap.add_argument("--quotas", default="",
                    metavar="TENANT[:SLOTS[:PAGES]],...",
                    help="per-tenant admission quotas "
                         "(HETU_TPU_SERVE_QUOTAS syntax)")
    ap.add_argument("--invariant-every", type=int, default=997,
                    help="check_invariants() every N sim steps")
    ap.add_argument("--retry-budget", type=int, default=0,
                    help="replica-death / re-prefill retries allowed "
                         "per request before retry_exhausted")
    # ---- disaggregated prefill/decode tiers (docs/serving.md)
    ap.add_argument("--disagg", action="store_true",
                    help="prefill on a separate tier running "
                         "concurrently with decode; KV ships over an "
                         "acked at-least-once wire")
    ap.add_argument("--prefill-slots", type=int, default=0,
                    help="prefill-tier width (0 = --slots)")
    ap.add_argument("--ship-latency", type=float, default=500e-6,
                    metavar="S", help="one-way shipment wire latency")
    ap.add_argument("--ship-timeout", type=float, default=0.05,
                    metavar="S",
                    help="un-acked shipment retransmit timeout")
    ap.add_argument("--ship-retry", type=int, default=2,
                    help="shipment resends before re-prefilling")
    ap.add_argument("--no-fallback", action="store_true",
                    help="naive mode: a dead prefill tier makes "
                         "arrivals wait instead of degrading to "
                         "colocated chunked prefill (the comparison "
                         "baseline)")
    # ---- service model
    ap.add_argument("--num-params", type=float, default=8e9)
    ap.add_argument("--layers", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=4096)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--kv-mode", default="fp16",
                    choices=("fp16", "int8", "int8_seg"))
    ap.add_argument("--hw-profile", default=None,
                    help="hardware profile JSON (default: obs/mfu "
                         "resolution chain)")
    # ---- output
    ap.add_argument("--sample", type=int, default=0,
                    help="RunLog/trace request sampling 1-in-N "
                         "(0 = HETU_TPU_RUNLOG_SERVE_SAMPLE)")
    ap.add_argument("--runlog", default=None,
                    help="write the sampled serve/span stream here "
                         "(readable by tools_serving_report.py)")
    ap.add_argument("--chrome-trace", default=None,
                    help="write the sampled requests' per-slot Perfetto "
                         "timeline here")
    ap.add_argument("--json", dest="json_out", action="store_true",
                    help="print the report as JSON (byte-identical per "
                         "seed) instead of text")
    args = ap.parse_args(argv)

    from hetu_tpu.obs.mfu import load_hardware_profile
    from hetu_tpu.obs.runlog import RunLog
    from hetu_tpu.serving.fleet import (FleetConfig, FleetSimulator,
                                        analytic_models, fleet_workload)
    from hetu_tpu.serving.request import SLOClass, parse_quotas

    classes = ([SLOClass.parse(s) for s in args.slo_class]
               if args.slo_class else None)
    reqs = fleet_workload(
        args.requests, rate_per_s=args.rate, burst=args.burst,
        tenants=[t for t in args.tenants.split(",") if t],
        slo_classes=classes,
        prompt_lens=_pair(args.prompt_lens, "prompt-lens"),
        max_new=_pair(args.max_new, "max-new"),
        shared_prefix_len=args.shared_prefix, seed=args.seed)
    svc, cost = analytic_models(
        num_params=args.num_params, num_layers=args.layers,
        hidden_size=args.hidden, num_kv_heads=args.kv_heads,
        head_dim=args.head_dim, page_size=args.page_size,
        kv_mode=args.kv_mode,
        hw=load_hardware_profile(args.hw_profile))
    cfg = FleetConfig(
        num_slots=args.slots, page_size=args.page_size,
        max_len=args.max_len, prefill_chunk=args.prefill_chunk,
        num_pages=args.pages, prefix_cache=args.prefix_cache,
        preempt=args.preempt, quotas=parse_quotas(args.quotas),
        invariant_every=args.invariant_every, sample=args.sample,
        retry_budget=args.retry_budget, disagg=args.disagg,
        prefill_slots=args.prefill_slots,
        ship_latency_s=args.ship_latency,
        ship_timeout_s=args.ship_timeout, ship_retry=args.ship_retry,
        fallback=not args.no_fallback)

    log_path = args.runlog
    if log_path is None and args.chrome_trace:
        import tempfile
        log_path = os.path.join(
            tempfile.mkdtemp(prefix="hetu_fleet_"), "fleet.jsonl")
    run_log = RunLog(log_path) if log_path else None
    sim = FleetSimulator(svc, config=cfg, cost_model=cost,
                         run_log=run_log)
    rep = sim.run(reqs)
    if run_log is not None:
        run_log.close()

    if args.chrome_trace:
        from hetu_tpu.obs.trace import serving_trace, stitched_trace
        tr = serving_trace(RunLog.read(log_path), pid="fleet")
        # the stitched multi-tier view rides the same file under its own
        # process: per-hop (prefill/decode) lanes with every causal edge
        # drawn as a flow arrow.  Built from the sim's in-memory hops —
        # prefill-tier spans deliberately never enter the RunLog stream.
        hops = list(sim.tracer.completed)
        if sim.pf_tracer is not None:
            hops += sim.pf_tracer.completed
        n_flows = 0
        if hops:
            from hetu_tpu.obs.spans import FleetTrace
            fts = FleetTrace.stitch(traces=hops, events=sim._events)
            st = stitched_trace(fts, pid="fleet-stitched")
            n_flows = sum(1 for e in st.events if e.get("ph") == "s")
            tr.events.extend(st.events)
        tr.save(args.chrome_trace)
        print(f"chrome trace -> {args.chrome_trace} "
              f"(1-in-{rep['sample']} requests, {n_flows} flow edges)",
              file=sys.stderr)
    if log_path:
        print(f"runlog -> {log_path}", file=sys.stderr)

    if args.json_out:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        print(render_text(rep))
    return 0 if (rep["completed"] == rep["requests"]
                 and rep["invariants"]["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
