"""Per-collective bytes-on-wire table for a compiled train step.

Lowers one Trainer train step for a tiny LLaMA on a virtual dp-mesh
(CPU — no device contact, safe when the TPU tunnel is down), walks the
optimized HLO with the obs.comm analyzer, and prints every collective's
payload/wire bytes plus the aggregate report — the comm twin of
tools_obs_report.py.

    python tools_comm_report.py                      # dp=4, fp32 sync
    python tools_comm_report.py --compress int8-ef   # quantized sync
    python tools_comm_report.py --compare            # both + the ratio
    python tools_comm_report.py --dp 8 --zero        # ZeRO-1 lowering

The model lowers with use_scan=False so every collective is top-level in
the HLO and the static count is exact (obs.comm's while-loop caveat).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":
    # must precede any jax import: the analyzer needs a real dp mesh
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()


def lowered_step_report(mode: str, *, dp: int = 4, zero: bool = False,
                        batch: int = 8, seq: int = 64):
    """(collective_report, collective_table) for one compiled tiny-LLaMA
    train step under HETU_TPU_GRAD_COMPRESS=`mode`."""
    os.environ["HETU_TPU_GRAD_COMPRESS"] = mode
    import numpy as np

    from hetu_tpu.core.mesh import MeshConfig
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.obs.comm import collective_report, collective_table
    from hetu_tpu.parallel import ParallelStrategy

    cfg = LlamaConfig.tiny(remat=False, use_scan=False)
    st = ParallelStrategy(mesh=MeshConfig(dp=dp), zero=zero)
    tc = TrainingConfig(global_batch_size=batch,
                        micro_batch_size=max(batch // dp, 1), seq_len=seq,
                        warmup_steps=2, total_steps=10, log_every=1000)
    tr = Trainer(LlamaLMHeadModel(cfg, st), tc, st).build()
    rng = np.random.default_rng(0)
    hb = {"input_ids": rng.integers(1, 250, (batch, seq)).astype(np.int32),
          "labels": rng.integers(1, 250, (batch, seq)).astype(np.int32)}
    key = tuple(sorted((k, tuple(v.shape)) for k, v in hb.items()))
    compiled = tr._compiled_for_shape(hb, key)
    return collective_report(compiled), collective_table(compiled)


def _print_table(mode: str, report, table, verbose: bool):
    print(f"== HETU_TPU_GRAD_COMPRESS={mode} ==")
    print(f"{'collective':<20}{'count':>6}{'wire bytes':>14}")
    for op, rec in sorted(report["collectives"].items()):
        print(f"{op:<20}{rec['count']:>6}{rec['wire_bytes']:>14,.0f}")
    print(f"{'TOTAL':<20}{report['num_collectives']:>6}"
          f"{report['total_wire_bytes']:>14,.0f}"
          f"   predicted {report['predicted_comm_s'] * 1e6:.1f}us "
          f"({report['chip']})")
    if verbose:
        for r in table:
            print(f"  {r['op']:<18}{r['out_bytes']:>10} B  "
                  f"n={r['group_size']}  wire={r['wire_bytes']:,.0f}")
    print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Bytes-on-wire table of a compiled train step "
                    "(hardware-free; obs.comm analyzer).")
    ap.add_argument("--compress", default="none",
                    choices=("none", "int8", "int8-ef"))
    ap.add_argument("--compare", action="store_true",
                    help="lower BOTH none and int8-ef, print the ratio")
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1 (reduce-scatter/all-gather lowering)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print each collective instruction")
    args = ap.parse_args(argv)

    modes = (("none", "int8-ef") if args.compare else (args.compress,))
    reports = {}
    for mode in modes:
        rep, table = lowered_step_report(
            mode, dp=args.dp, zero=args.zero, batch=args.batch,
            seq=args.seq)
        reports[mode] = rep
        _print_table(mode, rep, table, args.verbose)

    summary = {m: {"total_wire_bytes": r["total_wire_bytes"],
                   "num_collectives": r["num_collectives"],
                   "predicted_comm_s": r["predicted_comm_s"]}
               for m, r in reports.items()}
    if args.compare:
        f32 = reports["none"]["total_wire_bytes"]
        q = reports["int8-ef"]["total_wire_bytes"]
        summary["ratio"] = (f32 / q) if q else None
        print(f"bytes-on-wire ratio fp32/int8: {summary['ratio']:.2f}x")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
