"""Per-collective bytes-on-wire table for a compiled train step.

Lowers one Trainer train step for a tiny LLaMA on a virtual dp-mesh
(CPU — no device contact, safe when the TPU tunnel is down), walks the
optimized HLO with the obs.comm analyzer, and prints every collective's
payload/wire bytes plus the aggregate report — the comm twin of
tools_obs_report.py.

    python tools_comm_report.py                      # dp=4, fp32 sync
    python tools_comm_report.py --compress int8-ef   # quantized sync
    python tools_comm_report.py --compare            # per-path fp32 vs
                                                     # compressed table
    python tools_comm_report.py --dp 8 --zero        # ZeRO-1 lowering

`--compare` lowers every compressible wire path — the DP grad sync, the
SP activation gathers/scatters (dstates.convert), the ZeRO-1 param
refresh, the MoE expert dispatch (an ep=8 MoE layer's explicit a2a +
combine gather, nn/moe_dispatch.py) — flag-off vs flag-on, plus the
analytic hetero-DP/PP bridge, and prints fp32 vs compressed bytes with
predicted times at the topology's intra/inter-slice rates.

The model lowers with use_scan=False so every collective is top-level in
the HLO (the analyzer also resolves `while` trip counts for scanned
models, falling back to a `dynamic_trip_count` caveat when a bound is
not static).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":
    # must precede any jax import: the analyzer needs a real dp mesh
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()


class _scoped_env:
    """Set env vars for the scope, restoring the PRIOR values on exit
    (a caller's exported flags must survive a report)."""

    def __init__(self, **vals):
        self._vals = vals
        self._prev = {}

    def __enter__(self):
        for k, v in self._vals.items():
            self._prev[k] = os.environ.get(k)
            os.environ[k] = v

    def __exit__(self, *exc):
        for k, prev in self._prev.items():
            if prev is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = prev


def lowered_step_report(mode: str, *, dp: int = 4, zero: bool = False,
                        batch: int = 8, seq: int = 64,
                        zero_compress: str = "none"):
    """(collective_report, collective_table) for one compiled tiny-LLaMA
    train step under HETU_TPU_GRAD_COMPRESS=`mode` (+ optionally
    HETU_TPU_ZERO_COMPRESS=`zero_compress`)."""
    with _scoped_env(HETU_TPU_GRAD_COMPRESS=mode,
                     HETU_TPU_ZERO_COMPRESS=zero_compress):
        return _lowered_step_report(mode, dp=dp, zero=zero, batch=batch,
                                    seq=seq)


def _lowered_step_report(mode, *, dp, zero, batch, seq):
    import numpy as np

    from hetu_tpu.core.mesh import MeshConfig
    from hetu_tpu.engine import Trainer, TrainingConfig
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.obs.comm import collective_report, collective_table
    from hetu_tpu.parallel import ParallelStrategy

    cfg = LlamaConfig.tiny(remat=False, use_scan=False)
    st = ParallelStrategy(mesh=MeshConfig(dp=dp), zero=zero)
    tc = TrainingConfig(global_batch_size=batch,
                        micro_batch_size=max(batch // dp, 1), seq_len=seq,
                        warmup_steps=2, total_steps=10, log_every=1000)
    tr = Trainer(LlamaLMHeadModel(cfg, st), tc, st).build()
    rng = np.random.default_rng(0)
    hb = {"input_ids": rng.integers(1, 250, (batch, seq)).astype(np.int32),
          "labels": rng.integers(1, 250, (batch, seq)).astype(np.int32)}
    key = tuple(sorted((k, tuple(v.shape)) for k, v in hb.items()))
    compiled = tr._compiled_for_shape(hb, key)
    return collective_report(compiled), collective_table(compiled)


def lowered_sp_report(mode: str, *, tp: int = 4, batch: int = 4,
                      seq: int = 256, hidden: int = 256):
    """collective_report of a lowered SP round trip through
    dstates.convert (seq all-gather into a projection, reduce-scatter
    back out — the Megatron-SP edge pair) under
    HETU_TPU_SP_COMPRESS=`mode`.  Activations lower as f32 (the dtype
    the tier-1 CPU model trains in); a bf16 SP edge halves the fp32
    column, so its int8 ratio is ~1.97x, not ~3.94x."""
    with _scoped_env(HETU_TPU_SP_COMPRESS=mode):
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from hetu_tpu.core.mesh import MeshConfig, create_mesh
        from hetu_tpu.dstates import DistributedStates as DS, convert
        from hetu_tpu.obs.comm import collective_report

        mesh = create_mesh(MeshConfig(tp=tp))
        seq_sharded = DS.make(3, {1: "tp"})
        replicated = DS.dup(3)
        partial = DS.make(3, partial=("tp",))

        def run(x, w):
            full = convert(x, seq_sharded, replicated)   # seq all-gather
            y = full @ w                                  # "row-parallel"
            # declare y partial so the layout algebra emits the fused
            # reduce-scatter back onto the seq dim (lowering-only: this
            # program is analyzed, never executed)
            return convert(y, partial, seq_sharded)

        fn = jax.jit(shard_map(
            run, mesh=mesh,
            in_specs=(P(None, "tp", None), P()),
            out_specs=P(None, "tp", None), check_rep=False))
        x = jnp.zeros((batch, seq, hidden), jnp.float32)
        w = jnp.zeros((hidden, hidden), jnp.float32)
        compiled = fn.lower(x, w).compile()
        return collective_report(compiled)


def lowered_moe_report(mode: str, *, ep: int = 8, experts: int = 8,
                       batch: int = 2, seq: int = 16, hidden: int = 32,
                       topology: str = "flat"):
    """collective_report of a lowered MoE layer forward on an ep-mesh
    under HETU_TPU_MOE_DISPATCH=`mode` (nn/moe_dispatch.py): the
    dispatch all-to-all + combine all-gather are the only collectives
    in the program, so the report IS the dispatch cost.  topology=
    "two_level" opts into the hierarchical schedule (needs the
    profile's slice topology to apply to ep)."""
    env = {"HETU_TPU_MOE_DISPATCH": mode,
           "HETU_TPU_COMM_TOPOLOGY": topology}
    with _scoped_env(**env):
        import jax
        import jax.numpy as jnp
        import numpy as np

        import hetu_tpu as ht
        from hetu_tpu.core.mesh import MeshConfig
        from hetu_tpu.nn.moe import MoEConfig, MoELayer
        from hetu_tpu.obs.comm import collective_report
        from hetu_tpu.parallel import ParallelStrategy

        moe = MoEConfig(num_experts=experts, top_k=2, capacity_factor=2.0)
        st = ParallelStrategy(mesh=MeshConfig(ep=ep))
        mesh = st.build_mesh()
        layer = MoELayer(hidden, 2 * hidden, moe, st)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(batch, seq, hidden)), jnp.float32)
        with ht.use_mesh(mesh):
            p = layer.init(jax.random.key(0), mesh=mesh)
            compiled = jax.jit(lambda p_, x_: layer(p_, x_)[0]) \
                .lower(p, x).compile()
        return collective_report(compiled)


def _print_table(mode: str, report, table, verbose: bool):
    print(f"== HETU_TPU_GRAD_COMPRESS={mode} ==")
    print(f"{'collective':<20}{'count':>6}{'wire bytes':>14}")
    for op, rec in sorted(report["collectives"].items()):
        print(f"{op:<20}{rec['count']:>6}{rec['wire_bytes']:>14,.0f}")
    print(f"{'TOTAL':<20}{report['num_collectives']:>6}"
          f"{report['total_wire_bytes']:>14,.0f}"
          f"   predicted {report['predicted_comm_s'] * 1e6:.1f}us "
          f"({report['chip']})")
    if verbose:
        for r in table:
            trip = (f"  x{r['trip_count']}" if r["trip_count"] > 1 else "")
            print(f"  {r['op']:<18}{r['out_bytes']:>10} B  "
                  f"n={r['group_size']}  wire={r['wire_bytes']:,.0f}{trip}")
    print()


def path_compare(dp: int = 4, batch: int = 8, seq: int = 64,
                 compress: str = "int8-ef"):
    """The per-path fp32-vs-compressed comparison: measured (lowered HLO,
    obs.comm) for the DP grad sync, SP activations and ZeRO refresh;
    analytic (comm/wire.py) for the cross-mesh hetero bridge.  Returns
    {path: {fp32_bytes, compressed_bytes, ratio, fp32_s, compressed_s}}."""
    from hetu_tpu.comm.wire import wire_bytes_per_element
    from hetu_tpu.models.llama import LlamaConfig
    from hetu_tpu.obs.mfu import load_hardware_profile

    hw = load_hardware_profile()
    topo = hw.get("topology") or {}
    intra = float(topo.get("intra_gbps",
                           hw.get("ici_allreduce_gbps", 45.0))) * 1e9
    inter = float(topo.get("inter_gbps", hw.get("dcn_gbps", 6.25))) * 1e9
    paths = {}

    # DP grad sync: the non-zero trainer's collectives ARE the sync
    rep32, _ = lowered_step_report("none", dp=dp, batch=batch, seq=seq)
    rep8, _ = lowered_step_report(compress, dp=dp, batch=batch, seq=seq)
    paths["dp_grad_sync"] = _path_row(
        rep32["total_wire_bytes"], rep8["total_wire_bytes"],
        rep32["predicted_comm_s"], rep8["predicted_comm_s"])

    # SP activations: the convert() gather/scatter pair, per layer
    sp_mode = "int8" if compress.startswith("int8") else "int4"
    sp32 = lowered_sp_report("none")
    spq = lowered_sp_report(sp_mode)
    paths["sp_activations"] = _path_row(
        sp32["total_wire_bytes"], spq["total_wire_bytes"],
        sp32["predicted_comm_s"], spq["predicted_comm_s"])

    # ZeRO-1 param refresh: the all-gather bytes of the zero trainer
    z32, _ = lowered_step_report("none", dp=dp, zero=True, batch=batch,
                                 seq=seq)
    zq, _ = lowered_step_report("none", dp=dp, zero=True, batch=batch,
                                seq=seq, zero_compress=sp_mode)
    ag32 = z32["collectives"].get("all-gather", {}).get("wire_bytes", 0.0)
    agq = zq["collectives"].get("all-gather", {}).get("wire_bytes", 0.0)
    paths["zero_refresh"] = _path_row(ag32, agq, ag32 / intra, agq / intra)

    # MoE expert dispatch: the explicit a2a + combine gather of an
    # ep=8 MoE layer, fp32 vs quantized (nn/moe_dispatch.py; the only
    # collectives the lowered program contains)
    m32 = lowered_moe_report("fp32")
    mq = lowered_moe_report(sp_mode)
    paths["moe_dispatch"] = _path_row(
        m32["total_wire_bytes"], mq["total_wire_bytes"],
        m32["predicted_comm_s"], mq["predicted_comm_s"])

    # hetero-DP/PP bridge: one non-resident group shipping the tiny
    # model's sum-grads across meshes (device_put rides the slow
    # inter-slice/DCN links — comm/wire.py analytic)
    n = float(LlamaConfig.tiny().num_params())
    b32 = 4.0 * n
    bq = wire_bytes_per_element(
        "int8" if compress.startswith("int8") else "int4") * n
    paths["hetero_bridge"] = _path_row(b32, bq, b32 / inter, bq / inter)
    return paths


def _path_row(b32, bq, s32, sq):
    return {"fp32_bytes": b32, "compressed_bytes": bq,
            "ratio": (b32 / bq) if bq else None,
            "fp32_s": s32, "compressed_s": sq}


def _print_paths(paths):
    print("== per-path fp32 vs compressed (measured from lowered HLO; "
          "bridge analytic) ==")
    print(f"{'path':<16}{'fp32 bytes':>14}{'q bytes':>12}{'ratio':>8}"
          f"{'fp32 time':>12}{'q time':>12}")
    for name, r in paths.items():
        print(f"{name:<16}{r['fp32_bytes']:>14,.0f}"
              f"{r['compressed_bytes']:>12,.0f}"
              f"{r['ratio']:>7.2f}x"
              f"{r['fp32_s'] * 1e6:>10.1f}us"
              f"{r['compressed_s'] * 1e6:>10.1f}us")
    print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Bytes-on-wire table of a compiled train step "
                    "(hardware-free; obs.comm analyzer).")
    ap.add_argument("--compress", default="none",
                    choices=("none", "int8", "int8-ef", "int4", "int4-ef"))
    ap.add_argument("--compare", action="store_true",
                    help="lower fp32 AND compressed variants of every "
                         "wire path, print the per-path table + ratios")
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1 (reduce-scatter/all-gather lowering)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print each collective instruction")
    args = ap.parse_args(argv)

    if args.compare:
        cmode = args.compress if args.compress != "none" else "int8-ef"
        paths = path_compare(dp=args.dp, batch=args.batch, seq=args.seq,
                             compress=cmode)
        _print_paths(paths)
        summary = {"paths": paths, "compress": cmode,
                   "ratio": paths["dp_grad_sync"]["ratio"]}
        print(f"bytes-on-wire ratio fp32/{cmode} (dp sync): "
              f"{summary['ratio']:.2f}x")
        print(json.dumps(summary))
        return 0

    rep, table = lowered_step_report(
        args.compress, dp=args.dp, zero=args.zero, batch=args.batch,
        seq=args.seq)
    _print_table(args.compress, rep, table, args.verbose)
    summary = {args.compress: {
        "total_wire_bytes": rep["total_wire_bytes"],
        "num_collectives": rep["num_collectives"],
        "predicted_comm_s": rep["predicted_comm_s"]}}
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
