"""Per-kernel fused-vs-XLA HBM-traffic table for the Pallas layer.

Prints, for every kernel in `hetu_tpu/ops/pallas` (docs/kernels.md), the
analytic HBM bytes each path moves for the bench config's shapes and
the roofline time at the profiled chip's HBM rate — the SAME byte model
bench.py records in `detail.kernels`, so the CLI and the BENCH record
can never disagree (the tools_comm_report.py pattern: hardware-free,
no device contact, safe while the TPU tunnel is down).

    python tools_bench_kernels.py                  # bench-config table
    python tools_bench_kernels.py --batch 4 --seq 1024
    python tools_bench_kernels.py --json           # machine-readable
    python tools_bench_kernels.py --chain norm     # audit one kernel's
                                                   # unfused op chain

tools_obs_report.py embeds the same numbers as its `kernels` section
(--kernels).
"""
from __future__ import annotations

import argparse
import json
import sys


def kernel_section(batch: int = 8, seq: int = 2048) -> dict:
    """The analytic per-kernel record for the bench config — one shared
    producer for this CLI, bench.py detail.kernels, and
    tools_obs_report's `kernels` section."""
    import bench
    return bench._hardware_free_kernels(batch, seq)


def _fmt_bytes(b: float) -> str:
    for unit, scale in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if b >= scale:
            return f"{b / scale:8.2f} {unit}"
    return f"{b:8.0f} B "


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Analytic fused-vs-XLA HBM bytes + roofline time "
                    "per Pallas kernel (the bench.py detail.kernels "
                    "byte model).")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--json", action="store_true",
                    help="emit the record as JSON instead of the table")
    ap.add_argument("--chain", metavar="KERNEL", default=None,
                    help="print one kernel's unfused op chain (norm, "
                         "swiglu, rotary, quant, flash, paged_attn, "
                         "paged_attn_int8, paged_attn_int4, "
                         "paged_verify, sample, adam)")
    args = ap.parse_args(argv)

    if args.chain:
        from hetu_tpu.ops.pallas import traffic as t
        import bench
        cfg = bench._bench_config()
        tokens = args.batch * args.seq
        builders = {
            "norm": lambda: t.norm_traffic(tokens, cfg.hidden_size),
            "swiglu": lambda: t.swiglu_traffic(tokens,
                                               cfg.intermediate_size),
            "rotary": lambda: t.rotary_traffic(
                args.batch, args.seq, cfg.num_attention_heads,
                cfg.num_key_value_heads, cfg.head_dim),
            "quant": lambda: t.quant_traffic(
                cfg.num_hidden_layers * cfg.hidden_size
                * cfg.intermediate_size, 1024),
            "flash": lambda: t.flash_traffic(
                args.batch, args.seq, cfg.num_attention_heads,
                cfg.head_dim),
            "paged_attn": lambda: t.paged_attn_traffic(
                8, 16, 16, cfg.num_key_value_heads, cfg.head_dim),
            "paged_attn_int8": lambda: t.paged_attn_traffic(
                8, 16, 16, cfg.num_key_value_heads, cfg.head_dim,
                quant="int8"),
            "paged_attn_int4": lambda: t.paged_attn_traffic(
                8, 16, 16, cfg.num_key_value_heads, cfg.head_dim,
                quant="int4"),
            "paged_verify": lambda: t.paged_verify_traffic(
                8, 4, 16, 16, cfg.num_key_value_heads, cfg.head_dim,
                quant="int8"),
            "sample": lambda: t.sample_traffic(
                8 * 5, cfg.hidden_size, cfg.vocab_size),
            "adam": lambda: t.adam_traffic(cfg.num_params()),
        }
        if args.chain not in builders:
            print(f"unknown kernel {args.chain!r}; "
                  f"known: {sorted(builders)}", file=sys.stderr)
            return 2
        rec = builders[args.chain]()
        print(f"# {rec['kernel']} unfused op chain "
              f"(read + write bytes per op)")
        for op in rec["chain"]:
            print(f"  {op['op']:<18} R {_fmt_bytes(op['read'])}   "
                  f"W {_fmt_bytes(op['write'])}")
        print(f"  {'TOTAL unfused':<18} {_fmt_bytes(rec['unfused_bytes'])}"
              f"   fused {_fmt_bytes(rec['fused_bytes'])}   "
              f"{rec['reduction']:.2f}x")
        return 0

    rec = kernel_section(args.batch, args.seq)
    if args.json:
        print(json.dumps({"batch": args.batch, "seq": args.seq,
                          "kernels": rec}, indent=2))
        return 0
    print(f"# Pallas fused-kernel layer: analytic HBM traffic per step "
          f"(batch={args.batch}, seq={args.seq}; docs/kernels.md)")
    hdr = (f"{'kernel':<12} {'unfused':>12} {'fused':>12} {'cut':>7} "
           f"{'unfused_ms':>11} {'fused_ms':>9} {'xlayers':>8}")
    print(hdr)
    print("-" * len(hdr))
    for name, r in rec.items():
        print(f"{name:<12} {_fmt_bytes(r['unfused_bytes']):>12} "
              f"{_fmt_bytes(r['fused_bytes']):>12} "
              f"{r['reduction']:>6.2f}x "
              f"{r['unfused_s'] * 1e3:>11.3f} {r['fused_s'] * 1e3:>9.3f} "
              f"{r['per_step_multiplier']:>8}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
