"""Quantized collectives for shard_map manual regions (EQuARX-style).

`comm/grad_sync.py` compresses ONE hand-built path (the DP grad sync).
This module makes quantization a property of the COLLECTIVE instead:
drop-in `all_gather_q` / `reduce_scatter_q` / `all_to_all_q` /
`all_reduce_q` that move blockwise-int8 (or packed-int4) payloads plus
f32 block scales over the wire and dequantize on arrival, usable
anywhere a `lax` collective runs inside a `shard_map` manual region —
the SP activation gathers/scatters in `dstates.convert`, the hetero-TP
pipeline's sequence-parallel edges (`parallel/hetero_pp.py`), and any
future explicit path.

Differentiability: each collective is a `jax.custom_vjp` whose backward
is the TRANSPOSE collective, also quantized — an all-gather's cotangent
rides a quantized reduce-scatter and vice versa (straight-through
through the quantizer, the standard treatment: round() has zero gradient
almost everywhere, so differentiating through the quantize would kill
training).  Forward and backward therefore both get the byte reduction.

Fallbacks keep semantics exact where quantization is wrong or not worth
it: mode "none", non-float dtypes (token ids, segment ids, MoE indices)
and buffers smaller than one quantization block take the plain `lax`
path — bit-identical to not using this module at all.

Flag: `HETU_TPU_SP_COMPRESS = none | int8 | int4` routes the
`dstates.convert` + hetero-PP SP call sites; "none" (default) is
HLO-byte-identical to an unset environment.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from hetu_tpu.comm.compress import (dequantize_blockwise, pack_int4,
                                    quantize_blockwise, unpack_int4)
from hetu_tpu.comm.wire import DEFAULT_BLOCK, mode_bits

#: HETU_TPU_SP_COMPRESS values — activation compression is stateless, so
#: there are no "-ef" variants here (EF memory belongs to per-step
#: gradient state, not to per-call activation transport)
ACT_MODES = ("none", "int8", "int4")


def sp_mode() -> str:
    """The HETU_TPU_SP_COMPRESS flag value."""
    from hetu_tpu.utils import flags
    return flags.str_flag("HETU_TPU_SP_COMPRESS")


def eligible(x, mode: str, block_size: int = DEFAULT_BLOCK) -> bool:
    """Quantize only when it helps: compressing mode, a float payload,
    and at least one quantization block of elements (smaller buffers
    would PAY bytes: the padded block + scale exceeds the raw payload)."""
    return (mode not in (None, "none")
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.size >= block_size)


# ---------------------------------------------------------------------------
# flat quantize/dequantize helpers (padding + int4 packing)
# ---------------------------------------------------------------------------

def axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis.  `lax.axis_size` is guaranteed
    to exist here: hetu_tpu/__init__ installs the version-portability
    shim (core/jax_compat.py) before any submodule loads."""
    return int(lax.axis_size(axis_name))


def _group_size(axis_name: str, groups) -> int:
    if groups:
        return len(groups[0])
    return axis_size(axis_name)


def _q_flat(flat, block: int, bits: int):
    """f32 [n] -> (wire payload [nb, bs or bs//2], scales [nb]); pads to
    a block multiple (the pad quantizes to zero and is sliced off on
    arrival)."""
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, s = quantize_blockwise(flat, block, bits=bits)
    if bits == 4:
        q = pack_int4(q)
    return q, s


def _dq_flat(q, s, n: int, bits: int):
    if bits == 4:
        q = unpack_int4(q)
    flat = dequantize_blockwise(q, s)
    if flat.shape[0] != n:
        flat = lax.slice(flat, (0,), (n,))
    return flat


def _q_rows(rows, block: int, bits: int):
    """[r, m] f32 rows -> ([r, nb, bs or bs//2], [r, nb]) with column
    padding to a block multiple."""
    m = rows.shape[1]
    pad = (-m) % block
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((rows.shape[0], pad), jnp.float32)], axis=1)
    q, s = jax.vmap(lambda r: quantize_blockwise(r, block, bits=bits))(rows)
    if bits == 4:
        q = pack_int4(q)
    return q, s


def _dq_rows(q, s, m: int, bits: int):
    """Inverse of `_q_rows`: -> [r, m] f32."""
    return jax.vmap(lambda qq, ss: _dq_flat(qq, ss, m, bits))(q, s)


# ---------------------------------------------------------------------------
# all-gather
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _all_gather_q(x, axis_name, axis, tiled, mode, block, groups):
    bits = mode_bits(mode)
    npart = _group_size(axis_name, groups)
    q, s = _q_flat(x.reshape(-1).astype(jnp.float32), block, bits)
    qg = lax.all_gather(q, axis_name, axis=0, axis_index_groups=groups)
    sg = lax.all_gather(s, axis_name, axis=0, axis_index_groups=groups)
    parts = jax.vmap(lambda qq, ss: _dq_flat(qq, ss, x.size, bits))(qg, sg)
    out = jnp.moveaxis(parts.reshape((npart,) + x.shape), 0, axis)
    if tiled:
        shape = list(x.shape)
        shape[axis] *= npart
        out = out.reshape(shape)
    return out.astype(x.dtype)


def _all_gather_q_fwd(x, axis_name, axis, tiled, mode, block, groups):
    return _all_gather_q(x, axis_name, axis, tiled, mode, block, groups), None


def _all_gather_q_bwd(axis_name, axis, tiled, mode, block, groups, _, ct):
    # transpose of a (tiled) all-gather: reduce-scatter of the cotangent
    dx = _reduce_scatter_q(ct, axis_name, axis, True, mode, block, groups)
    if not tiled:
        dx = jnp.squeeze(dx, axis)
    return (dx,)


_all_gather_q.defvjp(_all_gather_q_fwd, _all_gather_q_bwd)


def all_gather_q(x, axis_name: str, *, axis: int = 0, tiled: bool = False,
                 mode: str = "int8", block_size: int = DEFAULT_BLOCK,
                 axis_index_groups=None):
    """Quantized `lax.all_gather` (same axis/tiled semantics).  Exact
    fallback when `eligible` says quantizing would not pay."""
    groups = _norm_groups(axis_index_groups)
    if not eligible(x, mode, block_size):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled,
                              axis_index_groups=axis_index_groups)
    return _all_gather_q(x, axis_name, axis, tiled, mode, block_size, groups)


# ---------------------------------------------------------------------------
# reduce-scatter (psum_scatter)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _reduce_scatter_q(x, axis_name, dim, tiled, mode, block, groups):
    if not tiled:
        raise NotImplementedError(
            "reduce_scatter_q supports tiled=True only (the form every "
            "call site in this repo uses)")
    bits = mode_bits(mode)
    npart = _group_size(axis_name, groups)
    if x.shape[dim] % npart:
        raise ValueError(
            f"cannot scatter dim {dim} of size {x.shape[dim]} over "
            f"{npart} participants (not divisible)")
    chunk = x.shape[dim] // npart
    xm = jnp.moveaxis(x, dim, 0).astype(jnp.float32)
    rest = xm.shape[1:]
    rows = xm.reshape(npart, -1)
    row_elems = rows.shape[1]
    q, s = _q_rows(rows, block, bits)
    q2 = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                        axis_index_groups=groups)
    s2 = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                        axis_index_groups=groups)
    shard = jnp.sum(_dq_rows(q2, s2, row_elems, bits), axis=0)
    out = shard.reshape((chunk,) + rest)
    return jnp.moveaxis(out, 0, dim).astype(x.dtype)


def _reduce_scatter_q_fwd(x, axis_name, dim, tiled, mode, block, groups):
    return (_reduce_scatter_q(x, axis_name, dim, tiled, mode, block, groups),
            None)


def _reduce_scatter_q_bwd(axis_name, dim, tiled, mode, block, groups, _, ct):
    # transpose of a tiled reduce-scatter: all-gather of the cotangent
    return (_all_gather_q(ct, axis_name, dim, True, mode, block, groups),)


_reduce_scatter_q.defvjp(_reduce_scatter_q_fwd, _reduce_scatter_q_bwd)


def reduce_scatter_q(x, axis_name: str, *, scatter_dimension: int = 0,
                     tiled: bool = True, mode: str = "int8",
                     block_size: int = DEFAULT_BLOCK,
                     axis_index_groups=None):
    """Quantized `lax.psum_scatter` (tiled): quantize my buffer, ride the
    chunks on an int all-to-all, dequantize + sum the received chunks."""
    groups = _norm_groups(axis_index_groups)
    if not eligible(x, mode, block_size):
        return lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled,
                                axis_index_groups=axis_index_groups)
    return _reduce_scatter_q(x, axis_name, scatter_dimension, tiled, mode,
                             block_size, groups)


# ---------------------------------------------------------------------------
# all-to-all
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _all_to_all_q(x, axis_name, split_axis, concat_axis, mode, block, groups):
    bits = mode_bits(mode)
    npart = _group_size(axis_name, groups)
    if x.shape[split_axis] % npart:
        raise ValueError(
            f"cannot split dim {split_axis} of size {x.shape[split_axis]} "
            f"over {npart} participants (not divisible)")
    xm = jnp.moveaxis(x, split_axis, 0).astype(jnp.float32)
    chunk = xm.shape[0] // npart
    rest = xm.shape[1:]
    rows = xm.reshape(npart, -1)
    row_elems = rows.shape[1]
    q, s = _q_rows(rows, block, bits)
    q2 = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                        axis_index_groups=groups)
    s2 = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                        axis_index_groups=groups)
    parts = _dq_rows(q2, s2, row_elems, bits).reshape(
        (npart, chunk) + rest)
    pieces = [jnp.moveaxis(parts[i], 0, split_axis) for i in range(npart)]
    return jnp.concatenate(pieces, axis=concat_axis).astype(x.dtype)


def _all_to_all_q_fwd(x, axis_name, split_axis, concat_axis, mode, block,
                      groups):
    return (_all_to_all_q(x, axis_name, split_axis, concat_axis, mode,
                          block, groups), None)


def _all_to_all_q_bwd(axis_name, split_axis, concat_axis, mode, block,
                      groups, _, ct):
    # transpose of a tiled all-to-all: the reverse all-to-all
    return (_all_to_all_q(ct, axis_name, concat_axis, split_axis, mode,
                          block, groups),)


_all_to_all_q.defvjp(_all_to_all_q_fwd, _all_to_all_q_bwd)


def all_to_all_q(x, axis_name: str, *, split_axis: int, concat_axis: int,
                 mode: str = "int8", block_size: int = DEFAULT_BLOCK,
                 axis_index_groups=None):
    """Quantized tiled `lax.all_to_all` (same split/concat semantics)."""
    groups = _norm_groups(axis_index_groups)
    if not eligible(x, mode, block_size):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True,
                              axis_index_groups=axis_index_groups)
    return _all_to_all_q(x, axis_name, split_axis, concat_axis, mode,
                         block_size, groups)


# ---------------------------------------------------------------------------
# all-reduce (psum) = quantized reduce-scatter + quantized all-gather
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _all_reduce_q(x, axis_name, mode, block, groups):
    bits = mode_bits(mode)
    npart = _group_size(axis_name, groups)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % (npart * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    shard = _reduce_scatter_q(flat, axis_name, 0, True, mode, block, groups)
    full = _all_gather_q(shard, axis_name, 0, True, mode, block, groups)
    if pad:
        full = lax.slice(full, (0,), (n,))
    return full.reshape(x.shape).astype(x.dtype)


def _all_reduce_q_fwd(x, axis_name, mode, block, groups):
    return _all_reduce_q(x, axis_name, mode, block, groups), None


def _all_reduce_q_bwd(axis_name, mode, block, groups, _, ct):
    # psum is self-adjoint
    return (_all_reduce_q(ct, axis_name, mode, block, groups),)


_all_reduce_q.defvjp(_all_reduce_q_fwd, _all_reduce_q_bwd)


def all_reduce_q(x, axis_name: str, *, mode: str = "int8",
                 block_size: int = DEFAULT_BLOCK, axis_index_groups=None):
    """Quantized `lax.psum`: the EQuARX decomposition (quantized
    reduce-scatter, then quantized all-gather of the reduced shard)."""
    groups = _norm_groups(axis_index_groups)
    if not eligible(x, mode, block_size):
        return lax.psum(x, axis_name, axis_index_groups=axis_index_groups)
    return _all_reduce_q(x, axis_name, mode, block_size, groups)


def _norm_groups(groups) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """axis_index_groups as a hashable tuple-of-tuples (custom_vjp
    nondiff args must hash)."""
    if groups is None:
        return None
    return tuple(tuple(int(i) for i in g) for g in groups)
