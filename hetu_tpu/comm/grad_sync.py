"""Compressed gradient synchronization paths.

Two consumers, one payload format (comm/compress.py, priced by
comm/wire.py):

* Homogeneous DP/ZeRO (`quantized_grad_sync`) — runs INSIDE a shard_map
  over the `dp` mesh axis, replacing the f32 all-reduce GSPMD would emit
  with the EQuARX-shaped pattern (PAPERS.md):

      quantize local sum-grads
        -> all-to-all int8 chunks + f32 block scales   (ring reduce-scatter)
        -> dequantize + sum the dp chunks of my shard
        -> re-quantize the reduced shard
        -> all-gather int8 + scales -> dequantize      (param-refresh gather)

  ~3.94x fewer bytes on wire than the f32 all-reduce at block 256
  (wire.py).  Each quantize point carries an optional error-feedback
  residual: "a2a" residuals are PER-REPLICA (each replica compresses its
  own grads — globally a [dp, L] array split over dp), "ag" residuals are
  per-shard (globally [L] split over dp).  The residuals ride in the
  optimizer state pytree (engine/trainer.py) so they checkpoint, donate
  and reshard with the rest of the training state.

* The hetero-DP cross-mesh bridge (`bridge_compress` /
  `bridge_accumulate`) — quantize-before-`jax.device_put`
  (parallel/hetero_dp.py): each non-resident group ships int8+scales
  instead of f32 sum-grads, with a per-GROUP error-feedback residual
  living on the source group's mesh.
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu.comm.bucketer import BucketPlan
from hetu_tpu.comm.compress import (dequantize_blockwise, ef_quantize,
                                    quantize_blockwise)
from hetu_tpu.comm.wire import COMPRESSED_MODES, DEFAULT_BLOCK

#: HETU_TPU_GRAD_COMPRESS values (utils/flags.py); "none" = the f32 path
MODES = ("none",) + COMPRESSED_MODES


def uses_error_feedback(mode: str) -> bool:
    return mode == "int8-ef"


# ---------------------------------------------------------------------------
# homogeneous DP/ZeRO: shard_map-internal quantized reduce-scatter+all-gather
# ---------------------------------------------------------------------------

def _sync_bucket(flat, axis_name: str, dp: int, block_size: int,
                 ef_a2a, ef_ag):
    """One flat bucket [L] of local sum-grads -> fully reduced [L]
    (replicated).  L % (dp * block_size) == 0 (BucketPlan guarantees).
    ef_a2a: local [1, L] or None; ef_ag: local [L // dp] or None."""
    L = flat.shape[0]
    chunk = L // dp
    nblk = chunk // block_size

    # stage 1: quantize my whole buffer, all-to-all whole-block chunks so
    # peer i receives every replica's piece of shard i
    q, s, new_a2a = ef_quantize(
        flat, None if ef_a2a is None else ef_a2a[0], block_size)
    if ef_a2a is not None:
        new_a2a = new_a2a[None]                      # keep the [1, L] lane
    q = q.reshape(dp, nblk, block_size)
    s = s.reshape(dp, nblk)
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    shard = jnp.sum(jax.vmap(dequantize_blockwise)(q, s), axis=0)  # [chunk]

    # stage 2: re-quantize the reduced shard, gather everyone's shard
    q2, s2, new_ag = ef_quantize(shard, ef_ag, block_size)
    qg = lax.all_gather(q2, axis_name, axis=0)       # [dp, nblk, bs]
    sg = lax.all_gather(s2, axis_name, axis=0)       # [dp, nblk]
    full = jax.vmap(dequantize_blockwise)(qg, sg).reshape(L)
    return full, new_a2a, new_ag


def quantized_grad_sync(grads, axis_name: str, dp: int, plan: BucketPlan,
                        mode: str, ef_state: Dict[str, List[jnp.ndarray]],
                        block_size: int = DEFAULT_BLOCK):
    """shard_map-internal: local sum-grad pytree -> globally summed pytree
    (replicated over `axis_name`), via bucketed int8 collectives.

    ef_state: {} for mode "int8"; for "int8-ef" a dict
    {"a2a": [local [1, L] per bucket], "ag": [local [L//dp] per bucket]}
    (the local view of `ef_init`'s global arrays).  Returns
    (synced grads, new ef_state of the same structure)."""
    if mode not in COMPRESSED_MODES:
        raise ValueError(f"mode {mode!r} does not compress; caller should "
                         f"have taken the plain path")
    ef = uses_error_feedback(mode)
    flats = plan.pack(grads)
    out, new_a2a, new_ag = [], [], []
    for i, flat in enumerate(flats):
        ea = ef_state["a2a"][i] if ef else None
        eg = ef_state["ag"][i] if ef else None
        full, na, ng = _sync_bucket(flat, axis_name, dp, block_size, ea, eg)
        out.append(full)
        if ef:
            new_a2a.append(na)
            new_ag.append(ng)
    new_state = {"a2a": new_a2a, "ag": new_ag} if ef else {}
    return plan.unpack(out), new_state


def ef_init(plan: BucketPlan, dp: int) -> Dict[str, List[jnp.ndarray]]:
    """GLOBAL error-feedback state for `quantized_grad_sync`: per bucket a
    [dp, L] per-replica residual (split over dp outside the shard_map) and
    an [L] per-shard residual (split over dp)."""
    return {
        "a2a": [jnp.zeros((dp, L), jnp.float32) for L in plan.sizes],
        "ag": [jnp.zeros((L,), jnp.float32) for L in plan.sizes],
    }


def ef_specs(plan: BucketPlan, axis: str = "dp"
             ) -> Dict[str, List[P]]:
    """PartitionSpecs matching `ef_init`'s layout (shard_map in/out specs
    and NamedSharding construction)."""
    return {
        "a2a": [P(axis, None) for _ in plan.sizes],
        "ag": [P(axis) for _ in plan.sizes],
    }


def ef_shardings(plan: BucketPlan, mesh, axis: str = "dp"):
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        ef_specs(plan, axis),
                        is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# hetero-DP bridge: quantize-before-device_put (parallel/hetero_dp.py)
# ---------------------------------------------------------------------------

def _pad_to_block(flat, block_size: int):
    n = flat.shape[0]
    padded = -(-n // block_size) * block_size
    if padded != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - n,), jnp.float32)])
    return flat


def bridge_residual_init(params_like, block_size: int = DEFAULT_BLOCK):
    """Zero EF residuals for one bridge source group: per leaf a padded
    flat f32 buffer (lives on the SOURCE group's mesh)."""
    def zeros(p):
        n = -(-p.size // block_size) * block_size
        return jnp.zeros((n,), jnp.float32)
    return jax.tree.map(zeros, params_like)


def bridge_compress(grads, residuals=None,
                    block_size: int = DEFAULT_BLOCK):
    """Per-leaf quantize of a sum-grad pytree for the cross-mesh bridge.
    Returns ({q}, {scales}, {new residuals}) pytrees — q/scales are the
    small arrays to `device_put` across meshes.  With residuals=None
    (mode "int8") the third output is None and no residual is computed —
    a jit output can't be DCE'd, so materializing a discarded
    params-sized f32 tree would cost every bridge step."""
    is_t = lambda t: isinstance(t, tuple)
    if residuals is None:
        def one_plain(g):
            flat = _pad_to_block(g.reshape(-1).astype(jnp.float32),
                                 block_size)
            return quantize_blockwise(flat, block_size)
        pairs = jax.tree.map(one_plain, grads)
        qs = jax.tree.map(lambda t: t[0], pairs, is_leaf=is_t)
        ss = jax.tree.map(lambda t: t[1], pairs, is_leaf=is_t)
        return qs, ss, None

    def one(g, r):
        flat = _pad_to_block(g.reshape(-1).astype(jnp.float32), block_size)
        return ef_quantize(flat, r, block_size)
    triples = jax.tree.map(one, grads, residuals)
    qs = jax.tree.map(lambda t: t[0], triples, is_leaf=is_t)
    ss = jax.tree.map(lambda t: t[1], triples, is_leaf=is_t)
    rs = jax.tree.map(lambda t: t[2], triples, is_leaf=is_t)
    return qs, ss, rs


def bridge_accumulate(acc, qs, ss):
    """acc + dequantize(qs, ss) leaf-wise (runs jitted on the resident
    group's mesh; the dequant drops each leaf's block padding)."""
    def one(a, q, s):
        flat = dequantize_blockwise(q, s)
        return a + lax.slice(flat, (0,), (a.size,)).reshape(a.shape)
    return jax.tree.map(one, acc, qs, ss)
