"""Compressed gradient synchronization paths.

Two consumers, one payload format (comm/compress.py, priced by
comm/wire.py):

* Homogeneous DP/ZeRO (`quantized_grad_sync`) — runs INSIDE a shard_map
  over the `dp` mesh axis, replacing the f32 all-reduce GSPMD would emit
  with the EQuARX-shaped pattern (PAPERS.md):

      quantize local sum-grads
        -> all-to-all int chunks + f32 block scales    (ring reduce-scatter)
        -> dequantize + sum the dp chunks of my shard
        -> re-quantize the reduced shard
        -> all-gather int + scales -> dequantize       (param-refresh gather)

  ~3.94x (int8) / ~7.76x (int4, packed two values per byte) fewer bytes
  on wire than the f32 all-reduce at block 256 (wire.py).  Each quantize
  point carries an optional error-feedback residual: "a2a" residuals are
  PER-REPLICA (each replica compresses its own grads — globally a
  [dp, L] array split over dp), "ag" residuals are per-shard (globally
  [L] split over dp).  The residuals ride in the optimizer state pytree
  (engine/trainer.py) so they checkpoint, donate and reshard with the
  rest of the training state.

  With a `Topology` (comm/topology.py, HETU_TPU_COMM_TOPOLOGY=two_level)
  the ring schedule goes HIERARCHICAL (HetCCL): reduce-scatter inside
  each slice over the fast intra links, exchange only the 1/slice shard
  across slices, all-gather back inside the slice — the slow inter-slice
  links move slice_devices-fold fewer bytes (wire.two_level_sync_bytes).
  The hierarchical schedule has FOUR quantize points, each with its own
  error-feedback residual in the "-ef" modes: the full-buffer intra
  scatter reuses the flat path's per-replica "a2a" [dp, L] residual and
  the inter gather's sub-shard re-quantize reuses the per-shard "ag"
  [L] one (same shapes); the two chunk-sized points get their own
  "tl_inter"/"tl_intra" [dp, L/slice_devices] residuals (split over dp,
  like "a2a").  The tl_* entries exist only while a topology routes —
  switching HETU_TPU_COMM_TOPOLOGY mid-run changes the optimizer-state
  structure, like any other program-shape knob.

* The hetero-DP cross-mesh bridge (`bridge_compress` /
  `bridge_accumulate`) — quantize-before-`jax.device_put`
  (parallel/hetero_dp.py): each non-resident group ships int8/packed-int4
  + scales instead of f32 sum-grads, with a per-GROUP error-feedback
  residual living on the source group's mesh.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu.comm.bucketer import BucketPlan
from hetu_tpu.comm.compress import (dequantize_blockwise, ef_quantize,
                                    pack_int4, quantize_blockwise,
                                    unpack_int4)
from hetu_tpu.comm.topology import Topology
from hetu_tpu.comm.wire import COMPRESSED_MODES, DEFAULT_BLOCK, mode_bits

#: HETU_TPU_GRAD_COMPRESS values (utils/flags.py); "none" = the f32 path
MODES = ("none",) + COMPRESSED_MODES


def uses_error_feedback(mode: str) -> bool:
    return mode.endswith("-ef")


def per_replica_keys(keys, axis_name: str):
    """Fold this replica's axis index into a [n] array of PRNG keys.

    Inside the manual grad-sync region every replica traces the same
    micro-batch scan with the same `keys` — without this fold, dropout
    masks are IDENTICAL across replicas (same mask on different rows:
    correlated noise the GSPMD path does not have).  Folding the axis
    index in gives each replica an independent stream, matching the
    per-row independence of the GSPMD lowering."""
    idx = lax.axis_index(axis_name)
    return jax.vmap(lambda k: jax.random.fold_in(k, idx))(keys)


# ---------------------------------------------------------------------------
# homogeneous DP/ZeRO: shard_map-internal quantized reduce-scatter+all-gather
# ---------------------------------------------------------------------------

def _maybe_pack(q, bits: int):
    return pack_int4(q) if bits == 4 else q


def _maybe_unpack(q, bits: int):
    return unpack_int4(q) if bits == 4 else q


def _sync_bucket(flat, axis_name: str, dp: int, block_size: int,
                 ef_a2a, ef_ag, bits: int = 8):
    """One flat bucket [L] of local sum-grads -> fully reduced [L]
    (replicated).  L % (dp * block_size) == 0 (BucketPlan guarantees).
    ef_a2a: local [1, L] or None; ef_ag: local [L // dp] or None."""
    L = flat.shape[0]
    chunk = L // dp
    nblk = chunk // block_size

    # stage 1: quantize my whole buffer, all-to-all whole-block chunks so
    # peer i receives every replica's piece of shard i
    q, s, new_a2a = ef_quantize(
        flat, None if ef_a2a is None else ef_a2a[0], block_size, bits=bits)
    # numerics SNR tap (obs/numerics.py): ef_quantize's residual IS the
    # exact quantization error of this stage — measured against the
    # buffer actually quantized (grads + carried EF residual in the -ef
    # modes, so the SNR reads wire fidelity; residual GROWTH has its own
    # `ef` scope + detector and must not alias into this one).  Costs
    # two power reductions, only traced when a collector is active.
    from hetu_tpu.obs import numerics as _numerics
    if _numerics.active():
        sig = flat if ef_a2a is None else flat + ef_a2a[0]
        _numerics.tap_quant_error("grad_sync/a2a", sig, new_a2a)
    if ef_a2a is not None:
        new_a2a = new_a2a[None]                      # keep the [1, L] lane
    q = _maybe_pack(q.reshape(dp, nblk, block_size), bits)
    s = s.reshape(dp, nblk)
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    q = _maybe_unpack(q, bits)
    shard = jnp.sum(jax.vmap(dequantize_blockwise)(q, s), axis=0)  # [chunk]

    # stage 2: re-quantize the reduced shard, gather everyone's shard
    q2, s2, new_ag = ef_quantize(shard, ef_ag, block_size, bits=bits)
    if _numerics.active():
        sig2 = shard if ef_ag is None else shard + ef_ag
        _numerics.tap_quant_error("grad_sync/ag", sig2, new_ag)
    qg = lax.all_gather(_maybe_pack(q2, bits), axis_name, axis=0)
    sg = lax.all_gather(s2, axis_name, axis=0)       # [dp, nblk]
    qg = _maybe_unpack(qg, bits)
    full = jax.vmap(dequantize_blockwise)(qg, sg).reshape(L)
    return full, new_a2a, new_ag


def _sync_bucket_two_level(flat, axis_name: str, dp: int, block_size: int,
                           bits: int, topo: Topology,
                           ef_a2a=None, ef_inter=None, ef_ag=None,
                           ef_intra=None):
    """Hierarchical twin of `_sync_bucket`: intra-slice quantized
    reduce-scatter -> inter-slice quantized all-reduce of the 1/k shard
    (a2a + re-quantized gather) -> intra-slice quantized all-gather.
    Inter-slice links carry only L/k elements instead of L.

    Each of the four quantize points carries an optional error-feedback
    residual (all four or none): ef_a2a local [1, L] (stage 1, the flat
    path's per-replica shape), ef_inter local [1, L/k] (stage 2),
    ef_ag local [L/dp] (stage 3, the flat path's per-shard shape),
    ef_intra local [1, L/k] (stage 4).  Returns
    (full [L], new_a2a, new_inter, new_ag, new_intra)."""
    intra, inter = topo.groups(dp)
    k = topo.slice_devices
    m = dp // k
    L = flat.shape[0]
    chunk = L // k          # my intra-slice shard
    sub = chunk // m        # my inter-slice sub-shard
    # BucketPlan pads to dp*block multiples, so sub % block == 0
    nblk_c = chunk // block_size
    nblk_s = sub // block_size

    from hetu_tpu.obs import numerics as _numerics

    def q_point(x, rows, nblk, ef):
        """One quantize point: ef_quantize when a residual rides (the
        residual IS the exact quantization error), stateless otherwise.
        The hierarchical schedule's four points accumulate into ONE
        numerics scope (the per-point split is a wire detail)."""
        q, s, nr = ef_quantize(x, ef, block_size, bits=bits)
        if _numerics.active():
            sig = x if ef is None else x + ef
            _numerics.tap_quant_error("grad_sync/two_level", sig, nr)
        return (_maybe_pack(q.reshape(rows, nblk, block_size), bits),
                s.reshape(rows, nblk), nr)

    def dq_sum(q, s):
        q = _maybe_unpack(q, bits)
        return jnp.sum(jax.vmap(dequantize_blockwise)(q, s), axis=0)

    # stage 1: intra-slice reduce-scatter (fast links, full buffer)
    q, s, new_a2a = q_point(flat, k, nblk_c,
                            None if ef_a2a is None else ef_a2a[0])
    if ef_a2a is not None:
        new_a2a = new_a2a[None]                      # keep the [1, L] lane
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                       axis_index_groups=intra)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                       axis_index_groups=intra)
    shard = dq_sum(q, s)                              # [chunk], slice-summed

    # stage 2: inter-slice all-reduce of the 1/k shard (slow links)
    q, s, new_inter = q_point(shard, m, nblk_s,
                              None if ef_inter is None else ef_inter[0])
    if ef_inter is not None:
        new_inter = new_inter[None]                  # [1, chunk]
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                       axis_index_groups=inter)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                       axis_index_groups=inter)
    sub_sum = dq_sum(q, s)                            # [sub], globally summed
    q2, s2, new_ag = ef_quantize(sub_sum, ef_ag, block_size, bits=bits)
    if _numerics.active():
        sig = sub_sum if ef_ag is None else sub_sum + ef_ag
        _numerics.tap_quant_error("grad_sync/two_level", sig, new_ag)
    qg = lax.all_gather(_maybe_pack(q2, bits), axis_name, axis=0,
                        axis_index_groups=inter)
    sg = lax.all_gather(s2, axis_name, axis=0, axis_index_groups=inter)
    shard_full = jax.vmap(dequantize_blockwise)(
        _maybe_unpack(qg, bits), sg).reshape(chunk)   # [chunk], global sum

    # stage 3: intra-slice all-gather of the finished shard (fast links)
    q3, s3, new_intra = ef_quantize(
        shard_full, None if ef_intra is None else ef_intra[0],
        block_size, bits=bits)
    if _numerics.active():
        sig = (shard_full if ef_intra is None
               else shard_full + ef_intra[0])
        _numerics.tap_quant_error("grad_sync/two_level", sig, new_intra)
    if ef_intra is not None:
        new_intra = new_intra[None]                  # [1, chunk]
    qg = lax.all_gather(_maybe_pack(q3.reshape(nblk_c, block_size), bits),
                        axis_name, axis=0, axis_index_groups=intra)
    sg = lax.all_gather(s3, axis_name, axis=0, axis_index_groups=intra)
    full = jax.vmap(dequantize_blockwise)(
        _maybe_unpack(qg, bits),
        sg.reshape(k, nblk_c)).reshape(L)
    return full, new_a2a, new_inter, new_ag, new_intra


def quantized_grad_sync(grads, axis_name: str, dp: int, plan: BucketPlan,
                        mode: str, ef_state: Dict[str, List[jnp.ndarray]],
                        block_size: int = DEFAULT_BLOCK,
                        topology: Optional[Topology] = None):
    """shard_map-internal: local sum-grad pytree -> globally summed pytree
    (replicated over `axis_name`), via bucketed int8/int4 collectives.

    ef_state: {} for the stateless modes; for "-ef" modes a dict
    {"a2a": [local [1, L] per bucket], "ag": [local [L//dp] per bucket]}
    (the local view of `ef_init`'s global arrays), plus
    {"tl_inter"/"tl_intra": [local [1, L//slice_devices] per bucket]}
    when a two-level topology routes (ef_init's `topology=` arm).
    topology: a slice Topology that `applies(dp)` routes every bucket
    through the two-level scheme.  Returns (synced grads, new ef_state
    of the same structure)."""
    if mode not in COMPRESSED_MODES:
        raise ValueError(f"mode {mode!r} does not compress; caller should "
                         f"have taken the plain path")
    ef = uses_error_feedback(mode)
    bits = mode_bits(mode)
    two_level = topology is not None and topology.applies(dp)
    if ef and two_level and not {"tl_inter", "tl_intra"} <= set(ef_state):
        raise ValueError(
            "two-level EF sync needs the per-stage chunk residuals "
            "'tl_inter'/'tl_intra' in ef_state — build it with "
            "ef_init(plan, dp, topology=...); a flat-layout EF state "
            "cannot carry across the hierarchical schedule's extra "
            "quantize points")
    flats = plan.pack(grads)
    out = []
    new_state = ({"a2a": [], "tl_inter": [], "ag": [], "tl_intra": []}
                 if (ef and two_level) else
                 {"a2a": [], "ag": []} if ef else {})
    for i, flat in enumerate(flats):
        if two_level:
            full, na, ni, ng, nt = _sync_bucket_two_level(
                flat, axis_name, dp, block_size, bits, topology,
                ef_a2a=ef_state["a2a"][i] if ef else None,
                ef_inter=ef_state["tl_inter"][i] if ef else None,
                ef_ag=ef_state["ag"][i] if ef else None,
                ef_intra=ef_state["tl_intra"][i] if ef else None)
            out.append(full)
            if ef:
                new_state["a2a"].append(na)
                new_state["tl_inter"].append(ni)
                new_state["ag"].append(ng)
                new_state["tl_intra"].append(nt)
            continue
        ea = ef_state["a2a"][i] if ef else None
        eg = ef_state["ag"][i] if ef else None
        full, na, ng = _sync_bucket(flat, axis_name, dp, block_size, ea, eg,
                                    bits)
        out.append(full)
        if ef:
            new_state["a2a"].append(na)
            new_state["ag"].append(ng)
    return plan.unpack(out), new_state


def ef_init(plan: BucketPlan, dp: int, topology: Optional[Topology] = None
            ) -> Dict[str, List[jnp.ndarray]]:
    """GLOBAL error-feedback state for `quantized_grad_sync`: per bucket a
    [dp, L] per-replica residual (split over dp outside the shard_map) and
    an [L] per-shard residual (split over dp).  Pass `topology` only when
    it routes (`applies(dp)`): the two-level schedule's two extra chunk
    points add per-replica [dp, L/slice_devices] residuals."""
    state = {
        "a2a": [jnp.zeros((dp, L), jnp.float32) for L in plan.sizes],
        "ag": [jnp.zeros((L,), jnp.float32) for L in plan.sizes],
    }
    if topology is not None:
        k = topology.slice_devices
        state["tl_inter"] = [jnp.zeros((dp, L // k), jnp.float32)
                             for L in plan.sizes]
        state["tl_intra"] = [jnp.zeros((dp, L // k), jnp.float32)
                             for L in plan.sizes]
    return state


def ef_specs(plan: BucketPlan, axis: str = "dp",
             topology: Optional[Topology] = None) -> Dict[str, List[P]]:
    """PartitionSpecs matching `ef_init`'s layout (shard_map in/out specs
    and NamedSharding construction)."""
    specs = {
        "a2a": [P(axis, None) for _ in plan.sizes],
        "ag": [P(axis) for _ in plan.sizes],
    }
    if topology is not None:
        specs["tl_inter"] = [P(axis, None) for _ in plan.sizes]
        specs["tl_intra"] = [P(axis, None) for _ in plan.sizes]
    return specs


def ef_shardings(plan: BucketPlan, mesh, axis: str = "dp",
                 topology: Optional[Topology] = None):
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        ef_specs(plan, axis, topology),
                        is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# hetero-DP bridge: quantize-before-device_put (parallel/hetero_dp.py)
# ---------------------------------------------------------------------------

def _pad_to_block(flat, block_size: int):
    n = flat.shape[0]
    padded = -(-n // block_size) * block_size
    if padded != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - n,), jnp.float32)])
    return flat


def bridge_residual_init(params_like, block_size: int = DEFAULT_BLOCK):
    """Zero EF residuals for one bridge source group: per leaf a padded
    flat f32 buffer (lives on the SOURCE group's mesh)."""
    def zeros(p):
        n = -(-p.size // block_size) * block_size
        return jnp.zeros((n,), jnp.float32)
    return jax.tree.map(zeros, params_like)


def bridge_compress(grads, residuals=None,
                    block_size: int = DEFAULT_BLOCK, bits: int = 8):
    """Per-leaf quantize of a sum-grad pytree for the cross-mesh bridge.
    Returns ({q}, {scales}, {new residuals}) pytrees — q/scales are the
    small arrays to `device_put` across meshes (bits=4 packs two values
    per byte, halving the shipped payload again).  With residuals=None
    (stateless modes) the third output is None and no residual is
    computed — a jit output can't be DCE'd, so materializing a discarded
    params-sized f32 tree would cost every bridge step."""
    is_t = lambda t: isinstance(t, tuple)
    if residuals is None:
        def one_plain(g):
            flat = _pad_to_block(g.reshape(-1).astype(jnp.float32),
                                 block_size)
            q, s = quantize_blockwise(flat, block_size, bits=bits)
            return _maybe_pack(q, bits), s
        pairs = jax.tree.map(one_plain, grads)
        qs = jax.tree.map(lambda t: t[0], pairs, is_leaf=is_t)
        ss = jax.tree.map(lambda t: t[1], pairs, is_leaf=is_t)
        return qs, ss, None

    def one(g, r):
        flat = _pad_to_block(g.reshape(-1).astype(jnp.float32), block_size)
        q, s, nr = ef_quantize(flat, r, block_size, bits=bits)
        return _maybe_pack(q, bits), s, nr
    triples = jax.tree.map(one, grads, residuals)
    qs = jax.tree.map(lambda t: t[0], triples, is_leaf=is_t)
    ss = jax.tree.map(lambda t: t[1], triples, is_leaf=is_t)
    rs = jax.tree.map(lambda t: t[2], triples, is_leaf=is_t)
    return qs, ss, rs


def bridge_accumulate(acc, qs, ss, bits: int = 8):
    """acc + dequantize(qs, ss) leaf-wise (runs jitted on the resident
    group's mesh; the dequant drops each leaf's block padding)."""
    def one(a, q, s):
        flat = dequantize_blockwise(_maybe_unpack(q, bits), s)
        return a + lax.slice(flat, (0,), (a.size,)).reshape(a.shape)
    return jax.tree.map(one, acc, qs, ss)
