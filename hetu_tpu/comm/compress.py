"""Blockwise int8/int4 quantize/dequantize primitives for collectives.

The payload format is the one `comm/wire.py` prices: flat f32 buffers cut
into blocks of `block_size`, each block carried as int8 values (or int4
values packed two per byte, `pack_int4`) plus one f32 absmax scale.
Unlike `ops/quantization.py` (weight-only storage quantization, arbitrary
nd-shapes), these primitives are collective-facing: they keep the block
axis outermost so chunks of whole blocks can ride all-to-all /
all-gather rows, and they offer

  * stochastic rounding — unbiased E[deq(q)] = x, the standard variance-
    for-bias trade for gradient compression (EQuARX, PAPERS.md),
  * error feedback — `ef_quantize` folds the previous round's
    quantization residual into the buffer before quantizing and returns
    the new residual, the SGD-with-memory correction that restores
    convergence when the same buffer is compressed every step, and
  * int4 (`bits=4`): symmetric [-7, 7] grid, absmax/7 scale, same block
    layout.  The wire carries two values per byte (`pack_int4` /
    `unpack_int4` — offset-binary nibbles, value+8 in [1, 15], high
    nibble = even index); block_size must be even.

All functions are jit-safe and shard_map-safe (elementwise + block
reductions only, no collectives here).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu.comm.wire import DEFAULT_BLOCK


def _qmax(bits: int) -> float:
    if bits == 8:
        return 127.0
    if bits == 4:
        return 7.0
    raise ValueError(f"bits must be 8 or 4, got {bits}")


def quantize_blockwise(x, block_size: int = DEFAULT_BLOCK, *,
                       stochastic: bool = False,
                       rng: Optional[jax.Array] = None,
                       bits: int = 8
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flat f32 [n] (n % block_size == 0) -> (q int8 [n//bs, bs],
    scales f32 [n//bs]).  Deterministic round-to-nearest by default;
    stochastic=True rounds up with probability equal to the fractional
    part (needs `rng`), making the dequantized value unbiased.
    bits=4 quantizes to the [-7, 7] grid (still carried as int8 here;
    `pack_int4` packs two values per byte for the wire)."""
    qmax = _qmax(bits)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    if n % block_size:
        raise ValueError(f"buffer of {n} elements is not a multiple of "
                         f"block_size={block_size}; pad first "
                         f"(comm.bucketer does)")
    if not stochastic:
        # fused Pallas quantize (ops/pallas/quant — one pass instead of
        # the abs/max/div/round/clip/cast chain) when the HETU_TPU_PALLAS
        # routing and the kernel's shape gate allow; int payload
        # bit-identical to the jnp path below, scales to 1 ulp (tested),
        # so every consumer (grad sync, SP compress, ZeRO refresh, KV
        # pages) inherits it transparently
        from hetu_tpu.ops.pallas import resolve_route
        from hetu_tpu.ops.pallas import quant as _pq
        if resolve_route("quant", _pq.compatible(n, block_size, bits)):
            with jax.named_scope("pallas_quantize"):
                return _pq.quantize_blockwise_pallas(flat, block_size,
                                                     bits=bits)
    blocks = flat.reshape(-1, block_size)
    scale = jnp.max(jnp.abs(blocks), axis=1) / qmax
    scale = jnp.maximum(scale, 1e-12)
    y = blocks / scale[:, None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        floor = jnp.floor(y)
        frac = y - floor
        up = jax.random.uniform(rng, y.shape) < frac
        y = floor + up.astype(jnp.float32)
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -qmax, qmax).astype(jnp.int8)
    return q, scale


def dequantize_blockwise(q, scale) -> jnp.ndarray:
    """(q int8 [nb, bs], scales f32 [nb]) -> flat f32 [nb*bs]."""
    from hetu_tpu.ops.pallas import resolve_route
    from hetu_tpu.ops.pallas import quant as _pq
    if resolve_route("quant",
                     _pq.compatible(q.shape[0] * q.shape[1], q.shape[1])):
        with jax.named_scope("pallas_dequantize"):
            return _pq.dequantize_blockwise_pallas(q, scale)
    return (q.astype(jnp.float32) * scale[:, None]).reshape(-1)


def pack_int4(q) -> jnp.ndarray:
    """int8 [nb, bs] with values in [-8, 7] -> uint8 [nb, bs//2]: two
    offset-binary nibbles per byte (value+8; even index rides the high
    nibble).  The wire format of the int4 modes.  Byte-shuffling is
    delegated to `ops.quantization.pack_nibbles` — ONE packer shared
    with the weight-storage format, so the two layouts are transposes
    of a single implementation instead of cousins that can drift."""
    from hetu_tpu.ops.quantization import pack_nibbles
    u = (q.astype(jnp.int32) + 8).astype(jnp.uint8)
    return pack_nibbles(u, even_high=True)


def unpack_int4(p) -> jnp.ndarray:
    """uint8 [nb, bs//2] -> int8 [nb, bs] (inverse of `pack_int4`)."""
    from hetu_tpu.ops.quantization import unpack_nibbles
    return unpack_nibbles(p, even_high=True).astype(jnp.int8) - 8


def ef_quantize(x, residual, block_size: int = DEFAULT_BLOCK, *,
                stochastic: bool = False,
                rng: Optional[jax.Array] = None,
                bits: int = 8
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback quantize: compress c = x + residual and return
    (q, scales, new_residual = c - dequantize(q)).  With residual=None
    behaves like plain quantize (new_residual still returned, for a
    uniform calling convention)."""
    flat = x.reshape(-1).astype(jnp.float32)
    c = flat if residual is None else flat + residual.reshape(-1)
    q, scale = quantize_blockwise(c, block_size, stochastic=stochastic,
                                  rng=rng, bits=bits)
    new_residual = c - dequantize_blockwise(q, scale)
    return q, scale, new_residual
