"""Blockwise int8/int4 quantize/dequantize primitives for collectives.

The payload format is the one `comm/wire.py` prices: flat f32 buffers cut
into blocks of `block_size`, each block carried as int8 values (or int4
values packed two per byte, `pack_int4`) plus one f32 absmax scale.
Unlike `ops/quantization.py` (weight-only storage quantization, arbitrary
nd-shapes), these primitives are collective-facing: they keep the block
axis outermost so chunks of whole blocks can ride all-to-all /
all-gather rows, and they offer

  * stochastic rounding — unbiased E[deq(q)] = x, the standard variance-
    for-bias trade for gradient compression (EQuARX, PAPERS.md),
  * error feedback — `ef_quantize` folds the previous round's
    quantization residual into the buffer before quantizing and returns
    the new residual, the SGD-with-memory correction that restores
    convergence when the same buffer is compressed every step, and
  * int4 (`bits=4`): symmetric [-7, 7] grid, absmax/7 scale, same block
    layout.  The wire carries two values per byte (`pack_int4` /
    `unpack_int4` — offset-binary nibbles, value+8 in [1, 15], high
    nibble = even index); block_size must be even.

All functions are jit-safe and shard_map-safe (elementwise + block
reductions only, no collectives here).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu.comm.wire import DEFAULT_BLOCK


def _qmax(bits: int) -> float:
    if bits == 8:
        return 127.0
    if bits == 4:
        return 7.0
    raise ValueError(f"bits must be 8 or 4, got {bits}")


def quantize_blockwise(x, block_size: int = DEFAULT_BLOCK, *,
                       stochastic: bool = False,
                       rng: Optional[jax.Array] = None,
                       bits: int = 8
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flat f32 [n] (n % block_size == 0) -> (q int8 [n//bs, bs],
    scales f32 [n//bs]).  Deterministic round-to-nearest by default;
    stochastic=True rounds up with probability equal to the fractional
    part (needs `rng`), making the dequantized value unbiased.
    bits=4 quantizes to the [-7, 7] grid (still carried as int8 here;
    `pack_int4` packs two values per byte for the wire)."""
    qmax = _qmax(bits)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    if n % block_size:
        raise ValueError(f"buffer of {n} elements is not a multiple of "
                         f"block_size={block_size}; pad first "
                         f"(comm.bucketer does)")
    blocks = flat.reshape(-1, block_size)
    scale = jnp.max(jnp.abs(blocks), axis=1) / qmax
    scale = jnp.maximum(scale, 1e-12)
    y = blocks / scale[:, None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        floor = jnp.floor(y)
        frac = y - floor
        up = jax.random.uniform(rng, y.shape) < frac
        y = floor + up.astype(jnp.float32)
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -qmax, qmax).astype(jnp.int8)
    return q, scale


def dequantize_blockwise(q, scale) -> jnp.ndarray:
    """(q int8 [nb, bs], scales f32 [nb]) -> flat f32 [nb*bs]."""
    return (q.astype(jnp.float32) * scale[:, None]).reshape(-1)


def pack_int4(q) -> jnp.ndarray:
    """int8 [nb, bs] with values in [-8, 7] -> uint8 [nb, bs//2]: two
    offset-binary nibbles per byte (value+8; even index rides the high
    nibble).  The wire format of the int4 modes."""
    if q.shape[-1] % 2:
        raise ValueError(f"int4 packing needs an even block, got "
                         f"{q.shape[-1]}")
    u = (q.astype(jnp.int32) + 8).astype(jnp.uint8)
    hi = u[..., 0::2]
    lo = u[..., 1::2]
    return (hi << 4) | lo


def unpack_int4(p) -> jnp.ndarray:
    """uint8 [nb, bs//2] -> int8 [nb, bs] (inverse of `pack_int4`)."""
    hi = ((p >> 4) & 0xF).astype(jnp.int8) - 8
    lo = (p & 0xF).astype(jnp.int8) - 8
    return jnp.stack([hi, lo], axis=-1).reshape(p.shape[:-1] +
                                                (2 * p.shape[-1],))


def ef_quantize(x, residual, block_size: int = DEFAULT_BLOCK, *,
                stochastic: bool = False,
                rng: Optional[jax.Array] = None,
                bits: int = 8
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback quantize: compress c = x + residual and return
    (q, scales, new_residual = c - dequantize(q)).  With residual=None
    behaves like plain quantize (new_residual still returned, for a
    uniform calling convention)."""
    flat = x.reshape(-1).astype(jnp.float32)
    c = flat if residual is None else flat + residual.reshape(-1)
    q, scale = quantize_blockwise(c, block_size, stochastic=stochastic,
                                  rng=rng, bits=bits)
    new_residual = c - dequantize_blockwise(q, scale)
    return q, scale, new_residual
