"""Compressed + hierarchical collectives (HETU_TPU_GRAD_COMPRESS,
HETU_TPU_SP_COMPRESS, HETU_TPU_COMM_TOPOLOGY).

Six pieces, one import surface (docs/comm_compression.md):

    comm.wire        — the bytes-on-wire model (pure python; shared with
                       obs.comm, search/cost_model.py and bench.py)
    comm.compress    — blockwise int8/int4 quantize/dequantize
                       (+ stochastic rounding, + error-feedback quantize,
                       + two-per-byte int4 packing)
    comm.bucketer    — BucketPlan: fuse small grads into flat buffers
    comm.grad_sync   — the quantized DP sync (shard_map-internal, flat or
                       two-level) and the hetero-DP bridge pair
    comm.collectives — drop-in quantized all_gather/reduce_scatter/
                       all_to_all/all_reduce for any shard_map region
                       (custom-vjp: backward transports quantize too)
    comm.topology    — slice topology descriptor + two-level group
                       construction (HetCCL-style hierarchy)
"""
from hetu_tpu.comm.bucketer import BucketPlan  # noqa: F401
from hetu_tpu.comm.collectives import (all_gather_q,  # noqa: F401
                                       all_reduce_q, all_to_all_q,
                                       reduce_scatter_q)
from hetu_tpu.comm.compress import (dequantize_blockwise,  # noqa: F401
                                    ef_quantize, pack_int4,
                                    quantize_blockwise, unpack_int4)
from hetu_tpu.comm.grad_sync import (MODES, bridge_accumulate,  # noqa: F401
                                     bridge_compress, bridge_residual_init,
                                     ef_init, ef_shardings, ef_specs,
                                     per_replica_keys, quantized_grad_sync,
                                     uses_error_feedback)
from hetu_tpu.comm.topology import Topology, load_topology  # noqa: F401
from hetu_tpu.comm.wire import (COMPRESSED_MODES, DEFAULT_BLOCK,  # noqa: F401
                                analytic_dp_sync, dp_sync_wire_bytes,
                                mode_bits, ring_wire_bytes,
                                two_level_sync_bytes,
                                wire_bytes_per_element, wire_factor)

__all__ = [
    "BucketPlan",
    "quantize_blockwise", "dequantize_blockwise", "ef_quantize",
    "pack_int4", "unpack_int4",
    "MODES", "COMPRESSED_MODES", "DEFAULT_BLOCK",
    "quantized_grad_sync", "ef_init", "ef_specs", "ef_shardings",
    "uses_error_feedback", "per_replica_keys",
    "bridge_compress", "bridge_accumulate", "bridge_residual_init",
    "all_gather_q", "reduce_scatter_q", "all_to_all_q", "all_reduce_q",
    "Topology", "load_topology",
    "wire_bytes_per_element", "wire_factor", "dp_sync_wire_bytes",
    "analytic_dp_sync", "ring_wire_bytes", "two_level_sync_bytes",
    "mode_bits",
]
