"""Compressed gradient collectives (HETU_TPU_GRAD_COMPRESS).

Four pieces, one import surface (docs/comm_compression.md):

    comm.wire       — the bytes-on-wire model (pure python; shared with
                      obs.comm, search/cost_model.py and bench.py)
    comm.compress   — blockwise int8 quantize/dequantize (+ stochastic
                      rounding, + error-feedback quantize)
    comm.bucketer   — BucketPlan: fuse small grads into flat buffers
    comm.grad_sync  — the quantized DP sync (shard_map-internal) and the
                      hetero-DP bridge compress/accumulate pair
"""
from hetu_tpu.comm.bucketer import BucketPlan  # noqa: F401
from hetu_tpu.comm.compress import (dequantize_blockwise,  # noqa: F401
                                    ef_quantize, quantize_blockwise)
from hetu_tpu.comm.grad_sync import (MODES, bridge_accumulate,  # noqa: F401
                                     bridge_compress, bridge_residual_init,
                                     ef_init, ef_shardings, ef_specs,
                                     quantized_grad_sync,
                                     uses_error_feedback)
from hetu_tpu.comm.wire import (COMPRESSED_MODES, DEFAULT_BLOCK,  # noqa: F401
                                analytic_dp_sync, dp_sync_wire_bytes,
                                wire_bytes_per_element, wire_factor)

__all__ = [
    "BucketPlan",
    "quantize_blockwise", "dequantize_blockwise", "ef_quantize",
    "MODES", "COMPRESSED_MODES", "DEFAULT_BLOCK",
    "quantized_grad_sync", "ef_init", "ef_specs", "ef_shardings",
    "uses_error_feedback",
    "bridge_compress", "bridge_accumulate", "bridge_residual_init",
    "wire_bytes_per_element", "wire_factor", "dp_sync_wire_bytes",
    "analytic_dp_sync",
]
