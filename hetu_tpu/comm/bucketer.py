"""Gradient bucketer: fuse per-parameter grads into flat f32 buffers.

A transformer's grad pytree is dominated by a few big matrices plus a long
tail of small tensors (norm gains, biases).  Launching one collective per
leaf pays per-op latency and per-block scale overhead on every tiny tensor;
fusing the tail into shared flat buckets amortizes both — the classic DDP
gradient-bucketing move, here feeding the quantized sync
(comm/grad_sync.py) whose chunking wants lengths divisible by
dp * block_size anyway.

`BucketPlan` is built ONCE from abstract grads (shapes/dtypes) at trainer
build time; `pack`/`unpack` are pure jnp reshape/concat/slice, traced into
the train step.  Leaves are assigned in tree-flatten order: leaves at
least `bucket_elems` large get a bucket of their own, smaller ones fuse
greedily.  Every bucket is zero-padded to a multiple of `multiple`
(pad contributes zero gradient, quantizes to zero, and is dropped by
`unpack`)."""
from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class _Slot:
    """One leaf's home: bucket index, offset into it, and its shape."""
    bucket: int
    offset: int
    size: int
    shape: Tuple[int, ...]
    dtype: Any


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    slots: Tuple[_Slot, ...]          # one per leaf, tree-flatten order
    sizes: Tuple[int, ...]            # padded bucket lengths
    treedef: Any

    @staticmethod
    def build(abstract_tree, *, bucket_elems: int = 1 << 22,
              multiple: int = 2048) -> "BucketPlan":
        """abstract_tree: grads-shaped pytree of arrays or
        ShapeDtypeStructs.  bucket_elems: fuse-target bucket size in
        elements; multiple: every padded bucket length divides by this
        (callers pass dp * block_size)."""
        leaves, treedef = jax.tree.flatten(abstract_tree)
        slots: List[_Slot] = []
        sizes: List[int] = []
        cur = -1          # open bucket index, -1 = none
        fill = 0
        for leaf in leaves:
            size = 1
            for d in leaf.shape:
                size *= int(d)
            if size >= bucket_elems:
                # big leaf: its own bucket, nothing else fuses in
                sizes.append(size)
                slots.append(_Slot(len(sizes) - 1, 0, size,
                                   tuple(leaf.shape), leaf.dtype))
                continue
            if cur < 0 or fill + size > bucket_elems:
                sizes.append(0)
                cur, fill = len(sizes) - 1, 0
            slots.append(_Slot(cur, fill, size, tuple(leaf.shape),
                               leaf.dtype))
            fill += size
            sizes[cur] = fill
        padded = tuple(-(-s // multiple) * multiple for s in sizes)
        return BucketPlan(tuple(slots), padded, treedef)

    @property
    def num_buckets(self) -> int:
        return len(self.sizes)

    @property
    def total_elements(self) -> int:
        """Padded flat elements across all buckets (what goes on the
        wire per sync)."""
        return sum(self.sizes)

    def pack(self, tree) -> List[jnp.ndarray]:
        """Grads pytree -> list of flat f32 buckets (zero-padded)."""
        leaves = jax.tree.leaves(tree)
        if len(leaves) != len(self.slots):
            raise ValueError(f"tree has {len(leaves)} leaves, plan has "
                             f"{len(self.slots)}")
        parts: List[List[jnp.ndarray]] = [[] for _ in self.sizes]
        fills = [0] * len(self.sizes)
        for leaf, slot in zip(leaves, self.slots):
            parts[slot.bucket].append(leaf.reshape(-1).astype(jnp.float32))
            fills[slot.bucket] += slot.size
        out = []
        for bi, chunks in enumerate(parts):
            pad = self.sizes[bi] - fills[bi]
            if pad:
                chunks = chunks + [jnp.zeros((pad,), jnp.float32)]
            out.append(chunks[0] if len(chunks) == 1
                       else jnp.concatenate(chunks))
        return out

    def unpack(self, flats: Sequence[jnp.ndarray]):
        """List of flat buckets -> grads pytree (original shapes/dtypes)."""
        if len(flats) != len(self.sizes):
            raise ValueError(f"got {len(flats)} buckets, plan has "
                             f"{len(self.sizes)}")
        leaves = []
        for slot in self.slots:
            flat = jax.lax.slice(flats[slot.bucket], (slot.offset,),
                                 (slot.offset + slot.size,))
            leaves.append(flat.reshape(slot.shape).astype(slot.dtype))
        return jax.tree.unflatten(self.treedef, leaves)
