"""Cluster topology descriptor + two-level collective routing (HetCCL).

A TPU deployment is rarely one flat ring: chips group into slices with
fast intra-slice ICI, and slices connect over a slower inter-slice
fabric (DCN, or the long way around a twisted torus).  A flat ring
collective paces every hop at the SLOWEST link; the HetCCL-style fix is
hierarchical: reduce-scatter inside each slice over ICI, exchange only
the 1/k shard across slices, all-gather back inside the slice —
inter-slice traffic drops by the slice size (byte math in
`comm/wire.py::two_level_sync_bytes`).

The descriptor loads from the `topology` section of the hardware
profile (`hardware_profile_v5e.json`, schema-validated by
`obs.mfu.validate_hardware_profile`):

    "topology": {"slice_devices": 4, "slice_shape": [2, 2],
                 "intra_gbps": 45.0, "inter_gbps": 6.25}

`HETU_TPU_COMM_TOPOLOGY=two_level` opts the DP grad sync's ring schedule
(comm/grad_sync.py) into the hierarchical scheme; `flat` (the default)
is byte-identical to an unset environment.  Inside a shard_map the two
levels run over ONE named axis via `axis_index_groups`: intra groups are
contiguous runs of `slice_devices` ranks, inter groups are the strided
transversals (`Topology.groups`).  The analyzer (obs.comm) classifies
each lowered collective's replica_groups back into intra/inter and
prices them at the two rates.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Topology:
    """Slice topology: `slice_devices` chips per slice at `intra_gbps`,
    slices joined at `inter_gbps` (allreduce bus bandwidths, GB/s)."""

    slice_devices: int
    intra_gbps: float
    inter_gbps: float
    slice_shape: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.slice_devices < 1:
            raise ValueError(
                f"topology.slice_devices must be >= 1, got "
                f"{self.slice_devices}")

    # ------------------------------------------------------------------
    @staticmethod
    def from_profile(hw: Dict[str, Any]) -> Optional["Topology"]:
        """The profile's `topology` section as a descriptor (None when
        the profile has none — flat accounting everywhere)."""
        sec = (hw or {}).get("topology")
        if not sec:
            return None
        shape = sec.get("slice_shape")
        return Topology(
            slice_devices=int(sec["slice_devices"]),
            intra_gbps=float(sec["intra_gbps"]),
            inter_gbps=float(sec["inter_gbps"]),
            slice_shape=tuple(int(d) for d in shape) if shape else None)

    def applies(self, world: int) -> bool:
        """True when a `world`-rank group actually spans slices and
        factors evenly into them (the two-level envelope)."""
        k = self.slice_devices
        return k > 1 and world > k and world % k == 0

    def num_slices(self, world: int) -> int:
        return world // self.slice_devices

    # ------------------------------------------------------------------
    def groups(self, world: int
               ) -> Tuple[Tuple[Tuple[int, ...], ...],
                          Tuple[Tuple[int, ...], ...]]:
        """(intra_groups, inter_groups) axis_index_groups for a
        `world`-rank axis: intra = contiguous runs of slice_devices
        ranks, inter = the k strided transversals linking equal intra
        positions across slices."""
        if not self.applies(world):
            raise ValueError(
                f"two-level topology (slice_devices={self.slice_devices}) "
                f"does not apply to a group of {world}")
        k = self.slice_devices
        s = world // k
        intra = tuple(tuple(range(b * k, (b + 1) * k)) for b in range(s))
        inter = tuple(tuple(i + b * k for b in range(s)) for i in range(k))
        return intra, inter

    def classify_group(self, ranks) -> str:
        """"intra" when every rank of a replica group lives in one slice,
        else "inter" — how the analyzer prices a lowered collective."""
        slices = {int(r) // self.slice_devices for r in ranks}
        return "intra" if len(slices) <= 1 else "inter"


def load_topology(hw: Optional[Dict[str, Any]] = None) -> Optional[Topology]:
    """Topology from the (loaded or default) hardware profile."""
    if hw is None:
        from hetu_tpu.obs.mfu import load_hardware_profile
        hw = load_hardware_profile()
    return Topology.from_profile(hw)


def topology_mode() -> str:
    """The HETU_TPU_COMM_TOPOLOGY flag ("flat" | "two_level")."""
    from hetu_tpu.utils import flags
    return flags.str_flag("HETU_TPU_COMM_TOPOLOGY")
