"""Wire-format accounting for compressed gradient collectives.

Pure python (no jax): the SAME byte model is consumed by the HLO analyzer
(`hetu_tpu.obs.comm`), the strategy-search cost model
(`search/cost_model.py` DP grad-sync term) and `bench.py`'s
unreachable-backend fallback, so "how many bytes does a sync move" has
exactly one definition in the repo.

The compressed DP sync (comm/grad_sync.py) is the EQuARX-shaped pattern
(PAPERS.md): quantize -> all-to-all (the ring reduce-scatter step, each
peer receives int8 chunks + f32 block scales) -> local dequant+sum ->
re-quantize the reduced shard -> all-gather.  Per ring participant of
n devices and a flat f32 buffer of N elements:

    fp32 all-reduce       2 (n-1)/n * 4N          bytes on wire
    int8 a2a + all-gather 2 (n-1)/n * N*(1 + 4/B) bytes on wire

with B the quantization block size (one f32 absmax scale per B int8
payload bytes).  The ratio is 4 / (1 + 4/B) ~ 3.94x at B=256,
independent of n — the "~4x fewer DP-sync bytes" the flag buys.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

#: default quantization block (one f32 scale per 256 int8 values)
DEFAULT_BLOCK = 256

#: the HETU_TPU_GRAD_COMPRESS modes that actually compress
COMPRESSED_MODES = ("int8", "int8-ef")


def wire_bytes_per_element(mode: str, block_size: int = DEFAULT_BLOCK) -> float:
    """Bytes on wire per f32 gradient element under `mode` (scales
    included)."""
    if mode in COMPRESSED_MODES:
        return 1.0 + 4.0 / float(block_size)
    return 4.0


def wire_factor(mode: str, block_size: int = DEFAULT_BLOCK) -> float:
    """Multiplier on the fp32 DP grad-sync wire bytes under `mode`
    (1.0 for "none"; ~0.254 for int8 at the default block)."""
    return wire_bytes_per_element(mode, block_size) / 4.0


def dp_sync_wire_bytes(n_elements: float, dp: int, mode: str = "none",
                       block_size: int = DEFAULT_BLOCK) -> float:
    """Per-chip bytes on wire for one DP grad sync of `n_elements` f32
    gradient values over a ring of `dp` devices."""
    if dp <= 1:
        return 0.0
    ring = 2.0 * (dp - 1) / dp
    return ring * n_elements * wire_bytes_per_element(mode, block_size)


def analytic_dp_sync(n_params: float, dp: int, *,
                     block_size: int = DEFAULT_BLOCK,
                     ici_gbps: Optional[float] = None) -> Dict[str, Any]:
    """The fp32-vs-int8 sync comparison for a model of `n_params` grads —
    the hardware-free record bench.py emits when no step can even lower
    (analytic twin of obs.comm.collective_report on a compiled step)."""
    fp32 = dp_sync_wire_bytes(n_params, dp, "none", block_size)
    int8 = dp_sync_wire_bytes(n_params, dp, "int8", block_size)
    out: Dict[str, Any] = {
        "dp": dp, "grad_elements": float(n_params),
        "fp32_wire_bytes": fp32, "int8_wire_bytes": int8,
        "ratio": (fp32 / int8) if int8 else None,
        "block_size": block_size, "analytic": True,
    }
    if ici_gbps:
        bw = float(ici_gbps) * 1e9
        out["fp32_comm_s"] = fp32 / bw
        out["int8_comm_s"] = int8 / bw
    return out
