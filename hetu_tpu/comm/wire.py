"""Wire-format accounting for compressed + hierarchical collectives.

Pure python (no jax): the SAME byte model is consumed by the HLO analyzer
(`hetu_tpu.obs.comm`), the strategy-search cost model
(`search/cost_model.py` DP grad-sync / TP-SP terms), `bench.py`'s
unreachable-backend fallback and `tools_comm_report.py`'s per-path table,
so "how many bytes does a sync move" has exactly one definition in the
repo.

The compressed DP sync (comm/grad_sync.py) is the EQuARX-shaped pattern
(PAPERS.md): quantize -> all-to-all (the ring reduce-scatter step, each
peer receives quantized chunks + f32 block scales) -> local dequant+sum ->
re-quantize the reduced shard -> all-gather.  Per ring participant of
n devices and a flat f32 buffer of N elements:

    fp32 all-reduce       2 (n-1)/n * 4N            bytes on wire
    int8 a2a + all-gather 2 (n-1)/n * N*(1 + 4/B)   bytes on wire
    int4 a2a + all-gather 2 (n-1)/n * N*(1/2 + 4/B) bytes on wire

with B the quantization block size (one f32 absmax scale per B quantized
values; int4 packs two values per byte).  The ratios are 4/(1+4/B) ~
3.94x and 4/(0.5+4/B) ~ 7.76x at B=256, independent of n.

Two-level (HetCCL-style) hierarchy over a topology of s slices of k
chips each (n = s*k): reduce-scatter intra-slice, all-reduce the 1/k
shard inter-slice, all-gather intra-slice.  Per participant:

    intra bytes: 2 (k-1)/k * N * w        (fast intra-slice links)
    inter bytes: 2 (s-1)/s * (N/k) * w    (slow inter-slice links)

with w the per-element wire bytes of the mode — the inter-slice (DCN)
traffic drops by the slice size k vs a flat ring that spans slices.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

#: default quantization block (one f32 scale per 256 quantized values)
DEFAULT_BLOCK = 256

#: the HETU_TPU_GRAD_COMPRESS modes that actually compress
COMPRESSED_MODES = ("int8", "int8-ef", "int4", "int4-ef")

#: payload bytes per quantized VALUE (before the per-block f32 scale)
_MODE_PAYLOAD = {"int8": 1.0, "int8-ef": 1.0, "int4": 0.5, "int4-ef": 0.5}


def mode_bits(mode: str) -> int:
    """Quantized bits per value under `mode` (8 for the uncompressed
    modes: they move full-width elements)."""
    return 4 if mode.startswith("int4") else 8


def wire_bytes_per_element(mode: str, block_size: int = DEFAULT_BLOCK,
                           elem_bytes: float = 4.0) -> float:
    """Bytes on wire per gradient/activation element under `mode`
    (per-block f32 scales included).  `elem_bytes` is the UNcompressed
    element width (4 for f32 grads, 2 for bf16 activations)."""
    if mode in COMPRESSED_MODES:
        return _MODE_PAYLOAD[mode] + 4.0 / float(block_size)
    return float(elem_bytes)


def wire_factor(mode: str, block_size: int = DEFAULT_BLOCK,
                elem_bytes: float = 4.0) -> float:
    """Multiplier on the uncompressed wire bytes under `mode` (1.0 for
    "none"; ~0.254 for int8 and ~0.129 for int4 at the default block vs
    f32)."""
    return (wire_bytes_per_element(mode, block_size, elem_bytes)
            / float(elem_bytes))


def ring_wire_bytes(op: str, payload_bytes: float, n: int) -> float:
    """Per-participant ring wire bytes for one collective moving a FULL
    local buffer of `payload_bytes` over a group of `n`:

        all-reduce      2 (n-1)/n * payload
        all-gather        (n-1)/n * gathered output
        reduce-scatter    (n-1)/n * input buffer
        all-to-all        (n-1)/n * local buffer
        collective-permute          payload (one hop)

    The SAME formulas the HLO analyzer (obs.comm) applies per
    instruction — the cross-validation test pins them together."""
    if op == "collective-permute":
        return float(payload_bytes)
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * payload_bytes
    if op in ("all-gather", "reduce-scatter", "all-to-all"):
        return (n - 1) / n * payload_bytes
    raise ValueError(f"unknown collective op {op!r}")


def dp_sync_wire_bytes(n_elements: float, dp: int, mode: str = "none",
                       block_size: int = DEFAULT_BLOCK) -> float:
    """Per-chip bytes on wire for one DP grad sync of `n_elements` f32
    gradient values over a ring of `dp` devices."""
    if dp <= 1:
        return 0.0
    ring = 2.0 * (dp - 1) / dp
    return ring * n_elements * wire_bytes_per_element(mode, block_size)


def two_level_sync_bytes(n_elements: float, dp: int, slice_devices: int,
                         mode: str = "none",
                         block_size: int = DEFAULT_BLOCK
                         ) -> Dict[str, float]:
    """Per-chip intra/inter-slice byte split of a two-level DP grad sync
    (intra reduce-scatter -> inter all-reduce of the 1/k shard -> intra
    all-gather) of `n_elements` f32 values over `dp` devices arranged as
    dp/k slices of k chips.  Falls back to flat accounting (all bytes
    "intra") when the topology does not apply."""
    w = wire_bytes_per_element(mode, block_size)
    k = int(slice_devices)
    if dp <= 1:
        return {"intra_bytes": 0.0, "inter_bytes": 0.0}
    if k <= 1 or dp % k or dp <= k:
        return {"intra_bytes": dp_sync_wire_bytes(n_elements, dp, mode,
                                                  block_size),
                "inter_bytes": 0.0}
    s = dp // k
    intra = 2.0 * (k - 1) / k * n_elements * w
    inter = 2.0 * (s - 1) / s * (n_elements / k) * w
    return {"intra_bytes": intra, "inter_bytes": inter}


def moe_dispatch_wire_bytes(n_elements: float, ep: int, mode: str = "none",
                            block_size: int = DEFAULT_BLOCK,
                            elem_bytes: float = 4.0) -> float:
    """Per-participant bytes on wire for one explicit MoE dispatch round
    trip (nn/moe_dispatch.py) of a local expert buffer of `n_elements`
    values over a flat `ep`-rank group: the dispatch all-to-all (each
    rank ships its partial [E, C, h] buffer, keeps 1/ep) PLUS the
    combine all-gather (each rank receives the other ranks' expert
    outputs) — both (ep-1)/ep * N * w.  `mode` "none" is the fp32 a2a
    path; int8/int4 ride the quantized collectives (~3.94x / ~7.76x
    fewer bytes at the default block, same as the grad-sync ratios)."""
    if ep <= 1:
        return 0.0
    w = wire_bytes_per_element(mode, block_size, elem_bytes)
    return 2.0 * (ep - 1) / ep * n_elements * w


def moe_two_level_dispatch_bytes(n_elements: float, ep: int,
                                 slice_devices: int, mode: str = "none",
                                 block_size: int = DEFAULT_BLOCK,
                                 elem_bytes: float = 4.0
                                 ) -> Dict[str, float]:
    """Per-participant intra/inter byte split of the HIERARCHICAL MoE
    dispatch (HetuMoE's HAllToAll over comm/topology groups): intra-slice
    a2a of the full partial buffer + intra all-gather of the finished
    outputs run at intra rates; only the 1/k slice-aggregated bundles
    cross slices on the strided transversals:

        intra: 2 (k-1)/k * N * w
        inter: 2 (s-1)/s * (N/k) * w

    vs a flat slice-spanning schedule whose inter-slice share is
    2 (ep-k)/ep * N * w — the inter links move ~k-fold fewer bytes.
    Falls back to flat accounting (all bytes intra) when the topology
    does not apply."""
    w = wire_bytes_per_element(mode, block_size, elem_bytes)
    k = int(slice_devices)
    if ep <= 1:
        return {"intra_bytes": 0.0, "inter_bytes": 0.0}
    if k <= 1 or ep % k or ep <= k:
        return {"intra_bytes": moe_dispatch_wire_bytes(
                    n_elements, ep, mode, block_size, elem_bytes),
                "inter_bytes": 0.0}
    s = ep // k
    intra = 2.0 * (k - 1) / k * n_elements * w
    inter = 2.0 * (s - 1) / s * (n_elements / k) * w
    return {"intra_bytes": intra, "inter_bytes": inter}


def moe_flat_inter_bytes(n_elements: float, ep: int, slice_devices: int,
                         mode: str = "none",
                         block_size: int = DEFAULT_BLOCK,
                         elem_bytes: float = 4.0) -> float:
    """Inter-slice share of a FLAT slice-spanning dispatch round trip:
    of each rank's (ep-1)/ep a2a sends, (ep-k)/(ep-1) target peers in
    other slices (ditto the combine gather) — the bytes the two-level
    schedule keeps off the slow links."""
    k = int(slice_devices)
    if ep <= k or k < 1 or ep % k:
        return 0.0
    w = wire_bytes_per_element(mode, block_size, elem_bytes)
    return 2.0 * (ep - k) / ep * n_elements * w


def moe_dispatch_report(n_elements: float, ep: int,
                        slice_devices: int = 0,
                        block_size: int = DEFAULT_BLOCK,
                        elem_bytes: float = 4.0) -> Dict[str, Any]:
    """The fp32-vs-int8-vs-two-level MoE dispatch comparison for a local
    expert buffer of `n_elements` values — the hardware-free record
    consumed by bench.py `detail.moe`, the cost model's EP terms and
    tools_comm_report's analytic fallback (the analyzer obs.comm does
    the same accounting from real lowered HLO)."""
    fp32 = moe_dispatch_wire_bytes(n_elements, ep, "none", block_size,
                                   elem_bytes)
    int8 = moe_dispatch_wire_bytes(n_elements, ep, "int8", block_size,
                                   elem_bytes)
    out: Dict[str, Any] = {
        "ep": ep, "buffer_elements": float(n_elements),
        "fp32_wire_bytes": fp32, "int8_wire_bytes": int8,
        "int4_wire_bytes": moe_dispatch_wire_bytes(
            n_elements, ep, "int4", block_size, elem_bytes),
        "ratio_int8": (fp32 / int8) if int8 else None,
        "block_size": block_size, "analytic": True,
    }
    k = int(slice_devices)
    if k > 1 and ep > k and ep % k == 0:
        out["two_level_int8"] = moe_two_level_dispatch_bytes(
            n_elements, ep, k, "int8", block_size, elem_bytes)
        out["flat_inter_int8"] = moe_flat_inter_bytes(
            n_elements, ep, k, "int8", block_size, elem_bytes)
        out["inter_ratio_two_level"] = (
            out["flat_inter_int8"] / out["two_level_int8"]["inter_bytes"]
            if out["two_level_int8"]["inter_bytes"] else None)
    return out


def analytic_dp_sync(n_params: float, dp: int, *,
                     block_size: int = DEFAULT_BLOCK,
                     ici_gbps: Optional[float] = None) -> Dict[str, Any]:
    """The fp32-vs-int8 sync comparison for a model of `n_params` grads —
    the hardware-free record bench.py emits when no step can even lower
    (analytic twin of obs.comm.collective_report on a compiled step)."""
    fp32 = dp_sync_wire_bytes(n_params, dp, "none", block_size)
    int8 = dp_sync_wire_bytes(n_params, dp, "int8", block_size)
    out: Dict[str, Any] = {
        "dp": dp, "grad_elements": float(n_params),
        "fp32_wire_bytes": fp32, "int8_wire_bytes": int8,
        "ratio": (fp32 / int8) if int8 else None,
        "block_size": block_size, "analytic": True,
    }
    if ici_gbps:
        bw = float(ici_gbps) * 1e9
        out["fp32_comm_s"] = fp32 / bw
        out["int8_comm_s"] = int8 / bw
    return out
