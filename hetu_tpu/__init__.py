"""hetu_tpu — a TPU-native distributed deep-learning framework.

A ground-up rebuild of the capabilities of PKU-DAIR/Hetu (reference surveyed in
/root/repo/SURVEY.md) designed for TPU hardware: JAX/XLA/pjit for the compute
path, GSPMD shardings driven by a first-class distributed-layout algebra
(`DistributedStates`), Pallas kernels for the hot ops, and shard_map +
collective-permute for ring-attention context parallelism and pipelining.

Top-level namespaces mirror the reference's Python framework
(reference: python/hetu/__init__.py):

- ``hetu_tpu.core``     — mesh/device model, dtypes, symbolic ints
- ``hetu_tpu.dstates``  — DistributedStates sharding algebra (the heart)
- ``hetu_tpu.nn``       — Module system + layers (incl. parallel layers)
- ``hetu_tpu.ops``      — functional ops & Pallas kernels
- ``hetu_tpu.models``   — model families (llama, gpt, ...)
- ``hetu_tpu.parallel`` — pipeline / context / expert parallel engines
- ``hetu_tpu.optim``    — optimizers (Adam/SGD w/ ZeRO sharding)
- ``hetu_tpu.engine``   — Trainer, plan pool, strategy handling
- ``hetu_tpu.data``     — datasets, tokenizers, bucketing/packing
- ``hetu_tpu.utils``    — checkpoint, parallel-config (ds JSON), logging
"""

__version__ = "0.1.0"

# version-portability shims FIRST: later imports (and user code) may use
# jax.shard_map / lax.axis_size / lax.pvary on releases that predate them
from hetu_tpu.core import jax_compat as _jax_compat
_jax_compat.install()

from hetu_tpu.core.mesh import (
    MeshConfig,
    create_mesh,
    current_mesh,
    use_mesh,
    mesh_axis_size,
)
from hetu_tpu.core import dtypes
from hetu_tpu.core.symbol import IntSymbol
from hetu_tpu.dstates import (
    DistributedStates,
    CommType,
    deduce_comm,
    convert,
)
from hetu_tpu import nn
from hetu_tpu import ops
from hetu_tpu import optim

# Short aliases mirroring the reference API surface.
ds = DistributedStates
