"""Learnable embedding-table compression methods.

Rebuild of the reference's embedding memory compression suite (reference:
tools/EmbeddingMemoryCompression/methods/layers/{quantize,hash,compo,
tensortrain,deduplication}.py — the VLDB'24 benchmark of learnable vector
storage compression over the Hetu PS embedding line).  The reference
implements each method as a graph-op layer over its PS tables; here each is
a functional module over jax arrays, picked for TPU execution:

  * QuantizedEmbedding  — int8/int4 rows with blockwise absmax scales,
    dequantize-on-gather; fake-quant STE training (ALPT-style) optional.
  * HashEmbedding       — k independent hashes into one small table, rows
    summed (hash.py / the "hashing trick" family).
  * QREmbedding         — quotient-remainder compositional tables
    (compo.py): row = combine(Q[id // m], R[id % m]).
  * TTEmbedding         — tensor-train factorization (tensortrain.py):
    vocab = prod(v_i), dim = prod(d_i), row = einsum over 3 TT cores.
  * DedupEmbedding      — near-duplicate rows share storage via an
    indirection map (deduplication.py), built from a trained table.

Every module reports memory() bytes and compression vs the dense table, so
the PS/embedding-cache line (data/embedding_cache.py) can budget storage.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.nn import initializers as init
from hetu_tpu.ops.quantization import (dequantize_int4, dequantize_int8,
                                       quantize_int4, quantize_int8)


def _dense_bytes(vocab: int, dim: int, dtype_bytes: int = 4) -> int:
    return vocab * dim * dtype_bytes


# ---------------------------------------------------------------------------
# quantized rows (methods/layers/quantize.py, alpt.py)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class QuantizedEmbedding:
    """int8/int4 storage with one absmax scale per row block.

    `compress(table)` -> params; `lookup(params, ids)` GATHERS the rows'
    quantized blocks + scales first and dequantizes only the gathered
    slice, so compiled temporaries stay O(batch*dim) — never the dense
    (vocab, dim) table (the point of the method for multi-GB tables;
    reference: EmbeddingMemoryCompression/methods/layers/quantize.py
    dequantizes gathered rows).  Blocks are row-aligned: the effective
    block size is the largest divisor of embedding_dim <= block_size, so
    every row owns whole blocks and gathers cleanly.  `fake_quant` builds
    the straight-through estimator for quantization-aware training:
    fwd quantize->dequantize, bwd identity (ALPT's learned-scale variant
    degenerates to absmax here)."""
    num_embeddings: int
    embedding_dim: int
    bits: int = 8
    block_size: int = 64

    def __post_init__(self):
        if self.bits == 4 and self.embedding_dim % 2:
            raise ValueError(
                f"int4 packs two nibbles per byte: embedding_dim="
                f"{self.embedding_dim} must be even")
        bs = min(self.block_size, self.embedding_dim)
        while self.embedding_dim % bs or (self.bits == 4 and bs % 2):
            bs -= 1
        self._bs = bs

    def compress(self, table: jnp.ndarray):
        assert table.shape == (self.num_embeddings, self.embedding_dim)
        v, d, bs = self.num_embeddings, self.embedding_dim, self._bs
        nb = d // bs
        qfn = quantize_int8 if self.bits == 8 else quantize_int4
        q, scale = qfn(table, bs)       # row-aligned: flat blocks = v*nb
        # store per-row block structure so lookup can gather rows
        q = q.reshape((v, nb) + q.shape[1:])
        return {"q": q, "scale": scale.reshape(v, nb)}

    def lookup(self, params, ids: jnp.ndarray) -> jnp.ndarray:
        qr = jnp.take(params["q"], ids, axis=0)        # [..., nb, bs(/2)]
        sr = jnp.take(params["scale"], ids, axis=0)    # [..., nb]
        if self.bits == 8:
            vals = qr.astype(jnp.float32) * sr[..., None]
        else:
            lo = (qr & 0xF).astype(jnp.int32) - 8
            hi = ((qr >> 4) & 0xF).astype(jnp.int32) - 8
            nib = jnp.stack([lo, hi], axis=-1).reshape(qr.shape[:-1]
                                                       + (self._bs,))
            vals = nib.astype(jnp.float32) * sr[..., None]
        return vals.reshape(ids.shape + (self.embedding_dim,))

    def fake_quant(self, table: jnp.ndarray) -> jnp.ndarray:
        qfn = quantize_int8 if self.bits == 8 else quantize_int4
        dqfn = dequantize_int8 if self.bits == 8 else dequantize_int4

        @jax.custom_vjp
        def ste(t):
            q, s = qfn(t, self._bs)
            return dqfn(q, s, t.shape)

        ste.defvjp(lambda t: (ste(t), None), lambda _, g: (g,))
        return ste(table)

    def memory(self) -> int:
        n = self.num_embeddings * self.embedding_dim
        blocks = n // self._bs
        return n * self.bits // 8 + blocks * 4

    def compression(self) -> float:
        return _dense_bytes(self.num_embeddings, self.embedding_dim) \
            / self.memory()


# ---------------------------------------------------------------------------
# hashing trick (methods/layers/hash.py)
# ---------------------------------------------------------------------------

_HASH_PRIMES = (2654435761, 805459861, 3674653429, 2097192037)


@dataclasses.dataclass
class HashEmbedding:
    """k hash functions into one compressed table; gathered rows sum.

    Collisions are soft: two ids only fully collide when ALL k hashes
    agree, so quality degrades gracefully with compressed_rows."""
    num_embeddings: int
    embedding_dim: int
    compressed_rows: int
    num_hashes: int = 2

    def init(self, key) -> jnp.ndarray:
        return init.normal(0.02)(
            key, (self.compressed_rows, self.embedding_dim), jnp.float32)

    def _slots(self, ids: jnp.ndarray) -> jnp.ndarray:
        ids = ids.astype(jnp.uint32)
        slots = []
        for i in range(self.num_hashes):
            h = (ids * np.uint32(_HASH_PRIMES[i % len(_HASH_PRIMES)])
                 + np.uint32(i * 97)) % np.uint32(self.compressed_rows)
            slots.append(h.astype(jnp.int32))
        return jnp.stack(slots, axis=-1)            # [..., k]

    def lookup(self, table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
        rows = jnp.take(table, self._slots(ids), axis=0)   # [..., k, d]
        return jnp.sum(rows, axis=-2)

    def memory(self) -> int:
        return self.compressed_rows * self.embedding_dim * 4

    def compression(self) -> float:
        return _dense_bytes(self.num_embeddings, self.embedding_dim) \
            / self.memory()


# ---------------------------------------------------------------------------
# quotient-remainder compositional (methods/layers/compo.py)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class QREmbedding:
    """row(id) = combine(Q[id // m], R[id % m]); m ~ sqrt(vocab) stores
    O(2*sqrt(V)*d) instead of O(V*d).  combine: "mult" (the QR paper's
    recommended collision-free composition) | "add" | "concat"."""
    num_embeddings: int
    embedding_dim: int
    num_remainders: Optional[int] = None    # m; default ceil(sqrt(vocab))
    combine: str = "mult"

    def __post_init__(self):
        if self.num_remainders is None:
            self.num_remainders = int(np.ceil(np.sqrt(self.num_embeddings)))
        self.num_quotients = -(-self.num_embeddings // self.num_remainders)
        if self.combine not in ("mult", "add", "concat"):
            raise ValueError(f"combine must be mult|add|concat, got "
                             f"{self.combine!r}")

    def _dims(self) -> Tuple[int, int]:
        if self.combine == "concat":
            half = self.embedding_dim // 2
            return half, self.embedding_dim - half
        return self.embedding_dim, self.embedding_dim

    def init(self, key):
        k1, k2 = jax.random.split(key)
        dq, dr = self._dims()
        return {
            "quotient": init.normal(0.02)(k1, (self.num_quotients, dq),
                                          jnp.float32),
            "remainder": init.normal(0.02)(k2, (self.num_remainders, dr),
                                           jnp.float32),
        }

    def lookup(self, params, ids: jnp.ndarray) -> jnp.ndarray:
        q = jnp.take(params["quotient"], ids // self.num_remainders, axis=0)
        r = jnp.take(params["remainder"], ids % self.num_remainders, axis=0)
        if self.combine == "mult":
            return q * r
        if self.combine == "add":
            return q + r
        return jnp.concatenate([q, r], axis=-1)

    def memory(self) -> int:
        dq, dr = self._dims()
        return (self.num_quotients * dq + self.num_remainders * dr) * 4

    def compression(self) -> float:
        return _dense_bytes(self.num_embeddings, self.embedding_dim) \
            / self.memory()


# ---------------------------------------------------------------------------
# tensor-train (methods/layers/tensortrain.py)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TTEmbedding:
    """3-core tensor-train table: vocab <= v1*v2*v3, dim = d1*d2*d3,
    cores G1 [v1, 1, d1, r], G2 [v2, r, d2, r], G3 [v3, r, d3, 1];
    row(id) = G1[i1] x G2[i2] x G3[i3] contracted over the TT ranks —
    three gathers + two small einsums, MXU-friendly."""
    num_embeddings: int
    embedding_dim: int
    vocab_factors: Sequence[int]
    dim_factors: Sequence[int]
    rank: int = 8

    def __post_init__(self):
        assert len(self.vocab_factors) == 3 and len(self.dim_factors) == 3
        v1, v2, v3 = self.vocab_factors
        assert v1 * v2 * v3 >= self.num_embeddings, "vocab factors too small"
        d1, d2, d3 = self.dim_factors
        assert d1 * d2 * d3 == self.embedding_dim, "dim factors must multiply"

    def init(self, key):
        v1, v2, v3 = self.vocab_factors
        d1, d2, d3 = self.dim_factors
        r = self.rank
        k1, k2, k3 = jax.random.split(key, 3)
        # scale so the reconstructed rows start near N(0, 0.02)
        s = 0.02 ** (1.0 / 3.0)
        return {
            "g1": init.normal(s)(k1, (v1, 1, d1, r), jnp.float32),
            "g2": init.normal(s)(k2, (v2, r, d2, r), jnp.float32),
            "g3": init.normal(s)(k3, (v3, r, d3, 1), jnp.float32),
        }

    def lookup(self, params, ids: jnp.ndarray) -> jnp.ndarray:
        v1, v2, v3 = self.vocab_factors
        d1, d2, d3 = self.dim_factors
        i3 = ids % v3
        i2 = (ids // v3) % v2
        i1 = ids // (v3 * v2)
        g1 = jnp.take(params["g1"], i1, axis=0)   # [..., 1, d1, r]
        g2 = jnp.take(params["g2"], i2, axis=0)   # [..., r, d2, r]
        g3 = jnp.take(params["g3"], i3, axis=0)   # [..., r, d3, 1]
        x = jnp.einsum("...oar,...rbs->...abs", g1, g2)   # [..., d1, d2, r]
        x = jnp.einsum("...abs,...sco->...abc", x, g3)    # [..., d1, d2, d3]
        return x.reshape(x.shape[:-3] + (d1 * d2 * d3,))

    def memory(self) -> int:
        v1, v2, v3 = self.vocab_factors
        d1, d2, d3 = self.dim_factors
        r = self.rank
        return 4 * (v1 * d1 * r + v2 * r * d2 * r + v3 * r * d3)

    def compression(self) -> float:
        return _dense_bytes(self.num_embeddings, self.embedding_dim) \
            / self.memory()


# ---------------------------------------------------------------------------
# deduplication (methods/layers/deduplication.py)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DedupEmbedding:
    """Near-duplicate rows of a TRAINED table share storage: rows are
    grouped by rounded fingerprints, each group stores its centroid, and
    lookup is ids -> group -> centroid (two gathers)."""
    num_embeddings: int
    embedding_dim: int

    def compress(self, table: np.ndarray, atol: float = 1e-2):
        table = np.asarray(table, np.float32)
        finger = np.round(table / max(atol, 1e-8)).astype(np.int64)
        _, first_idx, inverse = np.unique(
            finger, axis=0, return_index=True, return_inverse=True)
        groups = len(first_idx)
        centroids = np.zeros((groups, self.embedding_dim), np.float32)
        counts = np.zeros((groups,), np.int64)
        np.add.at(centroids, inverse, table)
        np.add.at(counts, inverse, 1)
        centroids /= counts[:, None]
        return {"rows": jnp.asarray(centroids),
                "assign": jnp.asarray(inverse.astype(np.int32))}

    def lookup(self, params, ids: jnp.ndarray) -> jnp.ndarray:
        return jnp.take(params["rows"], jnp.take(params["assign"], ids),
                        axis=0)

    @staticmethod
    def memory_of(params) -> int:
        return int(params["rows"].size * 4 + params["assign"].size * 4)

    def compression_of(self, params) -> float:
        return _dense_bytes(self.num_embeddings, self.embedding_dim) \
            / self.memory_of(params)
