"""Budgeted embedding-compression scheduler.

Rebuild of the reference suite's scheduler layer (reference: tools/
EmbeddingMemoryCompression/methods/scheduler/ — per-method trainers driven
by a target compress_rate, multistage.py's stage-wise method switching,
compressor.py's compress/decompress contract).  The reference fixes ONE
method per run from the CLI; this scheduler closes the loop the suite
implies: given a SET of tables, a byte budget, and per-table access
frequencies (the LFU/LRU cache stats, data/embedding_cache.py stats()),
choose a per-table method mix and MIGRATE tables between methods at a
checkpoint boundary.

Planning: every table gets a quality ladder (dense -> int8 -> int4 -> QR
-> hash -> TT) with measured bytes (module.memory()) and a quality-loss
proxy per step.  Starting all-dense, the planner greedily takes the
downgrade step with the best bytes-saved per access-weighted quality-loss
until the mix fits the budget — hot tables keep richer methods, cold
tables absorb the compression.

Migration: quantized/dedup compress directly from the dense table; the
learned structures (hash/QR/TT) are fitted to reproduce the old table's
rows (a short Adam regression on sampled ids — the distillation analog of
the reference's stage-wise retraining).  Optimizer state for a migrated
table restarts, which is why migrations belong at checkpoint boundaries.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.nn.embedding_compression import (HashEmbedding, QREmbedding,
                                               QuantizedEmbedding,
                                               TTEmbedding)
from hetu_tpu.utils.logging import get_logger

logger = get_logger("compress_sched")


@dataclasses.dataclass(frozen=True)
class TableSpec:
    name: str
    num_embeddings: int
    embedding_dim: int
    # relative access frequency (e.g. hits/accesses share from the
    # LFU/LRU cache stats); hotter tables resist compression
    access_freq: float = 1.0


@dataclasses.dataclass(frozen=True)
class MethodChoice:
    method: str                 # dense|quantized8|quantized4|qr|hash|tt
    bytes: int
    quality_loss: float         # proxy in [0, 1): 0 = exact
    module: Optional[object]    # the compression module (None for dense)


def _factor3(n: int) -> Tuple[int, int, int]:
    """n = a*b*c with factors as close as possible (TT dim factors)."""
    best = (1, 1, n)
    for a in range(1, int(round(n ** (1 / 3))) + 2):
        if n % a:
            continue
        m = n // a
        for b in range(a, int(np.sqrt(m)) + 1):
            if m % b == 0:
                best = (a, b, m // b)
    return best


def method_ladder(t: TableSpec) -> List[MethodChoice]:
    """This table's quality ladder, best-first, strictly shrinking bytes.
    Quality-loss proxies are coarse by design (the reference calibrates
    per-method AUC drops experimentally; these only need the right ORDER
    for the greedy trade to be sensible)."""
    v, d = t.num_embeddings, t.embedding_dim
    out = [MethodChoice("dense", v * d * 4, 0.0, None)]

    q8 = QuantizedEmbedding(v, d, bits=8, block_size=min(64, d))
    out.append(MethodChoice("quantized8", q8.memory(), 0.01, q8))
    if d % 2 == 0:
        q4 = QuantizedEmbedding(v, d, bits=4, block_size=min(64, d))
        out.append(MethodChoice("quantized4", q4.memory(), 0.05, q4))
    qr = QREmbedding(v, d)
    out.append(MethodChoice("qr", qr.memory(), 0.25, qr))
    h = HashEmbedding(v, d, compressed_rows=max(v // 16, 8))
    out.append(MethodChoice("hash", h.memory(), 0.35, h))
    d3 = _factor3(d)
    if d3[0] > 1 or d > 8:
        v3 = int(np.ceil(v ** (1 / 3)))
        tt = TTEmbedding(v, d, vocab_factors=(v3, v3, v3), dim_factors=d3,
                         rank=4)
        out.append(MethodChoice("tt", tt.memory(), 0.45, tt))
    # keep only strictly-shrinking steps (a "compressed" method larger
    # than its predecessor is useless for this table's shape)
    ladder = [out[0]]
    for c in out[1:]:
        if c.bytes < ladder[-1].bytes:
            ladder.append(c)
    return ladder


def plan_methods(tables: Sequence[TableSpec],
                 budget_bytes: float) -> Dict[str, MethodChoice]:
    """Greedy budgeted assignment: downgrade the (table, step) with the
    best bytes-saved per access-weighted quality-loss until under
    budget.  Raises if even the smallest mix exceeds the budget."""
    ladders = {t.name: method_ladder(t) for t in tables}
    freq = {t.name: max(t.access_freq, 1e-9) for t in tables}
    level = {t.name: 0 for t in tables}
    total = sum(ladders[n][0].bytes for n in level)
    while total > budget_bytes:
        best_name, best_ratio = None, -1.0
        for n, lv in level.items():
            if lv + 1 >= len(ladders[n]):
                continue
            cur, nxt = ladders[n][lv], ladders[n][lv + 1]
            saved = cur.bytes - nxt.bytes
            cost = (nxt.quality_loss - cur.quality_loss) * freq[n]
            ratio = saved / max(cost, 1e-12)
            if ratio > best_ratio:
                best_name, best_ratio = n, ratio
        if best_name is None:
            raise ValueError(
                f"budget {budget_bytes / 1e6:.2f}MB infeasible: the most "
                f"compressed mix still needs {total / 1e6:.2f}MB")
        total -= (ladders[best_name][level[best_name]].bytes
                  - ladders[best_name][level[best_name] + 1].bytes)
        level[best_name] += 1
    return {n: ladders[n][lv] for n, lv in level.items()}


def freqs_from_cache_stats(stats: Dict[str, Dict]) -> Dict[str, float]:
    """Per-table access share out of LFU/LRU cache stats()
    ({table: {"accesses": N, ...}}) — the planner's access_freq input."""
    tot = sum(max(s.get("accesses", 0), 0) for s in stats.values()) or 1
    return {n: max(s.get("accesses", 0), 1e-9) / tot
            for n, s in stats.items()}


# ---------------------------------------------------------------------------
# migration
# ---------------------------------------------------------------------------

def _fit_structure(module, target: jnp.ndarray, key,
                   steps: int = 300, lr: float = 0.05,
                   sample: int = 4096):
    """Fit a learned structure (hash/QR/TT) to reproduce `target` rows —
    the migration analog of the reference's stage-wise retraining."""
    import optax

    v = target.shape[0]
    params = module.init(key)
    opt = optax.adam(lr)
    state = opt.init(params)

    def loss_fn(p, ids):
        return jnp.mean((module.lookup(p, ids) - target[ids]) ** 2)

    @jax.jit
    def step(p, s, ids):
        g = jax.grad(loss_fn)(p, ids)
        up, s = opt.update(g, s)
        return jax.tree.map(lambda a, u: a + u, p, up), s

    rng = np.random.default_rng(0)
    for _ in range(steps):
        ids = jnp.asarray(rng.integers(0, v, size=min(sample, v)))
        params, state = step(params, state, ids)
    return params


def compress_table(choice: MethodChoice, dense: jnp.ndarray, key):
    """dense [v, d] -> params for the chosen method."""
    if choice.method == "dense":
        return dense
    if choice.method.startswith("quantized"):
        return choice.module.compress(dense)
    return _fit_structure(choice.module, dense, key)


def reconstruct_table(choice: MethodChoice, params,
                      num_embeddings: int) -> jnp.ndarray:
    """params -> approximate dense [v, d] (migration source)."""
    if choice.method == "dense":
        return params
    ids = jnp.arange(num_embeddings)
    return choice.module.lookup(params, ids)


class ScheduledEmbeddings:
    """A set of embedding tables living under a byte budget.

    init(key) builds per-table params for the planned mix; lookup(name,
    params, ids) dispatches to the table's method; replan(...) re-runs the
    planner (new budget and/or fresh cache stats) and MIGRATES any table
    whose method changed — reconstruct from the old method, compress into
    the new — returning the migration list.  Learned-structure params
    (dense/hash/QR/TT) take gradients; quantized storage is frozen
    (stop_gradient) — requantization training is the fake_quant STE path.
    """

    def __init__(self, tables: Sequence[TableSpec], budget_bytes: float):
        self.tables = {t.name: t for t in tables}
        self.budget_bytes = budget_bytes
        self.plan = plan_methods(tables, budget_bytes)

    def init(self, key) -> Dict[str, object]:
        params = {}
        for i, (name, t) in enumerate(sorted(self.tables.items())):
            k = jax.random.fold_in(key, i)
            choice = self.plan[name]
            if choice.method == "dense":
                params[name] = jax.random.normal(
                    k, (t.num_embeddings, t.embedding_dim)) * 0.02
            elif choice.method.startswith("quantized"):
                dense = jax.random.normal(
                    k, (t.num_embeddings, t.embedding_dim)) * 0.02
                params[name] = choice.module.compress(dense)
            else:
                params[name] = choice.module.init(k)
        return params

    def lookup(self, name: str, params, ids: jnp.ndarray) -> jnp.ndarray:
        choice = self.plan[name]
        if choice.method == "dense":
            return jnp.take(params[name], ids, axis=0)
        if choice.method.startswith("quantized"):
            return choice.module.lookup(
                jax.tree.map(jax.lax.stop_gradient, params[name]), ids)
        return choice.module.lookup(params[name], ids)

    def memory(self) -> int:
        return sum(c.bytes for c in self.plan.values())

    def describe(self) -> Dict[str, str]:
        return {n: c.method for n, c in self.plan.items()}

    def replan(self, params: Dict[str, object],
               budget_bytes: Optional[float] = None,
               access_freqs: Optional[Dict[str, float]] = None,
               key=None) -> Tuple[Dict[str, object], List[Dict]]:
        """Checkpoint-boundary re-plan + migration.  Returns (new_params,
        migrations); a migrated table's optimizer state must restart."""
        if budget_bytes is not None:
            self.budget_bytes = budget_bytes
        if access_freqs:
            self.tables = {
                n: dataclasses.replace(t, access_freq=access_freqs.get(
                    n, t.access_freq))
                for n, t in self.tables.items()}
        key = key if key is not None else jax.random.key(0)
        new_plan = plan_methods(list(self.tables.values()),
                                self.budget_bytes)
        migrations: List[Dict] = []
        new_params = dict(params)
        for i, (name, t) in enumerate(sorted(self.tables.items())):
            old, new = self.plan[name], new_plan[name]
            if old.method == new.method:
                continue
            dense = reconstruct_table(old, params[name], t.num_embeddings)
            new_params[name] = compress_table(
                new, dense, jax.random.fold_in(key, 1000 + i))
            migrations.append({"table": name, "from": old.method,
                               "to": new.method,
                               "bytes": (old.bytes, new.bytes)})
            logger.info(f"migrated {name}: {old.method} -> {new.method} "
                        f"({old.bytes / 1e6:.2f}MB -> "
                        f"{new.bytes / 1e6:.2f}MB)")
        self.plan = new_plan
        return new_params, migrations
