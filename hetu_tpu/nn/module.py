"""Functional Module system.

The reference exposes a torch-like stateful `hetu.nn.Module`
(reference: python/hetu/nn/modules/module.py) whose parameters are graph
variables.  On TPU the idiomatic form is functional: a Module instance is a
*static description* (architecture + parameter specs + layouts) and parameters
live in a pytree threaded through jit-compiled functions.  The API keeps the
torch-ish construction style (attribute assignment auto-registers children,
`ModuleList`, `Sequential`) while init/apply are pure:

    model = Linear(4, 8)
    params = model.init(jax.random.key(0))       # pytree of arrays
    y = model.apply(params, x)                   # == model(params, x)

Parameter layouts are `DistributedStates`; `model.shardings(mesh)` yields the
matching NamedSharding pytree, and `model.init(key, mesh=mesh)` materializes
parameters already sharded (via jit out_shardings), so trillion-parameter
models never fully exist on one host.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu.dstates import DistributedStates


@dataclasses.dataclass
class ParamSpec:
    """Declaration of one parameter (shape/dtype/init/distributed layout)."""

    shape: Tuple[int, ...]
    dtype: Any
    init: Callable[[jax.Array, Tuple[int, ...], Any], jax.Array]
    ds: Optional[DistributedStates] = None

    def abstract(self) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(self.shape, self.dtype)


class Module:
    """Base module. Subclasses declare params/children in __init__ and
    implement `forward(self, params, *args, **kwargs)`."""

    def __init__(self):
        object.__setattr__(self, "_params", {})
        object.__setattr__(self, "_children", {})

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Module):
            self._children[name] = value
        object.__setattr__(self, name, value)

    def param(self, name: str, shape: Tuple[int, ...], init: Callable,
              dtype=jnp.float32, ds: Optional[DistributedStates] = None) -> str:
        """Declare a parameter; returns its key into the params pytree."""
        self._params[name] = ParamSpec(tuple(int(s) for s in shape), dtype, init, ds)
        return name

    def add_module(self, name: str, module: "Module") -> "Module":
        self._children[name] = module
        object.__setattr__(self, name, module)
        return module

    # -- traversal ----------------------------------------------------------
    def param_specs(self) -> Dict[str, Any]:
        """Nested dict of ParamSpec mirroring the params pytree."""
        out: Dict[str, Any] = dict(self._params)
        for cname, child in self._children.items():
            sub = child.param_specs()
            if sub:
                out[cname] = sub
        return out

    def named_modules(self, prefix: str = ""):
        yield prefix or "", self
        for cname, child in self._children.items():
            yield from child.named_modules(f"{prefix}.{cname}" if prefix else cname)

    # -- init / shardings ---------------------------------------------------
    def abstract_params(self):
        return jax.tree.map(
            lambda spec: spec.abstract(), self.param_specs(),
            is_leaf=lambda s: isinstance(s, ParamSpec))

    def shardings(self, mesh):
        """NamedSharding pytree for all params (replicated when no ds).
        Axes that do not divide a dim are dropped (e.g. FSDP on an odd-sized
        norm weight) — sharding is an optimization, never a correctness
        requirement here."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(spec: ParamSpec):
            if spec.ds is None:
                return NamedSharding(mesh, P())
            ds = spec.ds
            for d, axes in enumerate(ds.spec):
                if not axes:
                    continue
                size = 1
                for a in axes:
                    size *= int(mesh.shape.get(a, 1))
                if spec.shape[d] % size:
                    ds = ds.without_split(d)
            return ds.named_sharding(mesh)

        return jax.tree.map(one, self.param_specs(),
                            is_leaf=lambda s: isinstance(s, ParamSpec))

    def partition_specs(self):
        from jax.sharding import PartitionSpec as P

        def one(spec: ParamSpec):
            return spec.ds.partition_spec() if spec.ds is not None else P()

        return jax.tree.map(one, self.param_specs(),
                            is_leaf=lambda s: isinstance(s, ParamSpec))

    def init(self, key: jax.Array, mesh=None):
        """Materialize parameters. With a mesh, init runs under jit with
        sharded outputs so each device only materializes its shard
        (the analog of reference ParallelVariableOp local init,
        reference: hetu/graph/ops/variable.cc)."""
        specs = self.param_specs()
        leaves, treedef = jax.tree.flatten(
            specs, is_leaf=lambda s: isinstance(s, ParamSpec))

        def build(key):
            keys = jax.random.split(key, len(leaves))
            return treedef.unflatten([
                spec.init(k, spec.shape, spec.dtype)
                for k, spec in zip(keys, leaves)
            ])

        if mesh is None:
            return build(key)
        shardings = self.shardings(mesh)
        with mesh:
            return jax.jit(build, out_shardings=shardings)(key)

    def num_params(self) -> int:
        total = 0
        for leaf in jax.tree.leaves(self.param_specs(),
                                    is_leaf=lambda s: isinstance(s, ParamSpec)):
            n = 1
            for s in leaf.shape:
                n *= s
            total += n
        return total

    # -- forward ------------------------------------------------------------
    def forward(self, params, *args, **kwargs):
        raise NotImplementedError

    def apply(self, params, *args, **kwargs):
        return self.forward(params, *args, **kwargs)

    def __call__(self, params, *args, **kwargs):
        return self.forward(params, *args, **kwargs)


def stacked_spec(spec: ParamSpec, num: int,
                 lead_axis: Optional[str] = None) -> ParamSpec:
    """Lift a ParamSpec to a stack of `num` independent copies with a leading
    layer dim — used by scan-over-layers decoder stacks.  Init vmaps the base
    initializer over per-layer keys.  `lead_axis` shards the layer dim (the
    pipeline-stage placement: each pp rank holds its own layer slice)."""
    base_init = spec.init

    def init(key, shape, dtype):
        keys = jax.random.split(key, shape[0])
        return jax.vmap(lambda k: base_init(k, shape[1:], dtype))(keys)

    lead = ((lead_axis,) if lead_axis else (),)
    if spec.ds is not None:
        ds = spec.ds.shifted(1, lead=lead)
    elif lead_axis:
        from hetu_tpu.dstates import DistributedStates
        ds = DistributedStates.make(len(spec.shape) + 1, {0: lead_axis})
    else:
        ds = None
    return ParamSpec((num,) + spec.shape, spec.dtype, init, ds)


def stack_param_specs(specs, num: int, lead_axis: Optional[str] = None):
    """Map stacked_spec over a nested spec dict."""
    return jax.tree.map(lambda s: stacked_spec(s, num, lead_axis), specs,
                        is_leaf=lambda s: isinstance(s, ParamSpec))


class ModuleList(Module):
    def __init__(self, modules: Optional[List[Module]] = None):
        super().__init__()
        self._list: List[Module] = []
        for m in modules or []:
            self.append(m)

    def append(self, module: Module):
        name = str(len(self._list))
        self._list.append(module)
        self._children[name] = module
        return self

    def __iter__(self):
        return iter(self._list)

    def __len__(self):
        return len(self._list)

    def __getitem__(self, i):
        return self._list[i]

    def items(self):
        return [(str(i), m) for i, m in enumerate(self._list)]


class Sequential(ModuleList):
    def forward(self, params, x, **kwargs):
        for name, m in self.items():
            # param-less children (activations, pooling) have no subtree
            x = m(params.get(name, {}), x, **kwargs)
        return x


class ModuleDict(Module):
    def __init__(self, modules: Optional[Dict[str, Module]] = None):
        super().__init__()
        for k, v in (modules or {}).items():
            self.add_module(k, v)

    def __getitem__(self, k):
        return self._children[k]

    def items(self):
        return self._children.items()
