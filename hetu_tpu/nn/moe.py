"""Mixture-of-Experts with expert parallelism.

Rebuild of the reference MoE (reference: hetu/v1/python/hetu/layers/
moe_layer.py + gates Top/KTop1/Hash/Balance + Dispatch.py and hierarchical
all-to-all HAllToAll.py — v1-only features per SURVEY.md §2.4 EP row).

TPU-first design (GShard/Switch style):
- experts are ONE stacked parameter [E, ...] sharded over the `ep` mesh axis.
- the DEFAULT dispatch is sort-based with O(T·k) index tensors: (token, slot)
  pairs are argsorted by expert, position-in-expert comes from an exclusive
  count prefix, and tokens scatter-add into the per-expert capacity buffers.
  No [T, E, C] one-hot masks are ever materialized (at gbs·seq ≈ 1M tokens
  and E=64 those are tens of GB), so MoE scales to the reference's
  benchmark sizes.  dispatch="dense" keeps the einsum-against-one-hot path
  for parity tests and tiny ablations.
- routing is computed PER DATA SHARD (the [G, Tg, h] group dim is laid out
  over dp×cp): each shard's position-in-expert prefix only scans its own
  tokens, so dispatch never serializes across data shards (GShard's
  per-group capacity semantics).  GSPMD lowers the group->expert buffer
  movement to all-to-all over ep (the reference's explicit HAllToAll becomes
  compiler-inserted; mesh axis order already makes it hierarchical: ICI
  within a slice, DCN across).
- gates: "topk" (GShard, default), "top1" (Switch), "ktop1" (k sequential
  top-1 picks with renormalized leftovers — reference KTop1Gate),
  "balance" (Sinkhorn-balanced assignment — reference BalanceAssignmentGate
  / BASE-style), "hash" (token_id % E).  All share the Switch load-balance
  aux loss + router z-loss.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu import ops
from hetu_tpu.dstates import DistributedStates as DS
from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module
from hetu_tpu.parallel.strategy import ParallelStrategy

GATES = ("topk", "top1", "ktop1", "balance", "hash", "sam")


@dataclasses.dataclass
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_z_loss_coef: float = 1e-3
    load_balance_coef: float = 1e-2
    gate: str = "topk"      # one of GATES
    dispatch: str = "sort"  # "sort" (O(T·k) indices) | "dense" ([T,E,C] masks)
    sinkhorn_iters: int = 4  # balance gate only
    # SAM gate (reference: v1 layers/SAMGate.py — locality-aware routing):
    # experts are grouped (one group per host/ICI neighborhood); all k picks
    # land in the token's best group so the dispatch all-to-all stays local.
    # 0 = auto (largest divisor of num_experts <= 8, the reference's
    # num_local_gpus default)
    sam_group_size: int = 0
    # weight of the SAM group-alignment hinge loss, separate from the
    # load-balance coefficient (reference: SAMGate.py keeps distinct
    # balance_loss/alignment_loss weights); None = follow load_balance_coef
    sam_alignment_coef: float | None = None

    def resolved_sam_alignment_coef(self) -> float:
        return (self.load_balance_coef if self.sam_alignment_coef is None
                else self.sam_alignment_coef)

    def resolved_sam_group_size(self) -> int:
        """Experts per SAM locality group (NOT the group count — that is
        num_experts // this).  Validates divisibility and that top_k fits
        inside one group (SAM picks all k experts from a single group)."""
        gs = self.sam_group_size
        if gs == 0:
            gs = next(g for g in range(min(8, self.num_experts), 0, -1)
                      if self.num_experts % g == 0)
        if self.num_experts % gs:
            raise ValueError(f"sam_group_size {gs} must divide "
                             f"num_experts {self.num_experts}")
        if max(self.top_k, 1) > gs:
            raise ValueError(
                f"sam gate needs top_k ({self.top_k}) <= group size ({gs}):"
                " all k picks come from one group")
        return gs


def _router_probs(logits):
    return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)


def _sinkhorn(logits, iters: int):
    """Sinkhorn normalization toward a doubly-'stochastic' plan: rows sum to
    1, columns to T/E — the balanced-assignment relaxation the reference's
    BalanceAssignmentGate solves with an LP."""
    log_p = jax.nn.log_softmax(logits, axis=-1)
    T, E = logits.shape
    log_col_target = jnp.log(jnp.asarray(T / E, jnp.float32))
    for _ in range(iters):
        log_p = log_p - jax.nn.logsumexp(log_p, axis=0, keepdims=True) \
            + log_col_target
        log_p = log_p - jax.nn.logsumexp(log_p, axis=1, keepdims=True)
    return jnp.exp(log_p)


def select_experts(logits, ids, moe: MoEConfig
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Gate selection: logits [T, E] -> (expert_idx [T, k], gate_vals [T, k]).

    Shared by the sort and dense dispatchers so they route identically."""
    T, E = logits.shape
    probs = _router_probs(logits)

    if moe.gate == "hash":
        expert_idx = (ids % E)[:, None]
        gate_vals = jnp.ones((T, 1), jnp.float32)
    elif moe.gate == "top1":
        # Switch: scale by the RAW router prob (the gate gradient signal)
        gate_vals, expert_idx = jax.lax.top_k(probs, 1)
    elif moe.gate == "ktop1":
        # k sequential top-1 picks; each pick's gate is its probability
        # renormalized over the experts still available (reference KTop1Gate)
        picks, gates = [], []
        remaining = probs
        for _ in range(max(moe.top_k, 1)):
            g, e = jax.lax.top_k(remaining, 1)
            denom = jnp.sum(remaining, axis=-1, keepdims=True)
            gates.append(g / jnp.maximum(denom, 1e-9))
            picks.append(e)
            remaining = remaining * (1.0 - jax.nn.one_hot(e[:, 0], E))
        expert_idx = jnp.concatenate(picks, axis=1)
        gate_vals = jnp.concatenate(gates, axis=1)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    elif moe.gate == "balance":
        plan = _sinkhorn(logits.astype(jnp.float32), moe.sinkhorn_iters)
        _, expert_idx = jax.lax.top_k(plan, max(moe.top_k, 1))
        gate_vals = jnp.take_along_axis(probs, expert_idx, axis=1)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    elif moe.gate == "sam":
        # SAM (reference: SAMGate.py samgating): pick the single best GROUP
        # by total gate mass, then top-k experts WITHIN that group — all of
        # a token's experts share one locality domain.  Gate values are the
        # raw probs of the picks (the reference does not renormalize).
        gs = moe.resolved_sam_group_size()
        G = E // gs
        k = max(moe.top_k, 1)
        grouped = probs.reshape(T, G, gs)
        top1_group = jnp.argmax(jnp.sum(grouped, axis=-1), axis=-1)  # [T]
        group_probs = jnp.take_along_axis(
            grouped, top1_group[:, None, None], axis=1)[:, 0]        # [T, gs]
        gate_vals, local_idx = jax.lax.top_k(group_probs, k)
        expert_idx = top1_group[:, None] * gs + local_idx
    else:  # topk (GShard)
        gate_vals, expert_idx = jax.lax.top_k(probs, moe.top_k)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    return expert_idx, gate_vals


def aux_losses(logits, expert_idx, moe: MoEConfig):
    """Switch load-balance loss + router z-loss."""
    E = logits.shape[-1]
    probs = _router_probs(logits)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32),
                  axis=0)
    load_balance = E * jnp.sum(me * ce)
    z = jnp.mean(jnp.square(jax.nn.logsumexp(logits.astype(jnp.float32),
                                             axis=-1)))
    aux = (moe.load_balance_coef * load_balance
           + moe.router_z_loss_coef * z)
    if moe.gate == "sam":
        # alignment loss (reference: SamMax.cu — hinge on every expert
        # OUTSIDE the chosen group whose gate exceeds the weakest chosen
        # in-group expert): pushes gate mass INTO one locality group
        gs = moe.resolved_sam_group_size()
        T = logits.shape[0]
        chosen = jnp.take_along_axis(probs, expert_idx, axis=1)
        tmp = jnp.min(chosen, axis=-1, keepdims=True)       # k-th pick
        group_of = expert_idx[:, :1] // gs                  # [T, 1]
        outside = (jnp.arange(E)[None, :] // gs) != group_of
        hinge = jnp.where(outside, jnp.maximum(probs - tmp, 0.0), 0.0)
        aux = aux + moe.resolved_sam_alignment_coef() * jnp.sum(hinge) / T
    return aux


def _numerics_active() -> bool:
    """Is a numerics collector installed (host-level check, static
    during one trace)?  Lazy import keeps nn free of obs at load."""
    from hetu_tpu.obs import numerics
    return numerics.active()


def _router_stats(logits, load_counts, dropped):
    """Router-health stats for the numerics observatory: per-expert load
    (fraction of TOKENS carrying each expert — sums to ~k, so a
    collapsed router reads load_max -> 1.0 whatever k is), its max,
    mean token routing entropy (nats), and capacity drops.
    ``load_counts``: [E] int assignment counts; ``dropped``: scalar
    int.  Only traced when a collector is active."""
    probs = _router_probs(logits)
    tokens = jnp.asarray(float(max(logits.shape[0], 1)), jnp.float32)
    load = load_counts.astype(jnp.float32) / tokens
    pairs = jnp.maximum(jnp.sum(load_counts).astype(jnp.float32), 1.0)
    entropy = jnp.mean(
        -jnp.sum(probs * jnp.log(probs + 1e-9), axis=-1))
    return {"load": load, "load_max": jnp.max(load), "entropy": entropy,
            "dropped": dropped.astype(jnp.float32),
            "drop_frac": dropped.astype(jnp.float32) / pairs}


def sort_routing(expert_idx, gate_vals, num_experts: int, capacity: int):
    """Sort-based routing plan with O(T·k) index tensors.

    (token, slot) pairs are flattened SLOT-major (all slot-0 picks first, in
    token order) so drop priority matches the dense path's sequential-slot
    semantics, stably argsorted by expert, and positioned via an exclusive
    per-expert count prefix.  Returns dict of [T*k] arrays:
      dest: flat index into [E*C] buffers (E*C = trash for dropped entries)
      tok:  source token index
      gate: combine weight
      keep: survived capacity
    plus the routing-plan telemetry (the live expert-load/capacity-drop
    surface ROADMAP item 1 names — free here, the counts already exist):
      load:    [E] int32 routed (pre-drop) assignments per expert
      dropped: scalar int32 count of capacity-dropped (token, slot) pairs
    """
    T, k = expert_idx.shape
    TK = T * k
    e_flat = expert_idx.T.reshape(TK)       # slot-major
    g_flat = gate_vals.T.reshape(TK)
    order = jnp.argsort(e_flat, stable=True)
    e_s = e_flat[order]
    counts = jnp.zeros((num_experts,), jnp.int32).at[e_flat].add(1)
    starts = jnp.cumsum(counts) - counts    # exclusive prefix
    pos = jnp.arange(TK, dtype=jnp.int32) - starts[e_s]
    keep = pos < capacity
    dest = jnp.where(keep, e_s * capacity + pos, num_experts * capacity)
    tok = order % T                         # slot-major: f = slot*T + t
    return {"dest": dest, "tok": tok, "gate": g_flat[order], "keep": keep,
            "load": counts,
            "dropped": TK - jnp.sum(keep.astype(jnp.int32))}


def scatter_to_experts(xt, plan, num_experts: int, capacity: int):
    """xt [T, h] --scatter-add--> [E, C, h].  Dropped entries land in (and
    are discarded with) a trash row, so they contribute exactly-zero output
    and gradient."""
    T, h = xt.shape
    E, C = num_experts, capacity
    buf = jnp.zeros((E * C + 1, h), xt.dtype)
    buf = buf.at[plan["dest"]].add(xt[plan["tok"]])
    return buf[: E * C].reshape(E, C, h)


def gather_from_experts(out_ec, plan, num_tokens: int):
    """[E, C, h'] --gate-weighted gather--> [T, h'] (dropped entries gather
    through a clamped index but are zeroed by the keep mask)."""
    E, C, h = out_ec.shape
    out_flat = out_ec.reshape(E * C, h)
    safe = jnp.minimum(plan["dest"], E * C - 1)
    w = (plan["keep"] * plan["gate"]).astype(out_flat.dtype)
    contrib = out_flat[safe] * w[:, None]
    y = jnp.zeros((num_tokens, h), out_flat.dtype)
    return y.at[plan["tok"]].add(contrib)


def sort_dispatch_combine(xt, plan, expert_fn, num_experts: int,
                          capacity: int):
    """xt [T, h] --scatter--> [E, C, h] --expert_fn--> [E, C, h'] --gather-->
    [T, h']."""
    out = expert_fn(scatter_to_experts(xt, plan, num_experts, capacity))
    return gather_from_experts(out, plan, xt.shape[0])


def topk_routing(logits, ids, moe: MoEConfig, capacity: int):
    """DENSE routing (parity/ablation path): returns (dispatch [T, E, C]
    bool, combine [T, E, C] f32, aux_loss, dropped) where ``dropped`` is
    the scalar int32 count of capacity-dropped (token, slot) pairs —
    the same accounting ``sort_routing`` carries in its plan.  Memory
    O(T·E·C) — use dispatch="sort" beyond toy sizes.

    Single cumsum-based construction (no per-slot Python loop): the
    (token, slot) pairs flatten SLOT-MAJOR — all slot-0 picks in token
    order, then slot-1 — exactly ``sort_routing``'s drop priority, so
    position-in-expert is one exclusive cumsum of the one-hot pair
    matrix and the [T, E, C] masks assemble from one einsum over the
    pair dim (the routing-parity regression test pins the plans
    identical to the sort path's)."""
    T, E = logits.shape
    expert_idx, gate_vals = select_experts(logits, ids, moe)
    k = expert_idx.shape[1]
    TK = T * k

    e_flat = expert_idx.T.reshape(TK)           # slot-major pair order
    g_flat = gate_vals.T.reshape(TK)
    onehot_e = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)       # [TK, E]
    pos_in_e = jnp.cumsum(onehot_e, axis=0) - onehot_e          # exclusive
    pos = jnp.take_along_axis(pos_in_e, e_flat[:, None], axis=1)[:, 0]
    keep = pos < capacity
    pos_c = jnp.clip(pos, 0, capacity - 1)
    w = jnp.where(keep, 1.0, 0.0)
    pair = (jax.nn.one_hot(e_flat, E, dtype=jnp.float32) * w[:, None],
            jax.nn.one_hot(pos_c, capacity, dtype=jnp.float32))
    # [TK, E] x [TK, C] -> [TK, E, C], folded back to tokens slot-major
    combine_f = jnp.einsum("se,sc->sec", pair[0] * g_flat[:, None], pair[1])
    combine = combine_f.reshape(k, T, E, capacity).sum(axis=0)
    disp_f = jnp.einsum("se,sc->sec", pair[0], pair[1])
    dispatch = disp_f.reshape(k, T, E, capacity).sum(axis=0) > 0

    dropped = TK - jnp.sum(keep.astype(jnp.int32))
    return dispatch, combine, aux_losses(logits, expert_idx, moe), dropped


class MoELayer(Module):
    """Sparse SwiGLU FFN: router + E experts, expert dim sharded over ep
    (reference: v1 moe_layer.py MoELayer; dense path = LlamaMLP)."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 moe: MoEConfig, strategy: ParallelStrategy,
                 param_dtype=jnp.float32, initializer_range: float = 0.02):
        super().__init__()
        if moe.gate not in GATES:
            raise ValueError(f"gate={moe.gate!r} not in {GATES}")
        if moe.dispatch not in ("sort", "dense"):
            raise ValueError(f"dispatch={moe.dispatch!r}")
        self.moe, self.strategy = moe, strategy
        self.hidden, self.inter = hidden_size, intermediate_size
        E = moe.num_experts
        if E % max(strategy.ep, 1):
            raise ValueError(f"num_experts={E} must divide by ep={strategy.ep}")
        ep_ds = DS.make(4, {0: "ep", 3: "tp"}) if strategy.ep > 1 or strategy.tp > 1 else None
        dn_ds = DS.make(3, {0: "ep", 1: "tp"}) if strategy.ep > 1 or strategy.tp > 1 else None
        self.param("router", (hidden_size, E), init.normal(initializer_range),
                   dtype=jnp.float32)
        self.param("w_gate_up", (E, hidden_size, 2, intermediate_size),
                   init.normal(initializer_range), dtype=param_dtype, ds=ep_ds)
        self.param("w_down", (E, intermediate_size, hidden_size),
                   init.normal(initializer_range), dtype=param_dtype, ds=dn_ds)

    # -- expert compute (shared by both dispatchers) ------------------------
    def _experts(self, params, buf):
        """buf [..., E, C, h] -> [..., E, C, h] (leading dims broadcast)."""
        x = buf
        gu = jnp.einsum("...ecd,edki->...ecki", x,
                        params["w_gate_up"].astype(x.dtype))
        hidden = ops.swiglu(gu[..., 0, :], gu[..., 1, :])
        return jnp.einsum("...eci,eih->...ech", hidden,
                          params["w_down"].astype(x.dtype))

    def _group_dims(self, b: int, s: int) -> Tuple[int, int]:
        """(db, cs) — how many shards the batch/seq dims split into for
        shard-local routing; 1 when the dim does not divide evenly (falls
        back to one global group, still correct just not shard-local)."""
        st = self.strategy
        db = st.dp if st.dp > 1 and b % st.dp == 0 else 1
        cs = st.cp if st.cp > 1 and s % st.cp == 0 else 1
        return db, cs

    def forward(self, params, x, *, token_ids: Optional[jnp.ndarray] = None):
        """x: [b, s, h] -> ([b, s, h], aux_loss)."""
        moe, st = self.moe, self.strategy
        b, s, h = x.shape
        E = moe.num_experts

        if moe.dispatch == "dense":
            return self._forward_dense(params, x, token_ids)

        # ---- grouped sort dispatch: G = dp*cp data shards route locally ----
        db, cs = self._group_dims(b, s)
        G = db * cs
        Tg = (b // db) * (s // cs)
        capacity = int(moe.capacity_factor * Tg * max(moe.top_k, 1) / E)
        capacity = max(8, min(Tg, -(-capacity // 8) * 8))  # mult of 8

        # [b, s, h] -> [G, Tg, h], group dim laid out over (dp, cp) so the
        # regroup is data-movement-free under the activation sharding
        xg = x.reshape(db, b // db, cs, s // cs, h)
        xg = xg.transpose(0, 2, 1, 3, 4).reshape(G, Tg, h)
        if token_ids is None:
            # hash-gate default ids are the GLOBAL flat index (the dense
            # path's convention) — group-local arange would re-route tokens
            token_ids = jnp.arange(b * s, dtype=jnp.int32).reshape(b, s)
        ig = token_ids.reshape(db, b // db, cs, s // cs)
        ig = ig.transpose(0, 2, 1, 3).reshape(G, Tg)
        group_axes = tuple(a for a, n in (("dp", db), ("cp", cs)) if n > 1)
        if group_axes:
            xg = DS.make(3, {0: group_axes}).constrain(xg)

        # explicit expert-parallel dispatch (HETU_TPU_MOE_DISPATCH,
        # nn/moe_dispatch.py): same routing plan, transport through a
        # shard_map over ep (quantized a2a + all-gather, hierarchical
        # under a two-level topology).  "gspmd" — the unset default —
        # takes the constraint-based path below, byte-identical to the
        # flag not existing (registered identity contract).
        from hetu_tpu.nn import moe_dispatch as _md
        if _md.resolved_mode(st) != "gspmd":
            yg, aux = _md.explicit_forward(self, params, xg, ig,
                                           capacity, group_axes, Tg)
            y = yg.reshape(db, cs, b // db, s // cs, h)
            y = y.transpose(0, 2, 1, 3, 4).reshape(b, s, h)
            return y, jnp.mean(aux)

        def route_one(xt, ids):
            logits = xt.astype(jnp.float32) @ params["router"]
            expert_idx, gate_vals = select_experts(logits, ids, moe)
            plan = sort_routing(expert_idx, gate_vals, E, capacity)
            aux = aux_losses(logits, expert_idx, moe)
            # router telemetry (obs.numerics): only COMPUTED when a
            # collector is active, so the unset-flag trace is untouched
            rstats = (_router_stats(logits, plan["load"], plan["dropped"])
                      if _numerics_active() else {})
            return scatter_to_experts(xt, plan, E, capacity), plan, aux, \
                rstats

        buf, plan, aux, rstats = jax.vmap(route_one)(xg, ig)  # [G, E, C, h]
        if rstats:
            # per-group stats stacked [G, ...] by vmap -> reduce with
            # each stat's own rule, tap under the "moe" scope (repeated
            # MoE layers accumulate into the same scope)
            from hetu_tpu.obs import numerics as _numerics
            _numerics.merge(_numerics.reduce_stacked({"moe": rstats}))
        ep_spec = {1: "ep"} if st.ep > 1 else {}
        if group_axes or ep_spec:
            buf = DS.make(4, {0: group_axes, **ep_spec}).constrain(buf)
        out = self._experts(params, buf)               # [G, E, C, h]
        if group_axes or ep_spec:
            out = DS.make(4, {0: group_axes, **ep_spec}).constrain(out)

        yg = jax.vmap(lambda o, p: gather_from_experts(o, p, Tg))(
            out, plan)                                 # [G, Tg, h]
        if group_axes:
            yg = DS.make(3, {0: group_axes}).constrain(yg)
        y = yg.reshape(db, cs, b // db, s // cs, h)
        y = y.transpose(0, 2, 1, 3, 4).reshape(b, s, h)
        return y, jnp.mean(aux)

    def _forward_dense(self, params, x, token_ids):
        moe, st = self.moe, self.strategy
        b, s, h = x.shape
        T = b * s
        E = moe.num_experts
        capacity = int(moe.capacity_factor * T * max(moe.top_k, 1) / E)
        capacity = max(8, min(T, -(-capacity // 8) * 8))  # mult of 8

        xt = x.reshape(T, h)
        logits = xt.astype(jnp.float32) @ params["router"]
        ids = (token_ids.reshape(T) if token_ids is not None
               else jnp.arange(T, dtype=jnp.int32))
        dispatch, combine, aux, dropped = topk_routing(logits, ids, moe,
                                                       capacity)
        if _numerics_active():
            from hetu_tpu.obs import numerics as _numerics
            # PRE-drop routing intent, same definition as the sort
            # plan's `load` (post-drop counts would both understate a
            # collapsed router's load_max and push drop_frac past 1).
            # select_experts runs a second time here, but it is pure on
            # identical inputs — XLA CSEs the duplicate — and only
            # traced when the numerics flag opted in.
            e_idx, _gv = select_experts(logits, ids, moe)
            counts = jnp.zeros((E,), jnp.int32).at[e_idx.reshape(-1)].add(1)
            _numerics.merge({"moe": _router_stats(logits, counts, dropped)})

        buf = jnp.einsum("th,tec->ech", xt, dispatch.astype(x.dtype))
        if st.ep > 1:
            buf = DS.make(3, {0: "ep"}).constrain(buf)
        out = self._experts(params, buf)
        if st.ep > 1:
            out = DS.make(3, {0: "ep"}).constrain(out)
        y = jnp.einsum("ech,tec->th", out, combine.astype(x.dtype))
        return y.reshape(b, s, h), aux
