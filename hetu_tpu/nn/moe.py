"""Mixture-of-Experts with expert parallelism.

Rebuild of the reference MoE (reference: hetu/v1/python/hetu/layers/
moe_layer.py + gates Top/KTop1/Hash/Balance + Dispatch.py and hierarchical
all-to-all HAllToAll.py — v1-only features per SURVEY.md §2.4 EP row).

TPU-first design (GShard/Switch style):
- experts are ONE stacked parameter [E, ...] sharded over the `ep` mesh axis.
- dispatch/combine are einsums against a one-hot routing mask with a fixed
  per-expert capacity — static shapes, MXU-friendly, and GSPMD lowers the
  token->expert movement to all-to-all over ep (the reference's explicit
  HAllToAll becomes compiler-inserted; mesh axis order already makes it
  hierarchical: ICI within a slice, DCN across).
- router: softmax gate with top-k (k=1/2), capacity dropping, load-balance
  auxiliary loss (Switch-style) and router z-loss; a HashGate mirrors the
  reference's hash gate for ablations.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu import ops
from hetu_tpu.dstates import DistributedStates as DS
from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module
from hetu_tpu.parallel.strategy import ParallelStrategy


@dataclasses.dataclass
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_z_loss_coef: float = 1e-3
    load_balance_coef: float = 1e-2
    gate: str = "topk"  # "topk" | "hash"


def _router_probs(logits):
    return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)


def topk_routing(logits, ids, moe: MoEConfig, capacity: int):
    """Returns (dispatch [T, E, C] bool, combine [T, E, C] f32, aux_loss).

    T = tokens, E = experts, C = capacity.  Top-k softmax routing with
    position-in-expert capacity dropping (GShard); aux = load-balance +
    z-loss (reference gate variants: v1 gates Top/KTop1/Balance)."""
    T, E = logits.shape
    probs = _router_probs(logits)                      # [T, E]

    if moe.gate == "hash":
        # reference HashGate: expert = token_id % E (no learned routing)
        expert_idx = (ids % E)[:, None]                # [T, 1]
        gate_vals = jnp.ones((T, 1), jnp.float32)
        k = 1
    else:
        k = moe.top_k
        gate_vals, expert_idx = jax.lax.top_k(probs, k)   # [T, k]
        # renormalize the kept gates
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # position of each token within its expert (for capacity) — computed per
    # k-slot sequentially so slot-0 assignments take priority
    dispatch = jnp.zeros((T, E, capacity), jnp.bool_)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    fill = jnp.zeros((E,), jnp.int32)
    for slot in range(k):
        e = expert_idx[:, slot]                        # [T]
        onehot = jax.nn.one_hot(e, E, dtype=jnp.int32)  # [T, E]
        pos_in_e = (jnp.cumsum(onehot, axis=0) - onehot)  # arrivals before t
        pos = jnp.take_along_axis(pos_in_e, e[:, None], axis=1)[:, 0] + fill[e]
        keep = pos < capacity
        pos_c = jnp.clip(pos, 0, capacity - 1)
        upd = (jax.nn.one_hot(e, E, dtype=jnp.float32)[:, :, None] *
               jax.nn.one_hot(pos_c, capacity, dtype=jnp.float32)[:, None, :])
        upd = upd * keep[:, None, None]
        dispatch = dispatch | (upd > 0)
        combine = combine + upd * gate_vals[:, slot][:, None, None]
        fill = fill + jnp.sum(
            jax.nn.one_hot(e, E, dtype=jnp.int32) * keep[:, None], axis=0)

    # aux losses
    me = jnp.mean(probs, axis=0)                       # mean prob per expert
    ce = jnp.mean(
        jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32), axis=0)
    load_balance = E * jnp.sum(me * ce)
    z = jnp.mean(jnp.square(jax.nn.logsumexp(logits.astype(jnp.float32),
                                             axis=-1)))
    aux = moe.load_balance_coef * load_balance + moe.router_z_loss_coef * z
    return dispatch, combine, aux


class MoELayer(Module):
    """Sparse SwiGLU FFN: router + E experts, expert dim sharded over ep
    (reference: v1 moe_layer.py MoELayer; dense path = LlamaMLP)."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 moe: MoEConfig, strategy: ParallelStrategy,
                 param_dtype=jnp.float32, initializer_range: float = 0.02):
        super().__init__()
        self.moe, self.strategy = moe, strategy
        self.hidden, self.inter = hidden_size, intermediate_size
        E = moe.num_experts
        if E % max(strategy.ep, 1):
            raise ValueError(f"num_experts={E} must divide by ep={strategy.ep}")
        ep_ds = DS.make(4, {0: "ep", 3: "tp"}) if strategy.ep > 1 or strategy.tp > 1 else None
        dn_ds = DS.make(3, {0: "ep", 1: "tp"}) if strategy.ep > 1 or strategy.tp > 1 else None
        self.param("router", (hidden_size, E), init.normal(initializer_range),
                   dtype=jnp.float32)
        self.param("w_gate_up", (E, hidden_size, 2, intermediate_size),
                   init.normal(initializer_range), dtype=param_dtype, ds=ep_ds)
        self.param("w_down", (E, intermediate_size, hidden_size),
                   init.normal(initializer_range), dtype=param_dtype, ds=dn_ds)

    def forward(self, params, x, *, token_ids: Optional[jnp.ndarray] = None):
        """x: [b, s, h] -> ([b, s, h], aux_loss)."""
        moe, st = self.moe, self.strategy
        b, s, h = x.shape
        T = b * s
        E = moe.num_experts
        capacity = int(moe.capacity_factor * T * max(moe.top_k, 1) / E)
        capacity = max(8, min(T, -(-capacity // 8) * 8))  # mult of 8

        xt = x.reshape(T, h)
        logits = xt.astype(jnp.float32) @ params["router"]
        ids = (token_ids.reshape(T) if token_ids is not None
               else jnp.arange(T, dtype=jnp.int32))
        dispatch, combine, aux = topk_routing(logits, ids, moe, capacity)

        # dispatch tokens into per-expert buffers [E, C, h]
        buf = jnp.einsum("th,tec->ech", xt, dispatch.astype(x.dtype))
        if st.ep > 1:
            buf = DS.make(3, {0: "ep"}).constrain(buf)
        # expert FFN (batched over E; ep-sharded -> local experts only)
        gu = jnp.einsum("ecd,edki->ecki", buf,
                        params["w_gate_up"].astype(x.dtype))
        hidden = ops.swiglu(gu[:, :, 0, :], gu[:, :, 1, :])
        out = jnp.einsum("eci,eih->ech", hidden,
                         params["w_down"].astype(x.dtype))
        if st.ep > 1:
            out = DS.make(3, {0: "ep"}).constrain(out)
        # combine back to tokens
        y = jnp.einsum("ech,tec->th", out, combine.astype(x.dtype))
        return y.reshape(b, s, h), aux
