"""Tensor/sequence-parallel layers.

TPU-native rebuild of the reference's multi-DS parallel modules
(reference: python/hetu/nn/modules/parallel_multi_ds.py:89-588).  The reference
inserts explicit `hetu.comm(tensor, ds)` ops where layouts mismatch; here the
layers run in *global view* under jit and express the same intent with
sharding constraints — GSPMD then inserts exactly the Megatron collectives
(all-gather before column, all-reduce/reduce-scatter after row) the reference
lowers CommOp to.  The DS algebra still documents/plans the comms
(hetu_tpu.dstates.deduce_comm) and drives the explicit shard_map paths used by
ring attention and MoE.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from hetu_tpu import ops
from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module
from hetu_tpu.parallel.strategy import ParallelStrategy


class ColumnParallelLinear(Module):
    """Y = X·W, W:[in, out] sharded on out over tp
    (reference: HtMultiColumnParallelLinear parallel_multi_ds.py:328)."""

    def __init__(self, in_features: int, out_features: int,
                 strategy: ParallelStrategy, bias: bool = True,
                 gather_output: bool = False, param_dtype=jnp.float32,
                 weight_init=None):
        super().__init__()
        self.strategy = strategy
        self.gather_output = gather_output
        if strategy.tp > 1 and out_features % strategy.tp:
            raise ValueError(f"out_features {out_features} must divide by "
                             f"tp={strategy.tp}")
        self.param("weight", (in_features, out_features),
                   weight_init or init.xavier_uniform(), dtype=param_dtype,
                   ds=strategy.col_weight())
        self.use_bias = bias
        if bias:
            self.param("bias", (out_features,), init.zeros, dtype=param_dtype,
                       ds=strategy.col_bias())

    def forward(self, params, x):
        y = x @ params["weight"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        st = self.strategy
        if x.ndim == 3:
            y = st.constrain(y, st.act_hidden() if self.gather_output else st.act_inner())
        return y


class RowParallelLinear(Module):
    """Y = X·W, W:[in, out] sharded on in over tp; output needs a reduction —
    all-reduce (plain TP) or reduce-scatter onto the seq dim (SP)
    (reference: HtMultiRowParallelLinear, parallel_multi_ds.py)."""

    def __init__(self, in_features: int, out_features: int,
                 strategy: ParallelStrategy, bias: bool = True,
                 param_dtype=jnp.float32, weight_init=None):
        super().__init__()
        self.strategy = strategy
        if strategy.tp > 1 and in_features % strategy.tp:
            raise ValueError(f"in_features {in_features} must divide by "
                             f"tp={strategy.tp}")
        self.param("weight", (in_features, out_features),
                   weight_init or init.xavier_uniform(), dtype=param_dtype,
                   ds=strategy.row_weight())
        self.use_bias = bias
        if bias:
            # bias added after the reduction → replicated
            self.param("bias", (out_features,), init.zeros, dtype=param_dtype)

    def forward(self, params, x):
        y = x @ params["weight"].astype(x.dtype)
        st = self.strategy
        if x.ndim == 3:
            # Constraining the (partial) matmul result to the SP/replicated
            # layout makes GSPMD emit reduce-scatter (SP) or all-reduce (TP).
            y = st.constrain(y, st.act_hidden())
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


class VocabParallelEmbedding(Module):
    """Embedding with the vocab dim sharded over tp
    (reference: HtMultiVocabParallelEmbedding, parallel_multi_ds.py:268)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 strategy: ParallelStrategy, param_dtype=jnp.float32,
                 weight_init=None):
        super().__init__()
        self.strategy = strategy
        self.num_embeddings = num_embeddings
        if strategy.tp > 1 and num_embeddings % strategy.tp:
            raise ValueError(
                f"vocab size {num_embeddings} must divide by tp="
                f"{strategy.tp}; pad the vocab (e.g. 50257 -> 50304)")
        self.param("weight", (num_embeddings, embedding_dim),
                   weight_init or init.normal(0.02), dtype=param_dtype,
                   ds=strategy.vocab_weight())

    def forward(self, params, ids):
        y = jnp.take(params["weight"], ids, axis=0)
        st = self.strategy
        y = st.constrain(y, st.act_hidden())
        return y


class ParallelRMSNorm(Module):
    """RMSNorm that understands sequence parallelism: in SP the input/output
    stay seq-sharded over tp (norm is per-token so no comm is needed; the
    reference wires split0<->dup comms around it, parallel_multi_ds.py:89-162 —
    GSPMD places the equivalent gathers at the next matmul instead)."""

    def __init__(self, dim: int, strategy: ParallelStrategy, eps: float = 1e-5,
                 param_dtype=jnp.float32):
        super().__init__()
        self.strategy = strategy
        self.eps = eps
        self.param("weight", (dim,), init.ones, dtype=param_dtype)

    def forward(self, params, x):
        y = ops.rms_norm(x, params["weight"], self.eps)
        if x.ndim == 3:
            y = self.strategy.constrain(y, self.strategy.act_hidden())
        return y

    def residual(self, params, x, h):
        """Fused residual-add + norm (the pre-norm block's pair):
        returns (norm(x + h), x + h).  Routes to the Pallas fused_norm
        kernel under HETU_TPU_PALLAS; the fallback is exactly the seed
        composition `s = x + h; forward(s)`, same constrain."""
        y, s = ops.residual_rms_norm(x, h, params["weight"], self.eps)
        if x.ndim == 3:
            y = self.strategy.constrain(y, self.strategy.act_hidden())
        return y, s


class ParallelLayerNorm(Module):
    def __init__(self, dim: int, strategy: ParallelStrategy, eps: float = 1e-5,
                 bias: bool = True, param_dtype=jnp.float32):
        super().__init__()
        self.strategy = strategy
        self.eps = eps
        self.use_bias = bias
        self.param("weight", (dim,), init.ones, dtype=param_dtype)
        if bias:
            self.param("bias", (dim,), init.zeros, dtype=param_dtype)

    def forward(self, params, x):
        y = ops.layer_norm(x, params["weight"],
                           params["bias"] if self.use_bias else None, self.eps)
        if x.ndim == 3:
            y = self.strategy.constrain(y, self.strategy.act_hidden())
        return y

    def residual(self, params, x, h):
        """Fused residual-add + LayerNorm pair — see
        ParallelRMSNorm.residual."""
        y, s = ops.residual_layer_norm(
            x, h, params["weight"],
            params["bias"] if self.use_bias else None, self.eps)
        if x.ndim == 3:
            y = self.strategy.constrain(y, self.strategy.act_hidden())
        return y, s
