from hetu_tpu.nn.module import Module, ModuleList, ModuleDict, Sequential, ParamSpec
from hetu_tpu.nn import initializers
from hetu_tpu.nn.layers import (
    Linear, Embedding, RMSNorm, LayerNorm, Dropout, Conv2d, MaxPool2d,
    AvgPool2d, GELU, ReLU, SiLU, BatchNorm, InstanceNorm, ConstantPad2d,
    ZeroPad2d,
)
from hetu_tpu.nn.parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelRMSNorm, ParallelLayerNorm,
)
