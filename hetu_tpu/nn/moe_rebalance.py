"""Capacity-factor rebalancing from the live expert-load gauges.

`nn/moe.py` publishes router telemetry through the numerics observatory
(PR 12): `moe.expert_load` gauges — per-expert fraction of TOKENS
carrying that expert, summing to ~top_k — and the `moe.capacity_dropped`
counter.  This module closes the loop HetuMoE closes with its dynamic
capacity: a host-side watcher that reads those gauges and proposes a new
`capacity_factor` when the observed load says the current one is wrong
in either direction.

Why host-side: capacity is a STATIC shape (the [E, C, h] dispatch
buffers), so changing it means re-tracing — exactly a plan change, the
same tier as a hot switch.  The watcher therefore never touches a live
step; it emits a decision the caller applies by rebuilding the layer /
train step with `apply(moe_cfg, factor)` (dataclasses.replace — the
PlanPool keys recompiles per strategy already).

The math: a perfectly balanced router puts `top_k / E` of the tokens on
each expert, and capacity C = cf * T * k / E holds `cf` times that.
The hottest expert needs `cf >= load_max * E / k` to drop nothing, so:

* GROW  when `needed > cf` (tokens are being capacity-dropped) for
  `strikes` consecutive observations -> `needed * headroom`, capped.
* SHRINK when `needed * shrink_margin < cf` (buffers mostly padding)
  for `strikes` observations -> `needed * headroom`, floored.

Hysteresis (strike counting + the shrink margin) keeps a noisy router
from thrashing recompiles — the same strike pattern the serving
LoadAdaptiveMesh uses.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from hetu_tpu.obs.metrics import MetricsRegistry, get_registry


@dataclasses.dataclass
class RebalanceDecision:
    """One proposed capacity change: the new factor + the evidence."""
    capacity_factor: float
    reason: str                # "grow" | "shrink"
    load_max: float            # observed hottest-expert token fraction
    needed_factor: float       # cf that would just fit the hottest


class CapacityRebalancer:
    """Watch the `moe.expert_load` gauges, propose capacity changes.

    Call `observe()` once per reporting interval (whenever the numerics
    observatory has refreshed the gauges — every HETU_TPU_NUMERICS_EVERY
    steps under the flag); it returns a RebalanceDecision when `strikes`
    consecutive observations agree a change is warranted, else None."""

    def __init__(self, num_experts: int, top_k: int,
                 capacity_factor: float,
                 registry: Optional[MetricsRegistry] = None, *,
                 headroom: float = 1.1, shrink_margin: float = 1.5,
                 strikes: int = 2, min_factor: float = 1.0,
                 max_factor: float = 8.0):
        if strikes < 1:
            raise ValueError(f"strikes must be >= 1, got {strikes}")
        self.num_experts = num_experts
        self.top_k = max(top_k, 1)
        self.capacity_factor = float(capacity_factor)
        self.registry = registry if registry is not None else get_registry()
        self.headroom = headroom
        self.shrink_margin = shrink_margin
        self.strikes = strikes
        self.min_factor = min_factor
        self.max_factor = max_factor
        self._grow_streak = 0
        self._shrink_streak = 0

    # ------------------------------------------------------------------
    def _load_max(self) -> Optional[float]:
        loads = [self.registry.gauge_value("moe.expert_load",
                                           expert=str(i))
                 for i in range(self.num_experts)]
        loads = [v for v in loads if v is not None]
        return max(loads) if loads else None

    def observe(self) -> Optional[RebalanceDecision]:
        load_max = self._load_max()
        if load_max is None:
            return None                      # gauges not published yet
        needed = load_max * self.num_experts / self.top_k
        cf = self.capacity_factor
        if needed > cf:
            self._grow_streak += 1
            self._shrink_streak = 0
            if self._grow_streak >= self.strikes:
                return self._decide("grow", load_max, needed)
        elif needed * self.shrink_margin < cf:
            self._shrink_streak += 1
            self._grow_streak = 0
            if self._shrink_streak >= self.strikes:
                return self._decide("shrink", load_max, needed)
        else:
            self._grow_streak = self._shrink_streak = 0
        return None

    def _decide(self, reason: str, load_max: float,
                needed: float) -> Optional[RebalanceDecision]:
        new = min(self.max_factor,
                  max(self.min_factor, needed * self.headroom))
        self._grow_streak = self._shrink_streak = 0
        if abs(new - self.capacity_factor) < 1e-9:
            return None                      # clamped into no-op
        self.capacity_factor = new
        self.registry.set_gauge("moe.capacity_factor", new)
        self.registry.inc("moe.rebalances", reason=reason)
        return RebalanceDecision(capacity_factor=new, reason=reason,
                                 load_max=load_max, needed_factor=needed)


def apply(moe_cfg, factor: float):
    """A MoEConfig with the rebalanced capacity factor — feed it to a
    fresh MoELayer / model build (a plan change, like a hot switch)."""
    return dataclasses.replace(moe_cfg, capacity_factor=float(factor))
