"""Recompute (activation checkpoint) policies shared by all model families
(reference: hetu/graph/recompute/recompute.cc pass + the activation
CPU-offload pass offload/activation_cpu_offload.h — 'offload' keeps dot
outputs staged in pinned host memory)."""
from __future__ import annotations

import jax

REMAT_POLICIES = ("nothing", "dots", "dots_attn", "offload")


def remat_policy(name: str):
    cp = jax.checkpoint_policies
    if name == "nothing":
        return cp.nothing_saveable
    if name == "dots":
        return cp.dots_with_no_batch_dims_saveable
    if name == "dots_attn":
        # dots + the named attention-kernel output (models tag it
        # checkpoint_name "attn_out"): the flash kernel is the costliest
        # thing the dot-only policy recomputes
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("attn_out"))
    if name == "offload":
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    raise ValueError(f"unknown remat_policy {name!r}; one of {REMAT_POLICIES}")


def validate_remat_policy(name: str):
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {name!r}; one of {REMAT_POLICIES}")
