"""Explicit expert-parallel MoE dispatch (HetuMoE's HAllToAll made ours).

The default MoE path (`nn/moe.py`, `HETU_TPU_MOE_DISPATCH` unset or
"gspmd") expresses token->expert movement as sharding constraints and
lets GSPMD choose the collectives: full-width fp32/bf16 transports,
invisible to the cost model and replicated routing work over the `ep`
axis.  This module is the flag's explicit alternative: one `shard_map`
over the mesh that

  1. routes IDENTICALLY to the GSPMD path (same `sort_routing` plan per
     data group — the bit-compare contract the goldens pin), with each
     `ep` rank scattering only its 1/ep share of the (token, slot)
     pairs into a partial `[E, C, h]` buffer (the replicated scatter
     work the GSPMD path pays is split ep-ways),
  2. delivers expert buffers with a dispatch ALL-TO-ALL + sum over `ep`
     (`comm/collectives.all_to_all_q` — int8/int4 blockwise payloads
     with f32 block scales under the quantized modes, exact `lax`
     collectives under "fp32"; the custom-vjp transpose quantizes the
     backward transport too),
  3. runs the local expert shard's SwiGLU, and
  4. combines with an ALL-GATHER of expert outputs over `ep`
     (`all_gather_q`, same mode).

With `HETU_TPU_COMM_TOPOLOGY=two_level` and a topology that
`applies(ep)` (comm/topology.py), both transports run HIERARCHICALLY —
the HetuMoE HAllToAll schedule: an intra-slice a2a exchanges
position-keyed bundles at intra rates, then only the 1/slice
slice-aggregated bundles cross the strided inter-slice transversals
(byte math in `comm/wire.py::moe_two_level_dispatch_bytes`; the
analyzer obs.comm prices the lowered groups at the two rates).

Envelope: ep > 1, tp == 1, pp == 1, sort dispatch, (tokens * slots)
divisible by ep — anything else raises loudly at trace time (the
grad-compress pattern).  ep == 1 or the dense parity dispatcher keep
the GSPMD path regardless of the flag.  See docs/moe.md.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from hetu_tpu.comm.collectives import all_gather_q, all_to_all_q
from hetu_tpu.comm.topology import Topology, load_topology, topology_mode
from hetu_tpu.comm.wire import DEFAULT_BLOCK
from hetu_tpu.core.mesh import EP_AXIS, current_mesh

#: HETU_TPU_MOE_DISPATCH values; "gspmd" = the constraint-based path
MODES = ("gspmd", "fp32", "int8", "int4")


def dispatch_mode() -> str:
    """The HETU_TPU_MOE_DISPATCH flag value."""
    from hetu_tpu.utils import flags
    return flags.str_flag("HETU_TPU_MOE_DISPATCH")


def resolved_mode(strategy) -> str:
    """The dispatch mode this trace actually takes: the flag, demoted to
    "gspmd" when there is no ep axis to dispatch over (the flag is a
    no-op at ep=1 — single-device serving decode, the canonical MoE
    program)."""
    mode = dispatch_mode()
    if mode != "gspmd" and strategy.ep <= 1:
        return "gspmd"
    return mode


def two_level_topology(ep: int) -> Optional[Topology]:
    """The slice topology the hierarchical schedule routes over, or None
    for the flat schedule: requires HETU_TPU_COMM_TOPOLOGY=two_level AND
    a profile topology that applies to an ep-rank group (the same
    opt-in pair the DP grad sync uses)."""
    if topology_mode() != "two_level":
        return None
    topo = load_topology()
    if topo is None or not topo.applies(ep):
        return None
    return topo


def validate_envelope(strategy, moe, num_pairs: int) -> None:
    """Loud trace-time envelope check for the explicit path (the
    grad-compress pattern: refuse instead of silently degrading)."""
    ep = strategy.ep
    if strategy.tp > 1 or strategy.pp > 1:
        raise ValueError(
            "HETU_TPU_MOE_DISPATCH explicit modes compose with tp=1, "
            f"pp=1 (got tp={strategy.tp}, pp={strategy.pp}); the tp-"
            "sharded expert einsum and the pipeline's partial-manual "
            "stage bodies cannot host the dispatch shard_map — unset "
            "the flag for those meshes")
    if moe.dispatch != "sort":
        raise ValueError(
            "HETU_TPU_MOE_DISPATCH explicit modes require the sort "
            f"dispatcher (got dispatch={moe.dispatch!r}); the dense "
            "[T,E,C] parity path stays on GSPMD")
    if num_pairs % ep:
        raise ValueError(
            f"explicit MoE dispatch splits the {num_pairs} (token, "
            f"slot) pairs per group over ep={ep}, which must divide "
            "evenly — adjust batch/seq/top_k or unset "
            "HETU_TPU_MOE_DISPATCH")


# ---------------------------------------------------------------------------
# the two transports (flat + hierarchical), over the bound `ep` axis
# ---------------------------------------------------------------------------

def _dispatch_reduce(partial, ep: int, mode: str, topo: Optional[Topology],
                     block: int = DEFAULT_BLOCK):
    """partial [G_loc, E, C, h] (this rank's token share scattered into
    the FULL expert range) -> buf [G_loc, E_loc, C, h] (this rank's
    expert block, summed over every rank's contribution).  The dispatch
    half of HAllToAll: a2a + sum == reduce-scatter by expert block."""
    g, E, C, h = partial.shape
    e_loc = E // ep
    bloc = e_loc * C * h
    if topo is None:
        x = partial.reshape(g, ep, bloc)
        recv = all_to_all_q(x, EP_AXIS, split_axis=1, concat_axis=1,
                            mode=mode, block_size=block)
        buf = jnp.sum(recv.reshape(g, ep, bloc), axis=1)
        return buf.reshape(g, e_loc, C, h)
    k, s = topo.slice_devices, ep // topo.slice_devices
    intra, inter = topo.groups(ep)
    # stage 1 (intra, fast): exchange position-keyed bundles inside the
    # slice — bundle i holds this rank's partials for the position-i
    # rank of EVERY slice
    x = partial.reshape(g, s, k, bloc).transpose(0, 2, 1, 3)
    recv = all_to_all_q(x.reshape(g, k, s * bloc), EP_AXIS,
                        split_axis=1, concat_axis=1, mode=mode,
                        block_size=block, axis_index_groups=intra)
    agg = jnp.sum(recv.reshape(g, k, s, bloc), axis=1)   # slice-aggregated
    # stage 2 (inter, slow): only the 1/k aggregated bundles cross the
    # strided transversal — the HetCCL/HAllToAll saving
    recv2 = all_to_all_q(agg, EP_AXIS, split_axis=1, concat_axis=1,
                         mode=mode, block_size=block,
                         axis_index_groups=inter)
    buf = jnp.sum(recv2.reshape(g, s, bloc), axis=1)
    return buf.reshape(g, e_loc, C, h)


def _combine_gather(out_loc, ep: int, mode: str, topo: Optional[Topology],
                    block: int = DEFAULT_BLOCK):
    """out_loc [G_loc, E_loc, C, h] -> [G_loc, E, C, h]: every rank
    receives every expert block (rank-major order matches the expert
    index).  Hierarchical form: inter-slice gather of the 1/k blocks
    first, then the intra-slice gather at fast rates."""
    g, e_loc, C, h = out_loc.shape
    if topo is None:
        return all_gather_q(out_loc, EP_AXIS, axis=1, tiled=True,
                            mode=mode, block_size=block)
    k, s = topo.slice_devices, ep // topo.slice_devices
    intra, inter = topo.groups(ep)
    g1 = all_gather_q(out_loc, EP_AXIS, axis=1, tiled=True, mode=mode,
                      block_size=block, axis_index_groups=inter)
    g2 = all_gather_q(g1, EP_AXIS, axis=1, tiled=True, mode=mode,
                      block_size=block, axis_index_groups=intra)
    # received layout (i, b, e_loc) -> expert id (b*k + i)*E_loc + e
    out = g2.reshape(g, k, s, e_loc, C, h).transpose(0, 2, 1, 3, 4, 5)
    return out.reshape(g, k * s * e_loc, C, h)


# ---------------------------------------------------------------------------
# the explicit forward
# ---------------------------------------------------------------------------

def explicit_forward(layer, params, xg, ig, capacity: int,
                     group_axes: Tuple[str, ...], Tg: int):
    """The shard_map dispatch path: xg [G, Tg, h] grouped over
    (dp, cp) -> (yg [G, Tg, h], aux [G]).  Routing, capacity semantics
    and the combine arithmetic are IDENTICAL to the GSPMD path (same
    helpers, same plan) — only the transport differs."""
    from hetu_tpu.nn.moe import (_numerics_active, _router_stats,
                                 aux_losses, gather_from_experts,
                                 scatter_to_experts, select_experts,
                                 sort_routing)

    moe, st = layer.moe, layer.strategy
    ep, E = st.ep, moe.num_experts
    mode = resolved_mode(st)
    qmode = "none" if mode == "fp32" else mode
    n_slots = 1 if moe.gate in ("hash", "top1") else max(moe.top_k, 1)
    validate_envelope(st, moe, Tg * n_slots)
    mesh = current_mesh()
    if mesh is None:
        raise ValueError(
            "explicit MoE dispatch needs an active mesh (use_mesh) so "
            "the dispatch shard_map can bind the ep axis")
    topo = two_level_topology(ep)
    active = _numerics_active()
    gs = tuple(group_axes) if group_axes else None

    def body(xg_l, ig_l, router, wgu, wdn):
        r = lax.axis_index(EP_AXIS)

        def route_one(xt, ids):
            logits = xt.astype(jnp.float32) @ router
            eidx, gv = select_experts(logits, ids, moe)
            plan = sort_routing(eidx, gv, E, capacity)
            aux = aux_losses(logits, eidx, moe)
            # token-share split: pair j of the slot-major sorted order
            # belongs to rank j // (TK/ep); pairs not mine scatter to
            # the trash row, so the cross-rank sum reassembles the
            # GSPMD buffer EXACTLY (disjoint destinations)
            TK = plan["dest"].shape[0]
            share = TK // ep
            j = jnp.arange(TK, dtype=jnp.int32)
            mine = (j >= r * share) & (j < (r + 1) * share)
            dest = jnp.where(mine, plan["dest"], E * capacity)
            partial = scatter_to_experts(xt, dict(plan, dest=dest), E,
                                         capacity)
            rst = (_router_stats(logits, plan["load"], plan["dropped"])
                   if active else {})
            return partial, plan, aux, rst

        partial, plan, aux, rst = jax.vmap(route_one)(xg_l, ig_l)
        buf = _dispatch_reduce(partial, ep, qmode, topo)
        out = layer._experts({"w_gate_up": wgu, "w_down": wdn}, buf)
        out_full = _combine_gather(out, ep, qmode, topo)
        yg = jax.vmap(lambda o, p: gather_from_experts(o, p, Tg))(
            out_full, plan)
        return yg, aux, rst

    from jax.experimental.shard_map import shard_map
    rst_spec = ({"load": P(gs, None), "load_max": P(gs),
                 "entropy": P(gs), "dropped": P(gs), "drop_frac": P(gs)}
                if active else {})
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(gs, None, None), P(gs, None), P(), P(EP_AXIS),
                  P(EP_AXIS)),
        out_specs=(P(gs, None, None), P(gs), rst_spec),
        # routing (hence yg/aux) is replicated over ep by construction,
        # but the checker cannot see that through the a2a
        check_rep=False)
    yg, aux, rst = fn(xg, ig, params["router"],
                      params["w_gate_up"], params["w_down"])
    if rst:
        # same per-group -> scope reduction as the GSPMD path
        from hetu_tpu.obs import numerics as _numerics
        _numerics.merge(_numerics.reduce_stacked({"moe": rst}))
    return yg, aux
