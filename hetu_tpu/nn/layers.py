"""Basic layers (reference: python/hetu/nn/modules/{linear,conv,normalization,
dropout,activation,loss}.py).

All layers follow the functional Module protocol: construction declares
ParamSpecs (with optional DistributedStates layouts), forward is pure.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu.nn import initializers as init
from hetu_tpu.nn.module import Module
from hetu_tpu import ops
from hetu_tpu.dstates import DistributedStates


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 param_dtype=jnp.float32, weight_init=None,
                 weight_ds: Optional[DistributedStates] = None,
                 bias_ds: Optional[DistributedStates] = None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        # Weight stored [in, out] — row-major matmul feeds the MXU directly
        # without the transpose the torch [out, in] convention would need.
        self.param("weight", (in_features, out_features),
                   weight_init or init.xavier_uniform(), dtype=param_dtype,
                   ds=weight_ds)
        self.use_bias = bias
        if bias:
            self.param("bias", (out_features,), init.zeros, dtype=param_dtype,
                       ds=bias_ds)

    def forward(self, params, x):
        w = params["weight"].astype(x.dtype)
        y = x @ w
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


class Embedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int,
                 param_dtype=jnp.float32, weight_init=None,
                 weight_ds: Optional[DistributedStates] = None):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.param("weight", (num_embeddings, embedding_dim),
                   weight_init or init.normal(0.02), dtype=param_dtype,
                   ds=weight_ds)

    def forward(self, params, ids):
        return jnp.take(params["weight"], ids, axis=0)


class RMSNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-5, param_dtype=jnp.float32,
                 weight_ds: Optional[DistributedStates] = None):
        super().__init__()
        self.eps = eps
        self.param("weight", (dim,), init.ones, dtype=param_dtype, ds=weight_ds)

    def forward(self, params, x):
        return ops.rms_norm(x, params["weight"], self.eps)

    def residual(self, params, x, h):
        """(norm(x + h), x + h) — one fused Pallas pass when
        HETU_TPU_PALLAS routes it (ops.residual_rms_norm)."""
        return ops.residual_rms_norm(x, h, params["weight"], self.eps)


class LayerNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-5, bias: bool = True,
                 param_dtype=jnp.float32):
        super().__init__()
        self.eps, self.use_bias = eps, bias
        self.param("weight", (dim,), init.ones, dtype=param_dtype)
        if bias:
            self.param("bias", (dim,), init.zeros, dtype=param_dtype)

    def forward(self, params, x):
        return ops.layer_norm(x, params["weight"],
                              params["bias"] if self.use_bias else None, self.eps)

    def residual(self, params, x, h):
        """(layer_norm(x + h), x + h) — one fused Pallas pass when
        HETU_TPU_PALLAS routes it (ops.residual_layer_norm)."""
        return ops.residual_layer_norm(
            x, h, params["weight"],
            params["bias"] if self.use_bias else None, self.eps)


class Dropout(Module):
    """Functional dropout: pass `rng=` and `deterministic=` at call time
    (the reference keeps per-device RNG state for recompute determinism,
    reference: hetu/impl/random/CUDARandomState.h; JAX PRNG keys subsume it)."""

    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def forward(self, params, x, *, rng: Optional[jax.Array] = None,
                deterministic: bool = True):
        if deterministic or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0).astype(x.dtype)


class Conv2d(Module):
    """NHWC conv (TPU-native layout; reference Conv2d is NCHW CUDA,
    hetu/graph/ops/Conv2d.cc)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size: int,
                 stride: int = 1, padding: str | int = "SAME", bias: bool = True,
                 param_dtype=jnp.float32):
        super().__init__()
        k = kernel_size
        self.stride = (stride, stride)
        self.padding = padding if isinstance(padding, str) else [(padding, padding)] * 2
        self.param("weight", (k, k, in_channels, out_channels), init.he_normal(),
                   dtype=param_dtype)
        self.use_bias = bias
        if bias:
            self.param("bias", (out_channels,), init.zeros, dtype=param_dtype)

    def forward(self, params, x):
        y = jax.lax.conv_general_dilated(
            x, params["weight"].astype(x.dtype), window_strides=self.stride,
            padding=self.padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


class MaxPool2d(Module):
    def __init__(self, kernel_size: int, stride: Optional[int] = None):
        super().__init__()
        self.k = kernel_size
        self.s = stride or kernel_size

    def forward(self, params, x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, self.k, self.k, 1),
            (1, self.s, self.s, 1), "VALID")


class AvgPool2d(Module):
    def __init__(self, kernel_size: int, stride: Optional[int] = None):
        super().__init__()
        self.k = kernel_size
        self.s = stride or kernel_size

    def forward(self, params, x):
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, self.k, self.k, 1),
            (1, self.s, self.s, 1), "VALID")
        return summed / float(self.k * self.k)


class GELU(Module):
    def forward(self, params, x):
        return ops.gelu(x)


class ReLU(Module):
    def forward(self, params, x):
        return ops.relu(x)


class SiLU(Module):
    def forward(self, params, x):
        return ops.silu(x)


class BatchNorm(Module):
    """NHWC batch normalization with explicit running-stats state
    (reference: nn/modules/batchnorm.py BatchNorm over CUDA kernels).

    Functional-state design: running stats are DATA, not module state —
    `init_state()` builds them, forward(training=True) returns
    (y, new_state) so the caller threads them (jit-friendly; the
    reference mutates saved_running_{mean,var} tensors in place)."""

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, param_dtype=jnp.float32):
        super().__init__()
        self.num_features, self.eps, self.momentum = num_features, eps, momentum
        self.param("weight", (num_features,), init.ones, dtype=param_dtype)
        self.param("bias", (num_features,), init.zeros, dtype=param_dtype)

    def init_state(self):
        return {"mean": jnp.zeros((self.num_features,), jnp.float32),
                "var": jnp.ones((self.num_features,), jnp.float32)}

    def forward(self, params, x, state, *, training: bool = False):
        axes = tuple(range(x.ndim - 1))          # all but channels
        xf = x.astype(jnp.float32)
        if training:
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            # running stats accumulate the UNBIASED variance (torch-style
            # reference semantics: checkpoints interop at eval time);
            # normalization itself uses the biased batch variance
            n = x.size // x.shape[-1]
            unbiased = var * (n / max(n - 1, 1))
            m = self.momentum
            state = {"mean": (1 - m) * state["mean"] + m * mean,
                     "var": (1 - m) * state["var"] + m * unbiased}
        else:
            mean, var = state["mean"], state["var"]
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["weight"].astype(jnp.float32) \
            + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype), state


class InstanceNorm(Module):
    """NHWC instance norm: per-(sample, channel) spatial statistics
    (reference: nn/modules/instancenorm.py)."""

    def __init__(self, num_features: int, eps: float = 1e-5,
                 affine: bool = True, param_dtype=jnp.float32):
        super().__init__()
        self.eps, self.affine = eps, affine
        if affine:
            self.param("weight", (num_features,), init.ones,
                       dtype=param_dtype)
            self.param("bias", (num_features,), init.zeros,
                       dtype=param_dtype)

    def forward(self, params, x):
        axes = tuple(range(1, x.ndim - 1))       # spatial dims
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["weight"].astype(jnp.float32) \
                + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype)


class ConstantPad2d(Module):
    """Pad the spatial dims of NHWC input (reference:
    nn/modules/padding.py ConstantPad2d; ZeroPad2d = value 0)."""

    def __init__(self, padding, value: float = 0.0):
        super().__init__()
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)
        self.padding = tuple(padding)   # (left, right, top, bottom)
        self.value = value

    def forward(self, params, x):
        l, r, t, b = self.padding
        # negative entries CROP (reference ConstantPad2d semantics)
        for lo, hi, axis in ((t, b, 1), (l, r, 2)):
            if max(-lo, 0) + max(-hi, 0) > x.shape[axis]:
                raise ValueError(
                    f"padding {self.padding} crops away the whole axis "
                    f"{axis} of input shape {x.shape}")

        def crop(v, lo, hi, axis):
            sl = [slice(None)] * v.ndim
            sl[axis] = slice(max(-lo, 0), v.shape[axis] - max(-hi, 0))
            return v[tuple(sl)]
        x = crop(crop(x, t, b, 1), l, r, 2)
        pads = ((0, 0), (max(t, 0), max(b, 0)), (max(l, 0), max(r, 0)),
                (0, 0))
        return jnp.pad(x, pads, constant_values=self.value)


class ZeroPad2d(ConstantPad2d):
    def __init__(self, padding):
        super().__init__(padding, 0.0)
