"""Parameter initializers (reference: hetu/graph/init/initializer.h)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def zeros(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def constant(value):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init


def normal(stddev=0.02, mean=0.0):
    def init(key, shape, dtype=jnp.float32):
        return mean + stddev * jax.random.normal(key, shape, jnp.float32).astype(dtype)
    return init


def truncated_normal(stddev=0.02):
    def init(key, shape, dtype=jnp.float32):
        return (stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                                     jnp.float32)).astype(dtype)
    return init


def uniform(scale=0.05):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, jnp.float32, -scale, scale).astype(dtype)
    return init


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: (h, w, in, out) — receptive field multiplies both fans
    rf = math.prod(shape[:-2])
    return shape[-2] * rf, shape[-1] * rf


def xavier_uniform(gain=1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)
    return init


def xavier_normal(gain=1.0):
    def init(key, shape, dtype=jnp.float32):
        std = gain * math.sqrt(2.0 / sum(_fans(shape)))
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)
    return init


def he_uniform():
    def init(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        limit = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)
    return init


def he_normal():
    def init(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        std = math.sqrt(2.0 / fan_in)
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)
    return init
