"""Training configuration (reference: python/hetu/engine/trainer_config.py
TrainingConfig; Hydra YAML sections rpc/ds_parallel/trainer/model map onto
this + ParallelStrategy + model config)."""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class TrainingConfig:
    # batch geometry
    global_batch_size: int = 32
    micro_batch_size: int = 4          # per-dp-replica micro batch
    seq_len: int = 1024
    packing: bool = False

    # optimization
    lr: float = 3e-4
    min_lr_ratio: float = 0.1
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    grad_clip: float = 1.0

    # logging / checkpoint
    log_every: int = 10
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 1000
    ckpt_keep: int = 3

    seed: int = 0
    dropout_deterministic: bool = True  # pretraining default: no dropout

    # pipeline schedule when strategy.pp > 1 (reference:
    # executable_graph.cc:836 GeneratePipedreamFlushSchedule vs :803 GPipe):
    # "gpipe" = scan + autodiff (fastest at small n_micro);
    # "1f1b"  = PipeDream-flush manual-VJP schedule — O(pp) activation
    #           memory instead of O(n_micro); use when n_micro >> pp
    pp_schedule: str = "gpipe"

    # AMP loss scaling (reference: hetu/graph/autocast/gradscaler.h:33):
    # "auto" = dynamic GradScaler iff the model computes in float16 (bf16 has
    # fp32's exponent range and needs none — the TPU default);
    # "dynamic" = always on; "none" = always off
    loss_scale: str = "auto"

    def num_micro_batches(self, dp: int) -> int:
        denom = self.micro_batch_size * dp
        if self.global_batch_size % denom:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} must divide by "
                f"micro_batch_size*dp={denom}")
        return self.global_batch_size // denom
