"""Supervised fine-tuning trainer.

Rebuild of the reference SFTTrainer (reference: python/hetu/engine/
sft_trainer.py:13): next-token loss masked to response tokens only, optional
LoRA so only adapters train.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu import optim
from hetu_tpu.core.mesh import use_mesh
from hetu_tpu.engine.trainer import Trainer
from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.peft.lora import LoRAConfig, LoRAWrappedModel


def mask_prompt_labels(input_ids: np.ndarray, prompt_lens: Sequence[int],
                       pad_id: int = 0) -> np.ndarray:
    """labels with prompt positions (and pads) set to -100 — only response
    tokens contribute loss (the SFT objective)."""
    labels = np.asarray(input_ids, np.int32).copy()
    for i, plen in enumerate(prompt_lens):
        labels[i, :plen] = -100
    labels[np.asarray(input_ids) == pad_id] = -100
    return labels


class SFTTrainer(Trainer):
    """Trainer whose batches carry prompt-masked labels; with `lora`, the
    base model is frozen and only adapters (+ their tiny optimizer state)
    train."""

    def __init__(self, model, config: TrainingConfig, strategy=None,
                 lora: Optional[LoRAConfig] = None, base_params=None, **kw):
        self.lora_cfg = lora
        if lora is not None:
            assert base_params is not None, \
                "LoRA SFT needs pretrained base_params"
            model = LoRAWrappedModel(model, base_params, lora)
        super().__init__(model, config, strategy, **kw)

    def build(self, rng=None):
        if self.lora_cfg is None:
            return super().build(rng)
        # LoRA: params = adapter tree (replicated — it is tiny); base stays
        # in the wrapper closure with its own shardings
        rng = rng if rng is not None else jax.random.key(self.config.seed)
        with use_mesh(self.mesh):
            self.params = self.model.init(rng, mesh=self.mesh)
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self.mesh, P())
            self._pshard = jax.tree.map(lambda _: rep, self.params)
            self._sshard = {
                "step": rep,
                "m": jax.tree.map(lambda _: rep, self.params),
                "v": jax.tree.map(lambda _: rep, self.params),
            }
            self.opt_state = jax.jit(
                self.optimizer.init, out_shardings=self._sshard)(self.params)
            self._step_fn = jax.jit(
                self._train_step,
                out_shardings=(self._pshard, self._sshard, None),
                donate_argnums=(0, 1))
        return self
