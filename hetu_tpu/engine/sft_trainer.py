"""Supervised fine-tuning trainer.

Rebuild of the reference SFTTrainer (reference: python/hetu/engine/
sft_trainer.py:13): next-token loss masked to response tokens only, optional
LoRA so only adapters train.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu import optim
from hetu_tpu.core.mesh import use_mesh
from hetu_tpu.engine.trainer import Trainer
from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.peft.lora import LoRAConfig, LoRAWrappedModel


def mask_prompt_labels(input_ids: np.ndarray, prompt_lens: Sequence[int],
                       seq_lens: Optional[Sequence[int]] = None,
                       pad_id: Optional[int] = 0) -> np.ndarray:
    """labels with prompt positions and padding set to -100 — only response
    tokens contribute loss (the SFT objective).

    Padding is masked BY POSITION: via `seq_lens` when given (exact), else
    by the trailing run of `pad_id` with its FIRST element kept — when
    eos == pad (the common GPT-2/LLaMA setup) that first trailing token is
    the response's terminating eos, which must keep its loss so the model
    learns to stop."""
    ids = np.asarray(input_ids)
    labels = ids.astype(np.int32).copy()
    n, L = labels.shape
    for i, plen in enumerate(prompt_lens):
        labels[i, :plen] = -100
    if seq_lens is not None:
        for i, slen in enumerate(seq_lens):
            labels[i, slen:] = -100
    elif pad_id is not None:
        for i in range(n):
            j = L
            while j > 0 and ids[i, j - 1] == pad_id:
                j -= 1
            # keep position j (the presumed eos terminator) when a run exists
            keep_eos = j < L and j > int(prompt_lens[i])
            labels[i, (j + 1 if keep_eos else j):] = -100
    return labels


class SFTTrainer(Trainer):
    """Trainer whose batches carry prompt-masked labels; with `lora`, the
    base model is frozen and only adapters (+ their tiny optimizer state)
    train."""

    def __init__(self, model, config: TrainingConfig, strategy=None,
                 lora: Optional[LoRAConfig] = None, base_params=None, **kw):
        self.lora_cfg = lora
        if lora is not None:
            assert base_params is not None, \
                "LoRA SFT needs pretrained base_params"
            model = LoRAWrappedModel(model, base_params, lora)
        super().__init__(model, config, strategy, **kw)

    def train_step(self, host_batch: Dict[str, np.ndarray]):
        """SFT batches are mostly prompt+padding: track how many label
        slots actually carry loss, so a run whose response fraction
        collapses (bad masking, over-padding) is visible in the metrics
        registry without stepping through data by hand.  Counted here —
        not in prepare_batch — so report paths (memory/phase/mfu) that
        prepare a batch without training don't skew the ratio."""
        labels = host_batch.get("labels")
        if labels is not None:
            lab = np.asarray(labels)
            masked = int((lab == -100).sum())
            self._registry.inc("sft.masked_tokens", masked)
            self._registry.inc("sft.loss_tokens", int(lab.size - masked))
        return super().train_step(host_batch)

    def _make_shardings(self):
        if self.lora_cfg is None:
            return super()._make_shardings()
        # LoRA: the adapter tree is tiny — replicate it (and its opt state);
        # the frozen base keeps its own shardings inside the wrapper closure
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(self.mesh, P())
        pshard = jax.tree.map(lambda _: rep, self.params)
        sshard = {"step": rep,
                  "m": jax.tree.map(lambda _: rep, self.params),
                  "v": jax.tree.map(lambda _: rep, self.params)}
        return pshard, sshard
