"""Ampelos-style joint hetero planner.

Rebuild of the reference's ILP planner (reference: python/hetu/engine/
strategy_ampelos.py, 1,679 LoC PuLP ILP — jointly chooses TP arrangement,
pipeline grouping, and per-stage layer counts from per-device straggler
ratios; the Malleus `StrategyModel` solves a related DFS form).

TPU version: the decision space per pod slice is small (tp ∈ powers of two,
stage groupings of speed-sorted devices), so the ILP is replaced by exact
enumeration with the same objective — minimize the pipeline-limited step
time, where a stage runs at the speed of its SLOWEST member and contributes
layers[s] / stage_speed[s] work per micro-batch:

    T(cfg) ∝ (max_s layers[s] / speed[s]) * (n_micro + pp - 1) / n_micro

balance_stages (C++ core) provides the optimal layer split for a fixed
grouping, so enumeration only ranges over (tp, grouping).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from hetu_tpu.engine.malleus import MalleusPlanner, StragglerProfile


@dataclasses.dataclass
class AmpelosPlanner:
    num_layers: int
    tp_candidates: Sequence[int] = (1, 2, 4, 8)
    n_micro: Optional[int] = None
    tp_efficiency: float = 0.85   # per-doubling scaling efficiency of TP;
                                  # default is coarse — calibrate it from the
                                  # hardware profile via `from_cost_model`
                                  # (search.calibrate.tp_efficiency_from_cost)

    @staticmethod
    def from_cost_model(num_layers: int, cost, **kw) -> "AmpelosPlanner":
        """tp_efficiency derived from the (measured) compute/ICI numbers in
        the CostModel's HardwareProfile instead of the hardcoded default."""
        from hetu_tpu.search.calibrate import tp_efficiency_from_cost
        return AmpelosPlanner(num_layers=num_layers,
                              tp_efficiency=tp_efficiency_from_cost(cost),
                              **kw)

    def _score(self, cfg: Dict, tp: int) -> float:
        """Pipeline-limited relative step time: a layer's compute is split
        across tp devices (at tp_efficiency scaling), a stage runs at its
        slowest member's speed, and GPipe's fill/drain bubble applies."""
        stages = cfg["stages"]
        pp = len(stages)
        n_micro = self.n_micro or max(2 * pp, 1)
        eff_tp = tp * (self.tp_efficiency ** max(
            int(np.log2(tp)) if tp > 1 else 0, 0))
        bottleneck = max((st["layers"][1] - st["layers"][0]) /
                         (st["speed"] * eff_tp) for st in stages)
        bubble = (n_micro + pp - 1) / n_micro
        return bottleneck * bubble

    def plan(self, speeds: Sequence[float]) -> Dict:
        """speeds: per-device relative speeds (1.0 = healthy).
        Enumerates tp via the Malleus stage planner (one grouping per tp)
        and scores each plan; returns the best hetero ds-parallel config
        with the predicted relative step time in config["score"]."""
        n = len(speeds)
        profile = StragglerProfile(speeds=list(speeds))
        best = None
        for tp in self.tp_candidates:
            if n % tp or n // tp < 1 or self.num_layers < n // tp:
                continue
            try:
                cfg = MalleusPlanner(self.num_layers, tp=tp, dp=1).plan(profile)
            except ValueError:
                continue
            score = self._score(cfg, tp)
            if best is None or score < best[0]:
                best = (score, cfg)
        if best is None:
            raise ValueError(f"no feasible plan for {n} devices, "
                             f"{self.num_layers} layers")
        score, cfg = best
        cfg["score"] = round(float(score), 4)
        return cfg


class AmpelosILP:
    """Exact joint ILP — the direct analog of the reference's PuLP model
    (reference: python/hetu/engine/strategy_ampelos.py): for each candidate
    tp, jointly choose the device->stage assignment AND per-stage layer
    counts minimizing the pipeline bottleneck, then pick the best tp.

    Formulation per tp (pp = n // tp stages):
      binaries x[d,s] (device d in stage s), integers L[s] >= 1,
      continuous t;  minimize t
      s.t.  sum_s x[d,s] = 1;  sum_d x[d,s] = tp;  sum_s L[s] = num_layers;
            L[s] * inv_d - M (1 - x[d,s]) <= t   (stage runs at its
                                                  slowest member)
    Solved with scipy.optimize.milp (HiGHS).  The speed-sorted enumeration
    (AmpelosPlanner) is near-optimal in practice; the ILP certifies it and
    covers corner cases the heuristic cannot (integer layer effects).
    """

    def __init__(self, num_layers: int, tp_candidates=(1, 2, 4, 8),
                 n_micro: Optional[int] = None, tp_efficiency: float = 0.85):
        self.num_layers = num_layers
        self.tp_candidates = tp_candidates
        self.n_micro = n_micro
        self.tp_efficiency = tp_efficiency

    def _solve_tp(self, speeds, tp):
        from scipy.optimize import LinearConstraint, milp
        from scipy.sparse import lil_matrix

        n = len(speeds)
        pp = n // tp
        eff_tp = tp * (self.tp_efficiency ** max(
            int(np.log2(tp)) if tp > 1 else 0, 0))
        inv = [1.0 / (s * eff_tp) for s in speeds]
        nx = n * pp          # x[d,s] at d*pp+s
        nv = nx + pp + 1     # + L[s] + t
        M = self.num_layers * max(inv)

        cons = []
        # each device in exactly one stage
        a = lil_matrix((n, nv))
        for d in range(n):
            for s in range(pp):
                a[d, d * pp + s] = 1.0
        cons.append(LinearConstraint(a.tocsr(), 1.0, 1.0))
        # each stage holds exactly tp devices
        a = lil_matrix((pp, nv))
        for s in range(pp):
            for d in range(n):
                a[s, d * pp + s] = 1.0
        cons.append(LinearConstraint(a.tocsr(), float(tp), float(tp)))
        # layers sum
        a = lil_matrix((1, nv))
        for s in range(pp):
            a[0, nx + s] = 1.0
        cons.append(LinearConstraint(a.tocsr(), float(self.num_layers),
                                     float(self.num_layers)))
        # bottleneck: L[s]*inv_d + M*x[d,s] - t <= M
        a = lil_matrix((n * pp, nv))
        for d in range(n):
            for s in range(pp):
                r = d * pp + s
                a[r, nx + s] = inv[d]
                a[r, d * pp + s] = M
                a[r, nx + pp] = -1.0
        cons.append(LinearConstraint(a.tocsr(), -np.inf, M))

        c = np.zeros(nv)
        c[nx + pp] = 1.0                       # minimize t
        integrality = np.concatenate([
            np.ones(nx), np.ones(pp), np.zeros(1)])
        from scipy.optimize import Bounds
        lb = np.concatenate([np.zeros(nx), np.ones(pp), np.zeros(1)])
        ub = np.concatenate([np.ones(nx),
                             np.full(pp, float(self.num_layers)),
                             np.asarray([np.inf])])
        res = milp(c, constraints=cons, integrality=integrality,
                   bounds=Bounds(lb, ub))
        if not res.success:
            return None
        x = res.x[:nx].reshape(n, pp).round().astype(int)
        L = res.x[nx:nx + pp].round().astype(int)
        members = [list(np.nonzero(x[:, s])[0]) for s in range(pp)]
        # canonical stage order: fastest stage first (matches the sorted
        # enumeration's convention)
        order = sorted(range(pp),
                       key=lambda s: -min(speeds[d] for d in members[s]))
        return (float(res.x[-1]), [int(L[s]) for s in order],
                [[int(d) for d in members[s]] for s in order])

    def plan(self, speeds: Sequence[float]) -> Dict:
        from hetu_tpu.utils.parallel_config import generate_ds_parallel_config
        n = len(speeds)
        best = None
        for tp in self.tp_candidates:
            if n % tp or self.num_layers < n // tp:
                continue
            pp = n // tp
            sol = self._solve_tp(speeds, tp)
            if sol is None:
                continue
            t, layers, members = sol
            n_micro = self.n_micro or max(2 * pp, 1)
            score = t * (n_micro + pp - 1) / n_micro
            if best is None or score < best[0]:
                best = (score, tp, layers, members)
        if best is None:
            raise ValueError(f"no feasible ILP plan for {n} devices, "
                             f"{self.num_layers} layers")
        score, tp, layers, members = best
        # plan-time envelope check (same chokepoint as Trainer/searcher)
        from hetu_tpu.parallel.strategy import validate_stage_plan
        validate_stage_plan(self.num_layers, 1, tp, layers)
        cfg = generate_ds_parallel_config(
            num_layers=self.num_layers, dp=1, tp=tp, pp=len(layers),
            stage_layers=layers)
        for st, mem, spd in zip(cfg["stages"], members,
                                [min(speeds[d] for d in m)
                                 for m in members]):
            st["devices"] = mem
            st["speed"] = round(float(spd), 3)
        cfg["score"] = round(float(score), 4)
        return cfg
