"""Ampelos-style joint hetero planner.

Rebuild of the reference's ILP planner (reference: python/hetu/engine/
strategy_ampelos.py, 1,679 LoC PuLP ILP — jointly chooses TP arrangement,
pipeline grouping, and per-stage layer counts from per-device straggler
ratios; the Malleus `StrategyModel` solves a related DFS form).

TPU version: the decision space per pod slice is small (tp ∈ powers of two,
stage groupings of speed-sorted devices), so the ILP is replaced by exact
enumeration with the same objective — minimize the pipeline-limited step
time, where a stage runs at the speed of its SLOWEST member and contributes
layers[s] / stage_speed[s] work per micro-batch:

    T(cfg) ∝ (max_s layers[s] / speed[s]) * (n_micro + pp - 1) / n_micro

balance_stages (C++ core) provides the optimal layer split for a fixed
grouping, so enumeration only ranges over (tp, grouping).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from hetu_tpu.engine.malleus import MalleusPlanner, StragglerProfile


@dataclasses.dataclass
class AmpelosPlanner:
    num_layers: int
    tp_candidates: Sequence[int] = (1, 2, 4, 8)
    n_micro: Optional[int] = None
    tp_efficiency: float = 0.85   # per-doubling scaling efficiency of TP;
                                  # default is coarse — calibrate it from the
                                  # hardware profile via `from_cost_model`
                                  # (search.calibrate.tp_efficiency_from_cost)

    @staticmethod
    def from_cost_model(num_layers: int, cost, **kw) -> "AmpelosPlanner":
        """tp_efficiency derived from the (measured) compute/ICI numbers in
        the CostModel's HardwareProfile instead of the hardcoded default."""
        from hetu_tpu.search.calibrate import tp_efficiency_from_cost
        return AmpelosPlanner(num_layers=num_layers,
                              tp_efficiency=tp_efficiency_from_cost(cost),
                              **kw)

    def _score(self, cfg: Dict, tp: int) -> float:
        """Pipeline-limited relative step time: a layer's compute is split
        across tp devices (at tp_efficiency scaling), a stage runs at its
        slowest member's speed, and GPipe's fill/drain bubble applies."""
        stages = cfg["stages"]
        pp = len(stages)
        n_micro = self.n_micro or max(2 * pp, 1)
        eff_tp = tp * (self.tp_efficiency ** max(
            int(np.log2(tp)) if tp > 1 else 0, 0))
        bottleneck = max((st["layers"][1] - st["layers"][0]) /
                         (st["speed"] * eff_tp) for st in stages)
        bubble = (n_micro + pp - 1) / n_micro
        return bottleneck * bubble

    def plan(self, speeds: Sequence[float]) -> Dict:
        """speeds: per-device relative speeds (1.0 = healthy).
        Enumerates tp via the Malleus stage planner (one grouping per tp)
        and scores each plan; returns the best hetero ds-parallel config
        with the predicted relative step time in config["score"]."""
        n = len(speeds)
        profile = StragglerProfile(speeds=list(speeds))
        best = None
        for tp in self.tp_candidates:
            if n % tp or n // tp < 1 or self.num_layers < n // tp:
                continue
            try:
                cfg = MalleusPlanner(self.num_layers, tp=tp, dp=1).plan(profile)
            except ValueError:
                continue
            score = self._score(cfg, tp)
            if best is None or score < best[0]:
                best = (score, cfg)
        if best is None:
            raise ValueError(f"no feasible plan for {n} devices, "
                             f"{self.num_layers} layers")
        score, cfg = best
        cfg["score"] = round(float(score), 4)
        return cfg
