"""Malleus-style straggler-resilient planning.

Rebuild of the reference's Malleus planner (reference: python/hetu/engine/
strategy.py:99 StrategyModel — solves TP arrangement + hetero pipeline layer
assignment from per-GPU straggler ratios; engine/straggler.py:20 workload
profiler; flags HETU_STRAGGLER executable_graph.cc:1228).

TPU mapping: per-chip slowdown ratios (from the straggler profiler or the
coordination KV) -> (a) hetero pipeline stage layer counts via the C++
balance_stages core, (b) a strategy recommendation that demotes stragglers to
the least-synchronous axis.  Emits the ds-parallel JSON hetero extension
("stages" with uneven layer ranges) — the contract the runtime consumes.

NOTE round-1 runtime status: the GSPMD pipeline executes EQUAL stage slices;
uneven-stage execution lands with the hetero-exec milestone.  The planner and
config contract are complete so planners/tests/integration don't block on it.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from hetu_tpu.search.dp import balance_stages
from hetu_tpu.utils.parallel_config import generate_ds_parallel_config


@dataclasses.dataclass
class StragglerProfile:
    """Per-device relative speed (1.0 = healthy; reference straggler ratios
    are slowdowns — we store speeds = 1/ratio)."""
    speeds: List[float]

    @staticmethod
    def measure(iters: int = 3) -> "StragglerProfile":
        """Measure per-local-device matmul speed (reference:
        engine/straggler.py Straggler workload runner)."""
        import jax
        import jax.numpy as jnp

        speeds = []
        for dev in jax.local_devices():
            a = jax.device_put(jnp.ones((1024, 1024), jnp.float32), dev)
            f = jax.jit(lambda a: jnp.sum(a @ a), device=dev)
            float(f(a))
            times = []
            for _ in range(iters):
                t = time.perf_counter()
                float(f(a))
                times.append(time.perf_counter() - t)
            speeds.append(1.0 / max(min(times), 1e-9))
        m = max(speeds)
        return StragglerProfile([s / m for s in speeds])


class MalleusPlanner:
    """ratios -> hetero strategy plan (reference: StrategyModel.solve)."""

    def __init__(self, num_layers: int, tp: int = 1, dp: int = 1):
        self.num_layers = num_layers
        self.tp = tp
        self.dp = dp

    def plan(self, profile: StragglerProfile) -> Dict:
        """Group devices into pipeline stages and assign layer counts
        proportional to measured stage speed."""
        speeds = profile.speeds
        n = len(speeds)
        per_stage = self.tp * self.dp
        if n % per_stage:
            raise ValueError(f"{n} devices do not divide into stages of "
                             f"{per_stage}")
        pp = n // per_stage
        # sort devices so similar speeds share a stage (a stage runs at the
        # speed of its slowest member — grouping stragglers together wastes
        # the least, the Malleus insight)
        order = np.argsort(speeds)[::-1]
        stage_speed = []
        stage_members: List[List[int]] = []
        for p in range(pp):
            members = order[p * per_stage:(p + 1) * per_stage].tolist()
            stage_members.append(members)
            stage_speed.append(min(speeds[i] for i in members))
        stage_layers = balance_stages(self.num_layers, stage_speed)
        # plan-time envelope check (the shared chokepoint): a degenerate
        # balance (zero-layer stage, bad stage count) is rejected HERE,
        # not when the pipeline engine traces
        from hetu_tpu.parallel.strategy import validate_stage_plan
        validate_stage_plan(self.num_layers, self.dp, self.tp, stage_layers)
        cfg = generate_ds_parallel_config(
            num_layers=self.num_layers, dp=self.dp, tp=self.tp, pp=pp,
            stage_layers=stage_layers)
        for st, members, spd in zip(cfg["stages"], stage_members, stage_speed):
            st["devices"] = members
            st["speed"] = round(float(spd), 3)
        return cfg


def plan_hetero_dp_shares(profile: StragglerProfile,
                          group_devices: Sequence[Sequence[int]],
                          group_dp: Sequence[int],
                          total_rows: int) -> List[int]:
    """Assign per-group batch rows proportional to measured group throughput
    (reference: Malleus's uneven batch shares across unequal device groups,
    python/hetu/engine/strategy.py:99).

    Each group's devices are organised as dp replicas of tp members; a tp
    replica runs at its slowest member's speed, so group throughput is the
    sum of per-replica min speeds.  Every group's row count is a positive
    multiple of its dp degree (so the slice shards evenly over the group's
    dp axis); total_rows must be expressible that way or this raises.
    """
    speeds = profile.speeds
    rates = []
    for devs, dp in zip(group_devices, group_dp):
        if len(devs) % dp:
            raise ValueError(f"group of {len(devs)} devices with dp={dp}")
        tp = len(devs) // dp
        rate = sum(min(speeds[i] for i in devs[r * tp:(r + 1) * tp])
                   for r in range(dp))
        rates.append(rate)
    # proportional target, then snap to dp multiples: exact DP over
    # "rows_g = positive multiple of dp_g, sum == total_rows" minimizing
    # total deviation from the throughput-proportional target (a greedy
    # floor+fixup can wrongly reject feasible configs, e.g. dp=[2,3]
    # total=9 with skewed rates)
    n = len(rates)
    if total_rows < sum(group_dp):
        raise ValueError(
            f"total_rows={total_rows} cannot give every group one row per "
            f"dp replica (need >= {sum(group_dp)})")
    s = sum(rates)
    target = [total_rows * r / s for r in rates]
    INF = float("inf")
    # cost[t] = best deviation allocating t rows to groups[0..g]; choice
    # tracks the per-group row count realizing it
    cost = [INF] * (total_rows + 1)
    cost[0] = 0.0
    choice: List[Dict[int, int]] = []
    for g in range(n):
        dp = group_dp[g]
        nxt = [INF] * (total_rows + 1)
        pick: Dict[int, int] = {}
        for t in range(total_rows + 1):
            if cost[t] is INF:
                continue
            k = dp
            while t + k <= total_rows:
                c = cost[t] + abs(k - target[g])
                if c < nxt[t + k]:
                    nxt[t + k] = c
                    pick[t + k] = k
                k += dp
        cost = nxt
        choice.append(pick)
    if cost[total_rows] is INF:
        raise ValueError(
            f"total_rows={total_rows} is not expressible as positive "
            f"multiples of group dp degrees {list(group_dp)}")
    rows = [0] * n
    t = total_rows
    for g in range(n - 1, -1, -1):
        rows[g] = choice[g][t]
        t -= rows[g]
    return rows
