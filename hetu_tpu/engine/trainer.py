"""Trainer: the end-to-end training engine.

Rebuild of the reference Trainer (reference: python/hetu/engine/trainer.py:67 —
build :187 create graph under contexts, train :655 step loop,
prepare_feed_dict :465 bucketing/packing/cp-split, _train :305 graph.run).
The graph-compile machinery collapses into jit: `build()` materializes sharded
params + ZeRO-sharded optimizer state; the train step (micro-batch
grad-accumulation scan -> clip -> AdamW) is one compiled program per shape
plan, cached in the PlanPool.

Micro-batching: the reference's PipeDream-flush interpreter consumes micro
batches sequentially (executable_graph.cc:1354-1374 CrucialRun); without
pipeline stages the TPU equivalent is a lax.scan over the micro dim
accumulating grads — identical arithmetic, one XLA program.  With pipeline
stages the pipeline engine (hetu_tpu.parallel.pipeline) replaces the scan.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.core.mesh import use_mesh
from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.optim.optimizer import zero_shardings
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.utils.checkpoint import CheckpointManager
from hetu_tpu.utils.logging import get_logger

logger = get_logger("trainer")


from hetu_tpu.utils.profiling import device_mem_bytes as _device_mem_bytes


class Trainer:
    def __init__(self, model, config: TrainingConfig,
                 strategy: Optional[ParallelStrategy] = None,
                 mesh=None):
        self.model = model
        self.config = config
        self.strategy = strategy or getattr(model, "strategy", ParallelStrategy())
        self._cp_split = None
        if self.strategy.cp > 1:
            # the trainer owns the data layout: resolve the CP split pattern
            # once (reference: HETU_PARALLEL_ATTN_SPLIT drives both the data
            # split and the ring's AttnInfo masks), reorder batches to match
            # (prepare_batch) and declare it around the traced step calls so
            # the ring schedules only live tiles (_declared scope below).
            from hetu_tpu.utils import flags as _flags
            self._cp_split = (self.strategy.cp_split
                              or _flags.str_flag("HETU_TPU_CP_SPLIT"))
            if self._cp_split != "normal":
                # the default differs from the reference's NORMAL: make the
                # host-side seq permutation + label pre-shift visible so
                # tooling that assumes positional order isn't surprised
                logger.info(
                    f"cp={self.strategy.cp}: seq axis host-permuted to the "
                    f"'{self._cp_split}' split (labels pre-shifted); set "
                    f"strategy.cp_split or HETU_TPU_CP_SPLIT to change")
        self._cp_perm_cache = {}
        self._cp_layout_used = False   # a step traced under this layout?
        # non-contiguous CP layouts require host pre-shifted labels
        # (_cp_reorder) — array adjacency stops meaning token adjacency
        self._labels_shifted = self._cp_split not in (None, "normal")
        self.mesh = mesh if mesh is not None else self.strategy.build_mesh()
        self.params = None
        self.opt_state = None
        self._step_fn = None
        self._ckpt = (CheckpointManager(config.ckpt_dir, config.ckpt_keep)
                      if config.ckpt_dir else None)
        self.global_step = 0

        if config.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"pp_schedule must be 'gpipe' or '1f1b', got "
                f"{config.pp_schedule!r}")
        if (config.pp_schedule == "1f1b" and self.strategy.pp > 1
                and not hasattr(model, "pipeline_train_grads")):
            raise ValueError(
                f"pp_schedule='1f1b' needs {type(model).__name__}"
                ".pipeline_train_grads (use 'gpipe')")

        if config.loss_scale not in ("auto", "dynamic", "none"):
            raise ValueError(f"loss_scale must be auto|dynamic|none, got "
                             f"{config.loss_scale!r}")

        # the ONE plan-time envelope chokepoint (StrategyValidationError
        # here, not a trace-time surprise later) — shared with the
        # searcher, Malleus/Ampelos and the batch dispatcher
        self.strategy.validate(
            getattr(model, "config", None),
            pp_schedule=config.pp_schedule,
            n_micro=config.num_micro_batches(max(self.strategy.dp, 1)),
            global_batch=config.global_batch_size,
            seq_len=config.seq_len,
            deterministic=config.dropout_deterministic)
        compute_dtype = getattr(getattr(model, "config", None),
                                "compute_dtype", None)
        use_scaler = (config.loss_scale == "dynamic"
                      or (config.loss_scale == "auto"
                          and compute_dtype == jnp.float16))
        from hetu_tpu.optim.grad_scaler import GradScaler
        self._scaler = GradScaler() if use_scaler else None
        self.scaler_state = None

        # -- compressed DP grad sync (hetu_tpu/comm, HETU_TPU_GRAD_COMPRESS;
        # docs/comm_compression.md).  "none" is the byte-identical default:
        # the branch below is python-level, so no traced program changes.
        from hetu_tpu.utils import flags as _flags
        self._grad_compress = _flags.str_flag("HETU_TPU_GRAD_COMPRESS")
        self._bucket_plan = None
        if self._grad_compress != "none":
            st = self.strategy
            if (st.tp > 1 or st.cp > 1 or st.pp > 1 or st.ep > 1
                    or st.zero_stage >= 3):
                # the quantized sync runs the per-replica grad computation
                # inside a shard_map over dp with replicated params — only
                # homogeneous DP/ZeRO-1/2 fits that envelope (the hetero-DP
                # BRIDGE compresses independently in parallel/hetero_dp.py)
                raise ValueError(
                    f"HETU_TPU_GRAD_COMPRESS={self._grad_compress!r} "
                    f"supports homogeneous DP/ZeRO-1/2 only (dp>1, "
                    f"tp=cp=pp=ep=1, zero_stage<3); got "
                    f"{self.strategy.describe()}")
            if st.dp <= 1:
                logger.info(
                    f"HETU_TPU_GRAD_COMPRESS={self._grad_compress} ignored: "
                    f"dp=1 has no grad sync to compress")
                self._grad_compress = "none"
        # -- two-level (HetCCL) routing of the compressed sync's ring
        # schedule (HETU_TPU_COMM_TOPOLOGY + the hardware profile's
        # `topology` section, comm/topology.py).  "flat" = byte-identical.
        self._comm_topology = None
        if (self._grad_compress == "none"
                and _flags.str_flag("HETU_TPU_COMM_TOPOLOGY") == "two_level"):
            # the flag only routes the COMPRESSED sync's ring schedule —
            # without grad compression nothing changes; say so loudly
            logger.warning(
                "HETU_TPU_COMM_TOPOLOGY=two_level has no effect without "
                "HETU_TPU_GRAD_COMPRESS (the flag routes the compressed "
                "DP sync's ring schedule); running the plain f32 sync")
        if (self._grad_compress != "none"
                and _flags.str_flag("HETU_TPU_COMM_TOPOLOGY") == "two_level"):
            from hetu_tpu.comm.topology import load_topology
            topo = load_topology()
            if topo is None:
                raise ValueError(
                    "HETU_TPU_COMM_TOPOLOGY=two_level needs a `topology` "
                    "section in the hardware profile "
                    "(hardware_profile_v5e.json / HETU_TPU_HW_PROFILE)")
            if topo.applies(self.strategy.dp):
                self._comm_topology = topo
            else:
                logger.info(
                    f"two-level topology (slice_devices="
                    f"{topo.slice_devices}) does not apply to dp="
                    f"{self.strategy.dp}; using the flat ring")
        # -- quantized ZeRO-1/2 param refresh (optim/zero_refresh.py,
        # HETU_TPU_ZERO_COMPRESS): the explicit delta-gather replaces
        # GSPMD's f32 param all-gather.  Same envelope as the grad sync.
        self._zero_compress = _flags.str_flag("HETU_TPU_ZERO_COMPRESS")
        if self._zero_compress != "none":
            st = self.strategy
            if (st.tp > 1 or st.cp > 1 or st.pp > 1 or st.ep > 1
                    or st.zero_stage >= 3):
                raise ValueError(
                    f"HETU_TPU_ZERO_COMPRESS={self._zero_compress!r} "
                    f"supports homogeneous DP ZeRO-1/2 only (dp>1, "
                    f"tp=cp=pp=ep=1, zero_stage<3); got "
                    f"{self.strategy.describe()}")
            if st.dp > 1 and not st.zero:
                raise ValueError(
                    f"HETU_TPU_ZERO_COMPRESS={self._zero_compress!r} "
                    f"compresses the ZeRO param refresh, but this strategy "
                    f"has zero=False (no refresh exists); enable ZeRO or "
                    f"unset the flag")
            if st.dp <= 1:
                logger.info(
                    f"HETU_TPU_ZERO_COMPRESS={self._zero_compress} ignored: "
                    f"dp=1 has no param refresh to compress")
                self._zero_compress = "none"

        from hetu_tpu.utils.profiling import StepProfiler
        self.profiler = StepProfiler()
        # -- telemetry (hetu_tpu.obs): the metrics registry is process-
        # global (rpc/elastic write into the same one); the RunLog lives
        # next to the checkpoints so every run leaves a machine-readable
        # trace (docs/observability.md)
        from hetu_tpu.obs.metrics import get_registry
        from hetu_tpu.obs.runlog import RunLog, default_runlog_path
        self._registry = get_registry()
        rl_path = default_runlog_path(config.ckpt_dir)
        # one writer per run: in multi-process runs only process 0 logs
        # (the same gate the checkpoint writer uses) — N appenders to one
        # JSONL would duplicate every record Nx and can tear lines on
        # shared filesystems
        if rl_path and jax.process_index() != 0:
            rl_path = None
        # the RunLog keeps an in-memory tail for the cluster telemetry
        # push only when pushing is on (obs.aggregate drains it)
        from hetu_tpu.obs.aggregate import push_interval
        tail = 256 if push_interval() > 0 else 0
        self.run_log = (RunLog(rl_path, tail_records=tail)
                        if rl_path else None)
        # -- training health monitor (obs.health, HETU_TPU_HEALTH): None
        # unless the flag is set — the per-step cost of "off" is one None
        # check.  On anomalies of the severe kinds it emergency-saves
        # through the PR 3 checkpoint path (best-effort, never raises).
        from hetu_tpu.obs.health import maybe_health_monitor
        self._health = maybe_health_monitor(
            runlog=self.run_log,
            emergency_hook=(self._health_emergency_save
                            if self._ckpt is not None else None))
        # -- numerics observatory (obs/numerics.py, HETU_TPU_NUMERICS):
        # read ONCE at build — the identity contract is that unset means
        # the step wrapper never runs and the traced program is
        # byte-identical to the seed.  The numerics health detectors
        # (underflow_creep, quant_snr_collapse, ef_residual_blowup,
        # router_collapse) ride the same HETU_TPU_HEALTH gate as the
        # scalar monitor above.
        from hetu_tpu.obs.numerics import numerics_enabled, record_every
        self._numerics = numerics_enabled()
        self._numerics_every = record_every()
        from hetu_tpu.obs.health import maybe_numerics_health_monitor
        self._num_health = (maybe_numerics_health_monitor(
            runlog=self.run_log) if self._numerics else None)
        # loss-scale transition tracking (scaler RunLog events +
        # scaler.loss_scale gauge — active whenever AMP is, numerics or
        # not: scale dynamics were previously unobservable)
        self._last_loss_scale = None
        self._pending_scale = None
        c = config
        self.optimizer = optim.AdamW(
            lr=optim.cosine_schedule(c.lr, c.warmup_steps, c.total_steps,
                                     c.min_lr_ratio),
            b1=c.beta1, b2=c.beta2, eps=c.eps, weight_decay=c.weight_decay)

    def _health_emergency_save(self):
        """Bank state NOW (the HealthMonitor's emergency hook for NaN
        anomalies): a synchronous save so a dying run loses at most the
        poisoned step, not a checkpoint interval."""
        self.save(wait=True)

    def _declared(self):
        """Context declaring this trainer's CP data layout to the ring for
        the duration of a (possibly tracing) step call."""
        from hetu_tpu.parallel.ring_attention import declared_cp_split
        return declared_cp_split(self._cp_split)

    # ------------------------------------------------------------------
    def _make_shardings(self):
        """(param_shardings, opt_state_shardings) — overridable (e.g. the
        LoRA SFT trainer replicates its tiny adapter tree)."""
        mesh, st = self.mesh, self.strategy
        pshard = self.model.shardings(mesh)
        abstract = self.model.abstract_params()
        if st.zero:
            sshard = {
                "step": NamedSharding(mesh, P()),
                "m": zero_shardings(pshard, abstract, mesh, "dp"),
                "v": zero_shardings(pshard, abstract, mesh, "dp"),
            }
        else:
            sshard = {"step": NamedSharding(mesh, P()),
                      "m": pshard, "v": pshard}
        return pshard, sshard

    def build(self, rng: Optional[jax.Array] = None):
        """Materialize sharded params/opt state and compile the step."""
        c, mesh = self.config, self.mesh
        rng = rng if rng is not None else jax.random.key(c.seed)

        with use_mesh(mesh):
            self.params = self.model.init(rng, mesh=mesh)
            self._pshard, self._sshard = self._make_shardings()
            self.opt_state = jax.jit(
                self.optimizer.init, out_shardings=self._sshard)(self.params)
            if self._grad_compress != "none":
                # bucket layout is a compile-time constant: one plan from
                # the abstract grad shapes, padded so every bucket chunks
                # cleanly into dp rows of whole quantization blocks
                from hetu_tpu.comm import DEFAULT_BLOCK, BucketPlan
                dp = self.strategy.dp
                self._bucket_plan = BucketPlan.build(
                    jax.tree.map(
                        lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32),
                        self.model.abstract_params()),
                    multiple=dp * DEFAULT_BLOCK)
                from hetu_tpu.comm.grad_sync import uses_error_feedback
                if uses_error_feedback(self._grad_compress):
                    # the EF residuals ride in the optimizer-state pytree:
                    # they checkpoint, donate and reshard with the moments
                    from hetu_tpu.optim.optimizer import ef_state_entry
                    ef0, ef_sh = ef_state_entry(
                        self._bucket_plan, mesh, dp,
                        topology=self._comm_topology)
                    self.opt_state["ef"] = ef0
                    self._sshard = dict(self._sshard, ef=ef_sh)
            if self._zero_compress != "none":
                # static slicing/gather plan of the quantized refresh:
                # which dim zero_shardings split over dp, per leaf
                from hetu_tpu.optim.zero_refresh import (refresh_dims,
                                                         refresh_specs)
                self._zr_dims = refresh_dims(self._sshard["m"])
                self._zr_specs = refresh_specs(self._sshard["m"])
            if self._scaler is not None:
                self.scaler_state = jax.device_put(
                    self._scaler.init(), NamedSharding(mesh, P()))
            self._step_fn = self._make_step_pool(self._pshard, self._sshard)
        from hetu_tpu.utils import flags
        sched_path = flags.str_flag("HETU_TPU_TRACE_SCHEDULE")
        if sched_path and self.strategy.pp > 1:
            # render THIS run's micro-batch schedule (per-stage fwd/bwd/
            # bubble lanes) for Perfetto — hardware-free, from the same
            # validity masks the pipeline engines scan over
            from hetu_tpu.obs.trace import pipeline_schedule_trace
            n_micro = c.num_micro_batches(max(self.strategy.dp, 1))
            try:
                pipeline_schedule_trace(
                    self.strategy.pp, n_micro,
                    schedule=c.pp_schedule).save(sched_path)
                logger.info(
                    f"pipeline schedule trace written to {sched_path}")
            except OSError as e:
                # telemetry must not be fatal: a bad trace path costs the
                # render, never the run
                logger.warning(f"schedule trace to {sched_path} "
                               f"failed: {e!r}")
        return self

    def _make_step_pool(self, pshard, sshard):
        """One compiled train step per batch-shape signature (the
        reference's ExecGraphPlan pool, define_and_run_graph.cc:1174/:303):
        multi-bucket training compiles once per bucket length and dispatches
        per batch, with the pool's retrace guard replacing jit's silent
        recompiles."""
        from hetu_tpu.engine.plan_pool import PlanPool
        return PlanPool(
            self._train_step,
            jit_kwargs=dict(out_shardings=(pshard, sshard, None, None),
                            donate_argnums=(0, 1)),
            max_plans=self._plan_cap(),
            name="train_step",
            # dispatch keys hash the BATCHES pytree only — params/opt_state
            # shapes never change within one pool
            key_argnums=(2,),
            on_compile=self._on_plan_compile)

    @staticmethod
    def _plan_cap():
        """HETU_TPU_MAX_PLANS resolution — one source of truth for the
        train and eval pools."""
        from hetu_tpu.utils import flags
        return flags.int_flag("HETU_TPU_MAX_PLANS") or None

    def _plan_dispatch_key(self):
        """Traced-behavior inputs that are NOT visible in the batch shapes:
        the CP data layout declared around the trace (it changes the ring's
        static tile masks and the label convention)."""
        return (self._cp_split, self._labels_shifted)

    def _on_plan_compile(self, pool_name, key, plan, compile_s):
        """PlanPool hook: every fresh XLA compile leaves a run-event record
        with XLA's FLOP count and a hardware-free estimated MFU (the
        roofline over cost_analysis — obs.mfu), so BENCH tooling can
        attribute cost even when the step never executes on hardware."""
        self._registry.inc("trainer.compiles", pool=pool_name)
        self._registry.observe("trainer.compile_s", compile_s,
                               pool=pool_name)
        from hetu_tpu.utils import flags as _flags
        est, comm = {}, {}
        # ONE lazy as_text() shared by the comm analysis and the
        # profiler — stringifying a large module twice per compile is
        # the cost HETU_TPU_COMM_ANALYZE=0 exists to avoid
        hlo_txt = [None]

        def _hlo_text():
            if hlo_txt[0] is None:
                hlo_txt[0] = plan.as_text()
            return hlo_txt[0]
        # the est/comm numbers feed BOTH the compile run-event and the
        # declared-budget check — a budget with no RunLog still needs
        # them (enforcement must not depend on where the log lives)
        if (self.run_log is not None
                or _flags.str_flag("HETU_TPU_BUDGETS")):
            from hetu_tpu.obs.mfu import estimate_from_compiled
            try:
                # phase attribution parses the full HLO text — too heavy
                # for a per-compile hook on big programs; mfu_report()
                # does the phase-resolved version on demand
                est = estimate_from_compiled(plan, with_phases=False)
            except Exception:
                est = {}
            try:
                # bytes-on-wire of this plan's collectives (obs.comm) —
                # this is where a HETU_TPU_GRAD_COMPRESS win becomes a
                # RunLog fact.  It costs the one shared as_text() per
                # fresh compile; that is once per plan, not per step,
                # but very large programs can opt out via
                # HETU_TPU_COMM_ANALYZE=0
                if _flags.bool_flag("HETU_TPU_COMM_ANALYZE"):
                    from hetu_tpu.obs.comm import collective_report
                    comm = collective_report(_hlo_text())
            except Exception:
                comm = {}
        if self.run_log is not None:
            self.run_log.log(
                "compile", name=pool_name, plan=str(key)[:500],
                compile_s=compile_s, flops=est.get("flops_per_step"),
                estimated_mfu=est.get("estimated_mfu"),
                estimated_step_s=est.get("estimated_step_s"),
                comm_bytes=comm.get("total_wire_bytes"),
                comm_s_est=comm.get("predicted_comm_s"),
                collectives={op: rec["count"] for op, rec in
                             (comm.get("collectives") or {}).items()}
                or None,
                grad_compress=(self._grad_compress
                               if self._grad_compress != "none"
                               else None),
                zero_compress=(self._zero_compress
                               if self._zero_compress != "none"
                               else None),
                comm_topology=("two_level"
                               if self._comm_topology is not None
                               else None))
        # analytic step profile (HETU_TPU_PROFILE): per-layer HLO
        # attribution + peak-HBM -> a schema-versioned `profile` record
        # next to the compile event, then the declared-budget check
        # (both run with or without a RunLog — enforcement must not
        # depend on where the log lives)
        prof = self._maybe_profile(plan, _hlo_text)
        if prof is not None and self.run_log is not None:
            self.run_log.log("profile", name=pool_name,
                             plan=str(key)[:500], **prof)
        # graph-contract lints (HETU_TPU_LINT): donation / replication /
        # dtype / scope-coverage over this plan's optimized HLO — same
        # shared as_text, pure post-compile analysis
        lint_rec = self._maybe_lint(pool_name, _hlo_text)
        if lint_rec is not None and self.run_log is not None:
            self.run_log.log("lint", name=pool_name,
                             plan=str(key)[:500], **lint_rec)
        self._check_budgets(pool_name, prof, est, comm)

    def _maybe_profile(self, plan, hlo_text_fn=None):
        """The flag-gated per-compile analytic profile
        (obs.hlo_profile.profile_record), or None.  Costs one more walk
        of the HLO text per FRESH compile; pure post-compile analysis —
        the traced program is identical with the flag on or off."""
        from hetu_tpu.utils import flags as _flags
        if not _flags.bool_flag("HETU_TPU_PROFILE"):
            return None
        try:
            from hetu_tpu.obs.hlo_profile import (flame_trace,
                                                  layer_profile,
                                                  profile_record)
            # ONE as_text (shared with the hook's comm analysis) + ONE
            # attribution walk, shared by the record and the flame graph
            txt = hlo_text_fn() if hlo_text_fn is not None \
                else plan.as_text()
            full = layer_profile(txt)
            prof = profile_record(
                plan, top_k=_flags.int_flag("HETU_TPU_PROFILE_TOPK"),
                profile=full, text=txt)
            trace_path = _flags.str_flag("HETU_TPU_PROFILE_TRACE")
            if trace_path:
                try:
                    flame_trace(full).save(trace_path)
                    logger.info(
                        f"analytic flame graph written to {trace_path}")
                except OSError as e:
                    logger.warning(f"flame graph to {trace_path} "
                                   f"failed: {e!r}")
            return prof
        except Exception as e:
            logger.warning(f"per-compile profile failed: {e!r}")
            return None

    def _maybe_lint(self, pool_name, hlo_text_fn):
        """The flag-gated per-compile graph-contract lint record
        (hetu_tpu/analysis/hlo_lints over this plan's optimized HLO), or
        None.  Error findings log loudly and count `lint.errors` but
        NEVER fail the step — tools_lint.py / the tier-1 acceptance test
        are the enforcing surfaces; a training run only observes.  Pure
        post-compile HLO-text analysis: the traced program is identical
        with the flag on or off (identity contract in utils/flags.py)."""
        from hetu_tpu.utils import flags as _flags
        if not _flags.bool_flag("HETU_TPU_LINT"):
            return None
        try:
            from hetu_tpu.analysis.findings import lint_record
            from hetu_tpu.analysis.hlo_lints import dtype_token, lint_hlo
            expected = dtype_token(getattr(
                getattr(self.model, "config", None), "compute_dtype", None))
            findings = lint_hlo(hlo_text_fn(), expected_dtype=expected,
                                program=pool_name)
            rec = lint_record(findings)
            if rec["findings"]:
                self._registry.inc("lint.findings", rec["findings"],
                                   pool=pool_name)
            if rec["errors"]:
                self._registry.inc("lint.errors", rec["errors"],
                                   pool=pool_name)
                for msg in rec.get("messages", []):
                    logger.warning(f"lint ({pool_name}): {msg}")
            if rec["warnings"]:
                self._registry.inc("lint.warnings", rec["warnings"],
                                   pool=pool_name)
            return rec
        except Exception as e:
            logger.warning(f"per-compile lint failed: {e!r}")
            return None

    def _check_budgets(self, pool_name, prof, est, comm):
        """Check this compile's hardware-free metrics against the
        declared perf budget (HETU_TPU_BUDGETS): breaches count
        `budget.breaches`, leave a `budget` run event, log loudly, and
        — only when the budget file declares `"enforce": true` — raise
        BudgetError.  Unset flag = one str check, nothing else."""
        from hetu_tpu.utils import flags as _flags
        if not _flags.str_flag("HETU_TPU_BUDGETS"):
            return
        from hetu_tpu.obs.budget import (BudgetError, PerfBudget,
                                         check_absolute, enforce,
                                         extract_metrics,
                                         summarize_breaches)
        try:
            budget = PerfBudget.load()
        except (OSError, ValueError) as e:
            # a typo'd budget must not silently watch nothing (the
            # loader's own contract): surface it as the one hook error
            # the PlanPool lets through
            raise BudgetError(
                f"invalid perf budget "
                f"({_flags.str_flag('HETU_TPU_BUDGETS')}): {e}") from e
        try:
            # estimator precedence is FIXED so a budget verdict cannot
            # flip with HETU_TPU_PROFILE: step time always comes from
            # the whole-program roofline (est) and comm bytes from the
            # analyzer — the profile only contributes the metrics no
            # other estimator produces (peak HBM)
            metrics = {}
            if est:
                metrics["estimated_mfu"] = est.get("estimated_mfu")
                metrics["step_time_s"] = est.get("estimated_step_s")
            if comm:
                metrics["comm_bytes"] = comm.get("total_wire_bytes")
            for k, v in (extract_metrics(prof) if prof else {}).items():
                if metrics.get(k) is None:
                    metrics[k] = v
            metrics = {k: v for k, v in metrics.items() if v is not None}
            breaches = check_absolute(metrics, budget)
            from hetu_tpu.obs.budget import ABSOLUTE_CEILINGS
            missing = [k for k, attr, _kind in ABSOLUTE_CEILINGS
                       if getattr(budget, attr) is not None
                       and k not in metrics]
            if missing:
                # a declared ceiling that silently goes unchecked is the
                # failure mode the sentinel exists to prevent — say so
                logger.warning(
                    f"budget ceilings on {missing} could not be checked "
                    f"for compile {pool_name} (metric unavailable; "
                    f"peak_hbm_bytes needs HETU_TPU_PROFILE=1)")
        except Exception as e:
            logger.warning(f"budget check failed: {e!r}")
            return
        self._registry.inc("budget.checks")
        if breaches:
            self._registry.inc("budget.breaches", len(breaches))
            logger.error(f"perf budget breached (compile {pool_name}):\n"
                         + summarize_breaches(breaches))
        if self.run_log is not None:
            self.run_log.log("budget", name=pool_name, ok=not breaches,
                             breaches=breaches or None,
                             budget=budget.source)
        enforce(breaches, budget)

    # ------------------------------------------------------------------
    def _loss_fn(self, params, batch, rng):
        """Returns (sum_loss, token_count): micro batches are weighted by
        their true (non-pad) token counts so accumulation == full batch."""
        c = self.config
        return self.model(
            params, batch["input_ids"], labels=batch["labels"],
            position_ids=batch.get("position_ids"),
            segment_ids=batch.get("segment_ids"),
            rng=rng, deterministic=c.dropout_deterministic,
            loss_reduction="sum", labels_shifted=self._labels_shifted)

    def _train_step(self, params, opt_state, batches, rng, scaler_state):
        """The traced step the PlanPool jits.  With HETU_TPU_NUMERICS on
        it wraps the real step in a numerics collector: taps anywhere in
        the step's trace accumulate into an auxiliary stats pytree that
        rides out under ``metrics["numerics"]`` (donation-safe — metrics
        are never donated; host-fetched only on record boundaries).
        Flag unset: the wrapper never runs, the trace is byte-identical
        (registered identity contract, swept by tools_lint --flags)."""
        if not self._numerics:
            return self._train_step_impl(params, opt_state, batches, rng,
                                         scaler_state)
        from hetu_tpu.obs import numerics as _numerics
        with _numerics.collecting() as col:
            params, opt_state, metrics, scaler_state = \
                self._train_step_impl(params, opt_state, batches, rng,
                                      scaler_state)
            stats = col.finalize()
            if stats:
                metrics = dict(metrics, numerics=stats)
        return params, opt_state, metrics, scaler_state

    def _train_step_impl(self, params, opt_state, batches, rng,
                         scaler_state):
        """batches: pytree with leading micro-batch dim [n_micro, mb, seq]."""
        c = self.config
        lead = jax.tree.leaves(batches)[0]
        n_micro = lead.shape[0]
        # the EF residuals ride in opt_state but belong to the SYNC, not
        # the optimizer update: lift them out here, reattach updated ones
        # below ({} when mode "int8" carries no residuals)
        ef_state, new_ef = {}, {}
        if self._grad_compress != "none":
            ef_state = opt_state.pop("ef", {})
        if self._scaler is not None:
            # normalize the scale by the STATIC token-slot count so fp16
            # cotangent magnitudes are batch-size-independent (the torch
            # mean-loss convention) — the sum-loss would push the effective
            # scale up by O(tokens) and overflow before calibrating
            slots = float(n_micro * lead.shape[1] * max(lead.shape[2] - 1, 1))
            scale = scaler_state["scale"] / slots
        else:
            scale = jnp.asarray(1.0, jnp.float32)

        if self.strategy.pp > 1:
            # pipeline mode: micro-batching happens INSIDE the model's
            # circular pipeline (reference CrucialRun micro loop); feed the
            # whole global batch at once
            flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batches.items()}

            if c.pp_schedule == "1f1b":
                # PipeDream-flush manual-VJP schedule (reference:
                # executable_graph.cc:836) — grads come back directly;
                # dropout masks replay exactly in the backward visit (the
                # rng rides the saved token stream)
                (lsum, csum), grads = self.model.pipeline_train_grads(
                    params, flat["input_ids"], flat["labels"],
                    position_ids=flat.get("position_ids"),
                    segment_ids=flat.get("segment_ids"), n_micro=n_micro,
                    labels_shifted=self._labels_shifted,
                    loss_scale=scale,
                    rng=None if c.dropout_deterministic else rng)
            else:
                def pp_loss(p):
                    lsum_, csum_ = self.model(
                        p, flat["input_ids"], labels=flat["labels"],
                        position_ids=flat.get("position_ids"),
                        segment_ids=flat.get("segment_ids"),
                        rng=None if c.dropout_deterministic else rng,
                        deterministic=c.dropout_deterministic,
                        loss_reduction="sum", n_micro=n_micro,
                        labels_shifted=self._labels_shifted)
                    # loss SCALING happens on the fp32 sum (gradscaler.h:33)
                    return lsum_.astype(jnp.float32) * scale, (lsum_, csum_)

                (_, (lsum, csum)), grads = jax.value_and_grad(
                    pp_loss, has_aux=True)(params)
        elif self._grad_compress != "none":
            # quantized DP sync (comm/grad_sync.py): per-replica grads in a
            # shard_map over dp, then int8 all-to-all/all-gather instead of
            # the f32 all-reduce GSPMD would insert
            keys = jax.random.split(rng, n_micro)
            grads, lsum, csum, new_ef = self._compressed_grads(
                params, batches, keys, scale, ef_state)
        else:
            keys = jax.random.split(rng, n_micro)
            grads, lsum, csum, mstats = self._accumulate_grads(
                params, batches, keys, scale)
            if mstats:
                # model-scope taps drained inside the micro scan, stacked
                # [n_micro, ...] by its ys — fold per stat rule and hand
                # to the ambient collector (no-op when numerics is off)
                from hetu_tpu.obs import numerics as _numerics
                _numerics.merge(_numerics.reduce_stacked(mstats))

        denom = jnp.maximum(csum, 1.0)
        # fold the unscale into the token normalize (one pass over grads)
        grads = jax.tree.map(lambda g: g / (denom * scale), grads)
        if self._numerics:
            from hetu_tpu.obs import numerics as _numerics
            _numerics.tap_tree("params", params)
            _numerics.tap_tree("grads", grads)
            if self._scaler is not None:
                _numerics.tap_stats("scaler",
                                    scale=scaler_state["scale"])
        grads_sharded = False
        if getattr(self.strategy, "zero_stage", 1) >= 2 and self.strategy.dp > 1:
            # ZeRO-2: keep grads dp-sharded through clip+update (GSPMD turns
            # the grad sync into reduce-scatter; params re-gather after)
            grads = jax.tree.map(
                lambda g, sh: jax.lax.with_sharding_constraint(g, sh),
                grads, self._sshard["m"])
            grads_sharded = True
        with jax.named_scope("optimizer"):
            grads, gnorm = optim.clip_by_global_norm(grads, c.grad_clip)
        metrics = {"loss": lsum / denom}
        if self._scaler is None:
            params, opt_state = self._apply_update(
                grads, opt_state, params, grads_sharded)
            if new_ef:
                opt_state["ef"] = new_ef
            metrics["grad_norm"] = gnorm
            metrics["lr"] = self.optimizer._lr(opt_state["step"])
            return params, opt_state, metrics, scaler_state

        # AMP: skip the update on non-finite grads, back the scale off
        # (reference: CheckFinite.cc + update_scale.cc semantics)
        finite = self._scaler.all_finite(grads)
        safe_grads = jax.tree.map(jnp.nan_to_num, grads)
        new_params, new_opt = self._apply_update(
            safe_grads, opt_state, params, grads_sharded)
        params = jax.tree.map(lambda n, o: jnp.where(finite, n, o),
                              new_params, params)
        opt_state = jax.tree.map(lambda n, o: jnp.where(finite, n, o),
                                 new_opt, opt_state)
        if new_ef:
            # a skipped step keeps the previous residuals too: the grads
            # that produced new_ef never entered the params
            opt_state["ef"] = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_ef, ef_state)
        new_scaler_state = self._scaler.update(scaler_state, finite)
        if new_ef:
            # EF residuals live in SCALED-grad units (the sync quantizes
            # grads of scale * loss).  When the dynamic scale moves —
            # growth streak or non-finite backoff — last step's residuals
            # would be off by old/new scale at the next quantize (the
            # PR 2 known limit: one step of stale error feedback per
            # scale change).  Rescaling by new/old keeps them exact;
            # the ratio is 1 on scale-stable steps.
            ratio = new_scaler_state["scale"] / scaler_state["scale"]
            opt_state["ef"] = jax.tree.map(lambda r: r * ratio,
                                           opt_state["ef"])
        scaler_state = new_scaler_state
        metrics["grad_norm"] = jnp.where(finite, gnorm, jnp.nan)
        metrics["lr"] = self.optimizer._lr(opt_state["step"])
        metrics["loss_scale"] = scaler_state["scale"]
        metrics["amp_skipped"] = 1.0 - finite.astype(jnp.float32)
        return params, opt_state, metrics, scaler_state

    def _apply_update(self, grads, opt_state, params,
                      grads_sharded: bool = False):
        """The optimizer update, routed through the quantized ZeRO
        refresh when HETU_TPU_ZERO_COMPRESS is on: the update math runs
        on each rank's dp shard of the opt state and the param DELTA
        all-gathers as int8/int4 + scales instead of GSPMD's f32 param
        all-gather (optim/zero_refresh.py).  "none" calls the plain
        update — traced program unchanged."""
        # the "optimizer" scope marks the update region in HLO metadata
        # so obs.hlo_profile attributes its FLOPs/bytes separately from
        # the model layers (the GSPMD-inserted ZeRO param all-gather
        # lands here too — it consumes the updated shards)
        with jax.named_scope("optimizer"):
            if self._zero_compress == "none":
                return self.optimizer.update(grads, opt_state, params)
            from hetu_tpu.optim.zero_refresh import quantized_zero_update
            return quantized_zero_update(
                self.optimizer, grads, opt_state, params, mesh=self.mesh,
                dims=self._zr_dims, specs=self._zr_specs,
                mode=self._zero_compress, grads_sharded=grads_sharded)

    # ------------------------------------------------------------------
    def _accumulate_grads(self, params, batches, keys, scale):
        """The micro-batch grad-accumulation scan -> (sum-grads, loss
        sum, token count, per-micro numerics stats).  ONE definition
        shared by the GSPMD path and the compressed shard_map body —
        fp32/int8 loss parity is defined by these being the same
        arithmetic, so they must not drift apart.

        The stats frame opens INSIDE the grad-traced loss so the model's
        boundary taps (embed/hidden/logits, MoE router) can escape the
        transform legally via value_and_grad's aux channel; the scan
        stacks them [n_micro, ...] into its ys (an empty pytree — and an
        unchanged trace — when numerics is off)."""
        from hetu_tpu.obs import numerics as _numerics

        def micro(acc, xs):
            batch, key = xs

            def scaled_loss(p):
                with _numerics.frame() as nf:
                    l, count = self._loss_fn(p, batch, key)
                return l.astype(jnp.float32) * scale, (l, count, nf.stats)

            (_, (l, count, ns)), g = jax.value_and_grad(
                scaled_loss, has_aux=True)(params)
            acc_g, acc_l, acc_c = acc
            return (jax.tree.map(jnp.add, acc_g, g), acc_l + l,
                    acc_c + count), ns

        zero_g = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                              params)
        zero = jnp.zeros((), jnp.float32)
        (grads, lsum, csum), mstats = jax.lax.scan(
            micro, (zero_g, zero, zero), (batches, keys))
        return grads, lsum, csum, mstats

    def _compressed_grads(self, params, batches, keys, scale, ef_state):
        """Per-replica grad accumulation + quantized DP sync, as ONE
        shard_map over the dp axis (comm/grad_sync.py).

        Inside the manual region each replica runs the same micro-batch
        scan as the GSPMD path over its local batch rows, then the sync
        replaces GSPMD's f32 grad all-reduce with int8/int4 all-to-all +
        all-gather (~3.94x / ~7.76x fewer bytes on wire, comm/wire.py),
        hierarchically routed when a two-level topology applies.
        Loss/token sums psum as f32 scalars.  Dropout keys fold in the
        replica's axis index (grad_sync.per_replica_keys) so each replica
        draws independent masks — matching the per-row independence of
        the GSPMD lowering."""
        from jax.experimental.shard_map import shard_map
        from hetu_tpu.comm.grad_sync import (ef_specs, per_replica_keys,
                                             quantized_grad_sync)
        from hetu_tpu.obs import numerics as _numerics
        dp = self.strategy.dp

        def body(params, batches, keys, scale, ef_state):
            keys = per_replica_keys(keys, "dp")
            grads, lsum, csum, mstats = self._accumulate_grads(
                params, batches, keys, scale)
            # "grad_sync" scope: the explicit quantized collectives are
            # individually attributable in the per-layer HLO profile
            # (the GSPMD path's implicit all-reduce cannot be scoped —
            # it inherits its producing layer's scope; documented limit)
            with jax.named_scope("grad_sync"):
                with _numerics.frame() as nf:
                    grads, new_ef = quantized_grad_sync(
                        grads, "dp", dp, self._bucket_plan,
                        self._grad_compress, ef_state,
                        topology=self._comm_topology)
            nstats = {}
            if _numerics.active():
                # micro-stacked model stats + the sync's SNR taps + EF
                # residual norms, folded across dp inside the manual
                # region so the body can return replicated stats
                nstats = dict(_numerics.reduce_stacked(mstats))
                nstats.update(nf.stats)
                if new_ef:
                    nstats["ef"] = _numerics.tree_stats(new_ef)
                nstats = _numerics.reduce_axis(nstats, "dp")
            return (grads, jax.lax.psum(lsum, "dp"),
                    jax.lax.psum(csum, "dp"), new_ef, nstats)

        batch_specs = jax.tree.map(
            lambda v: P(*([None, "dp"] + [None] * (v.ndim - 2))), batches)
        especs = (ef_specs(self._bucket_plan,
                           topology=self._comm_topology)
                  if ef_state else {})
        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), batch_specs, P(), P(), especs),
            out_specs=(P(), P(), P(), especs, P()),
            # the gathered grads ARE replicated over dp but the checker
            # cannot infer that through all-to-all
            check_rep=False)
        from hetu_tpu.dstates import suppress_constraints
        with suppress_constraints():
            # the model's activation constraints (strategy.constrain) are
            # illegal AND vacuous inside the fully-manual region
            grads, lsum, csum, new_ef, nstats = fn(
                params, batches, keys, scale, ef_state)
        _numerics.merge(nstats)
        return grads, lsum, csum, new_ef

    # ------------------------------------------------------------------
    def _batch_sharding(self, ndim: int):
        """[n_micro, mb, seq(, ...)]: mb over dp, seq over cp."""
        st = self.strategy
        spec = [None] * ndim
        if st.dp > 1:
            spec[1] = "dp"
        if st.cp > 1:
            spec[2] = "cp"
        return NamedSharding(self.mesh, P(*spec))

    def _cp_reorder(self, host_batch: Dict[str, np.ndarray]):
        """Apply the declared CP split's seq permutation (reference:
        bucket.py:193 generate_cp_pack_data — pre-shift labels, then deal
        the seq across ranks for causal balance).

        Pre-shifting labels (labels[t] := labels[t+1], tail -100) makes the
        next-token objective permutation-safe; the models consume them with
        labels_shifted=True. position_ids are synthesized when absent so
        rotary + ring masking see true token positions after the reorder."""
        split = self._cp_split
        if split in (None, "normal"):
            return host_batch
        seq = host_batch["input_ids"].shape[1]
        perm = self._cp_perm_cache.get(seq)
        if perm is None:
            from hetu_tpu.data.bucket import cp_split_indices
            try:
                perm = np.concatenate(
                    cp_split_indices(seq, self.strategy.cp, split))
            except (AssertionError, ValueError) as e:
                if not self._cp_layout_used:
                    # nothing traced yet: fall back to the contiguous layout
                    # instead of failing runs whose seq doesn't divide the
                    # fancier split (flag defaults are not an opt-in wall)
                    logger.warning(
                        f"seq {seq} incompatible with cp_split={split!r} at "
                        f"cp={self.strategy.cp} ({e}); falling back to "
                        f"'normal'")
                    self._cp_split = "normal"
                    self._labels_shifted = False
                    return host_batch
                raise ValueError(
                    f"seq {seq} incompatible with cp_split={split!r} at "
                    f"cp={self.strategy.cp} after steps already ran under "
                    f"this layout: {e}; pad the bucket ladder or set "
                    f"HETU_TPU_CP_SPLIT=normal") from None
            self._cp_perm_cache[seq] = perm
        self._cp_layout_used = True
        out = dict(host_batch)
        if "labels" in out:
            lab = out["labels"]
            shifted = np.full_like(lab, -100)
            shifted[:, :-1] = lab[:, 1:]
            out["labels"] = shifted
        if "position_ids" not in out:
            out["position_ids"] = np.broadcast_to(
                np.arange(seq, dtype=np.int32),
                out["input_ids"].shape).copy()
        for k, v in out.items():
            if v.ndim >= 2 and v.shape[1] == seq:
                out[k] = np.ascontiguousarray(v[:, perm])
        return out

    def prepare_batch(self, host_batch: Dict[str, np.ndarray]):
        """Reshape [gbs, seq] -> [n_micro, mb*dp, seq], device_put sharded.
        (reference: trainer.py:465 prepare_feed_dict)"""
        c, st = self.config, self.strategy
        host_batch = self._cp_reorder(host_batch)
        n_micro = c.num_micro_batches(st.dp)
        out = {}
        for k, v in host_batch.items():
            g = v.shape[0]
            assert g == c.global_batch_size, (k, v.shape)
            v = v.reshape(n_micro, g // n_micro, *v.shape[1:])
            out[k] = jax.device_put(v, self._batch_sharding(v.ndim))
        return out

    @staticmethod
    def _shape_key(host_batch):
        """THE per-batch-shape cache key — one construction shared by
        _memo_by_shape and lowered_step so the report caches and the
        linter's compiled-text path can never diverge."""
        return tuple(sorted((k, tuple(np.asarray(v).shape))
                            for k, v in host_batch.items()))

    def _memo_by_shape(self, attr: str, host_batch, compute):
        """Per-batch-shape memo shared by the report surfaces (memory/
        phase/mfu): ONE key construction so the three caches can never
        diverge.  `compute(key)` runs on miss."""
        key = self._shape_key(host_batch)
        cache = self.__dict__.setdefault(attr, {})
        if key not in cache:
            cache[key] = compute(key)
        return cache[key]

    def memory_report(self, host_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """XLA's compiled-memory breakdown of the train step for this batch
        shape — the per-plan analog of the reference's micro-batch memory
        profiler (reference: hetu/graph/profiler.h:15-39 memory records;
        GetCUDAProfiler).  AOT lower().compile() does NOT share jit's
        dispatch cache, so the first call per batch shape pays one full XLA
        compile; results are memoized per shape here."""
        def compute(key):
            mem = self._compiled_for_shape(host_batch, key).memory_analysis()
            out = {}
            for k in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes"):
                v = getattr(mem, k, None)
                if v is not None:
                    out[k.replace("_in_bytes", "")] = int(v)
            # donated params/opt aliasing means live peak ~ args + temp
            out["peak_estimate"] = (out.get("argument_size", 0)
                                    + out.get("temp_size", 0))
            return out
        return self._memo_by_shape("_memory_reports", host_batch, compute)

    def lowered_step(self, host_batch, *, optimized: bool = False) -> str:
        """The train step's lowered module text for this batch shape.

        optimized=False (default) returns the TRACED pre-optimization
        module — one trace, no XLA compile: the flag-identity sweep's
        fingerprint surface (hetu_tpu/analysis/flag_identity.py; every
        flag contract acts at trace/build time, so trace-level identity
        implies compiled identity).  optimized=True returns the
        post-optimization text of the AOT compile, shared with
        memory_report/phase_report via the per-shape memo — what the
        HLO lints (tools_lint.py --hlo) walk."""
        if optimized:
            return self._compiled_for_shape(
                host_batch, self._shape_key(host_batch)).as_text()
        batches = self.prepare_batch(host_batch)
        rng = jax.random.key(0)
        with use_mesh(self.mesh), self._declared():
            return self._step_fn.lower(
                self.params, self.opt_state, batches, rng,
                self.scaler_state).as_text()

    def _compiled_for_shape(self, host_batch, key):
        """AOT lower().compile() of the step for this batch shape — ONE
        compile shared by memory_report and phase_report (it does not
        share jit's dispatch cache, so it costs a full XLA compile)."""
        cache = getattr(self, "_compiled_steps", None)
        if cache is None:
            cache = self._compiled_steps = {}
        if key not in cache:
            batches = self.prepare_batch(host_batch)
            rng = jax.random.key(0)
            with use_mesh(self.mesh), self._declared():
                cache[key] = self._step_fn.lower(
                    self.params, self.opt_state, batches, rng,
                    self.scaler_state).compile()
        return cache[key]

    def phase_report(self, host_batch: Dict[str, np.ndarray]):
        """Per-phase (embed/attn/moe/mlp/lm_head) attribution of the
        compiled train step from the named-scope HLO metadata — the
        reference's per-op cost records (profiler.h:25), hardware-free.
        Pairs with memory_report (shares its one AOT compile per shape)."""
        from hetu_tpu.utils.profiling import phase_breakdown
        return self._memo_by_shape(
            "_phase_reports", host_batch,
            lambda key: phase_breakdown(
                self._compiled_for_shape(host_batch, key)))

    def mfu_report(self, host_batch: Dict[str, np.ndarray]):
        """Hardware-free estimated MFU + per-phase roofline bound for the
        compiled train step at this batch shape (obs.mfu: cost_analysis
        FLOPs x hardware-profile peaks x phase_breakdown traffic).  Shares
        the one AOT compile per shape with memory_report/phase_report."""
        from hetu_tpu.obs.mfu import estimate_from_compiled
        return self._memo_by_shape(
            "_mfu_reports", host_batch,
            lambda key: estimate_from_compiled(
                self._compiled_for_shape(host_batch, key)))

    def profile_report(self, host_batch: Dict[str, np.ndarray]):
        """On-demand per-layer analytic profile of the compiled train
        step at this batch shape (obs.hlo_profile): the full roofline
        attribution per named layer/op-group plus the liveness-based
        peak-HBM estimate under "peak_hbm".  Shares the one AOT compile
        per shape with memory_report/phase_report/mfu_report; the
        flag-gated per-compile `profile` RunLog record is the compact
        top-k version of this."""
        from hetu_tpu.obs.hlo_profile import (layer_profile,
                                              peak_hbm_estimate)

        def compute(key):
            compiled = self._compiled_for_shape(host_batch, key)
            rep = layer_profile(compiled)
            rep["peak_hbm"] = peak_hbm_estimate(compiled)
            return rep
        return self._memo_by_shape("_profile_reports", host_batch, compute)

    def train_step(self, host_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batches = self.prepare_batch(host_batch)
        rng = jax.random.fold_in(jax.random.key(self.config.seed + 1),
                                 self.global_step)
        with use_mesh(self.mesh), self._declared():
            self.params, self.opt_state, metrics, self.scaler_state = \
                self._step_fn(self.params, self.opt_state, batches, rng,
                              self.scaler_state,
                              strategy_id=self._plan_dispatch_key())
        self.global_step += 1
        return metrics

    def train(self, batches: Iterable[Dict[str, np.ndarray]],
              num_steps: Optional[int] = None) -> Dict[str, float]:
        """Main loop (reference: trainer.py:655). Returns last metrics."""
        c = self.config
        if self.params is None:
            self.build()
        t0 = time.perf_counter()
        tokens = 0
        metrics = {}
        for i, host_batch in enumerate(batches):
            if num_steps is not None and i >= num_steps:
                break
            with self.profiler.step(self.global_step):
                metrics = self.train_step(host_batch)
            step_s = self.profiler.last_step_s
            batch_tokens = int(np.prod(host_batch["input_ids"].shape))
            tokens += batch_tokens
            self._registry.inc("trainer.steps")
            self._registry.inc("trainer.tokens", batch_tokens)
            self._registry.observe("trainer.step_time_s", step_s)
            log_boundary = (self.global_step % c.log_every) == 0
            loss = None
            self._note_scaler(metrics)
            nstats = (metrics.pop("numerics", None)
                      if isinstance(metrics, dict) else None)
            if (nstats is not None
                    and self.global_step % self._numerics_every == 0):
                self._record_numerics(nstats)
            if self._health is not None:
                # the monitor needs loss/grad_norm PER STEP — a device
                # sync the HETU_TPU_HEALTH flag explicitly opts into
                loss = float(metrics["loss"])
                gn = metrics.get("grad_norm")
                self._health.observe_step(
                    self.global_step, step_s, loss=loss,
                    grad_norm=None if gn is None else float(gn))
            if log_boundary:
                loss = float(metrics["loss"])  # forces device sync
                dt = time.perf_counter() - t0
                logger.info(
                    f"step {self.global_step} loss {loss:.4f} "
                    f"lr {float(metrics['lr']):.2e} "
                    f"grad_norm {float(metrics['grad_norm']):.3f} "
                    f"tokens/s {tokens / max(dt, 1e-9):,.0f}")
                t0, tokens = time.perf_counter(), 0
            if self.run_log is not None:
                # loss AND the device memory probe ride only on
                # log-boundary steps — float(loss) is a device sync and
                # memory_stats() a runtime query (a host round-trip on the
                # remote-TPU backend) the hot path must not pay per step.
                # With HETU_TPU_MEMORY_PROFILE on, the profiler already
                # probed this step — reuse its value so EVERY step record
                # carries memory (the flag opted into the per-step query).
                if self.profiler.mem_profile:
                    mem = self.profiler.last_mem_bytes
                else:
                    # the probe stays on log boundaries even when the
                    # health monitor synced loss on this step
                    mem = _device_mem_bytes() if log_boundary else None
                self.run_log.step(
                    self.global_step, step_s, loss=loss,
                    tokens_per_s=batch_tokens / max(step_s, 1e-9),
                    device_mem_bytes=mem,
                    plan=self._plan_fingerprint(host_batch))
            if self._ckpt and (self.global_step % c.ckpt_every) == 0:
                self.save()
        self._flush_scaler()
        self.profiler.close()
        self._obs_summary()
        return metrics

    def _note_scaler(self, metrics):
        """Loss-scale observability (docs/observability.md): with AMP on,
        every step updates the ``scaler.loss_scale`` gauge and every
        growth/backoff transition leaves ONE ``scaler`` RunLog event +
        a ``scaler.growth``/``scaler.backoff`` counter.

        The loop's hot-path invariant (per-step device syncs need an
        explicit opt-in) is preserved by reading each step's scale one
        step LATE: the device scalar is stashed here and converted on
        the next call — by then the producing step has long finished
        (the device queue is serial), so float() never blocks the host
        out of its overlap with the running step.  train() flushes the
        last pending scale at loop exit."""
        if self._scaler is None or "loss_scale" not in metrics:
            return
        self._flush_scaler()
        self._pending_scale = (self.global_step, metrics["loss_scale"])

    def _flush_scaler(self):
        """Convert-and-record the stashed loss scale (no-op when none)."""
        if self._pending_scale is None:
            return
        step, dev_scale = self._pending_scale
        self._pending_scale = None
        try:
            scale = float(dev_scale)
        except Exception:   # telemetry never kills a step
            return
        self._registry.set_gauge("scaler.loss_scale", scale)
        from hetu_tpu.optim.grad_scaler import classify_transition
        event = classify_transition(self._last_loss_scale, scale)
        if event is not None:
            self._registry.inc(f"scaler.{event}")
            if self.run_log is not None:
                self.run_log.log("scaler", event=event, scale=scale,
                                 prev=self._last_loss_scale, step=step)
        self._last_loss_scale = scale

    def _record_numerics(self, stats):
        """Host-fetch one step's numerics pytree (a handful of scalars,
        every HETU_TPU_NUMERICS_EVERY steps) and fan it out through the
        one sink: RunLog `numerics` record, numerics.* gauges (riding
        the cluster telemetry push), moe.* gauges/counters, and the
        numerics health detectors when HETU_TPU_HEALTH is on."""
        from hetu_tpu.obs import numerics as _numerics
        try:
            host = jax.device_get(stats)
        except Exception as e:   # telemetry never kills a step
            logger.warning(f"numerics fetch failed: {e!r}")
            return
        _numerics.record(host, step=self.global_step,
                         registry=self._registry, runlog=self.run_log)
        if self._num_health is not None:
            self._num_health.observe(self.global_step, host)

    def _plan_fingerprint(self, host_batch) -> str:
        """Stable id of (strategy, batch shapes) — which compiled plan a
        step dispatched to, readable across runs."""
        shapes = ",".join(f"{k}:{'x'.join(map(str, v.shape))}"
                          for k, v in sorted(host_batch.items()))
        return f"{self.strategy.describe()}|{shapes}"

    def _obs_summary(self):
        """Flush telemetry at a loop boundary: one 'summary' run-event
        (registry snapshot + step-time summary) and the optional
        HETU_TPU_METRICS_EXPORT registry dump.  Idempotent — a later
        close() appends another snapshot, never corrupts."""
        from hetu_tpu.utils import flags
        if self.run_log is not None:
            self.run_log.log("summary", profiler=self.profiler.summary(),
                             metrics=self._registry.snapshot())
        path = flags.str_flag("HETU_TPU_METRICS_EXPORT")
        if path:
            try:
                self._registry.export_jsonl(path)
            except OSError as e:
                logger.warning(f"metrics export to {path} failed: {e!r}")

    def close(self):
        """Release observability sinks (flush + close the RunLog).  Safe to
        call more than once; training after close() still runs, it just
        stops leaving run events."""
        self.profiler.close()
        self._obs_summary()
        if self.run_log is not None:
            self.run_log.close()

    # ------------------------------------------------------------------
    def evaluate(self, batches: Iterable[Dict[str, np.ndarray]],
                 max_batches: Optional[int] = None) -> Dict[str, float]:
        """Evaluation loop: token-weighted CE (router aux excluded) and
        perplexity (reference: trainer eval path)."""
        if self.params is None:
            self.build()
        if not hasattr(self, "_eval_fn"):
            def eval_step(params, batch):
                return self.model(
                    params, batch["input_ids"], labels=batch["labels"],
                    position_ids=batch.get("position_ids"),
                    segment_ids=batch.get("segment_ids"),
                    deterministic=True, loss_reduction="sum",
                    include_aux_loss=False,
                    labels_shifted=self._labels_shifted)
            from hetu_tpu.engine.plan_pool import PlanPool
            # eval over the bucket ladder gets the same plan-pool
            # bookkeeping as training (one compile per shape, loud past
            # the cap) instead of jit's silent retraces; compilation
            # happens at call time inside the loop's mesh context.
            # (HotSwitchTrainer stashes/restores this per strategy —
            # plans compiled for one mesh/model must not serve another.)
            self._eval_fn = PlanPool(
                eval_step,
                max_plans=self._plan_cap(),
                name="eval_step", key_argnums=(1,))
        total, count = 0.0, 0.0
        for i, host_batch in enumerate(batches):
            if max_batches is not None and i >= max_batches:
                break
            # same dp/cp input sharding as training (batches here have no
            # micro dim: [gbs, seq])
            st = self.strategy
            spec = [None, None]
            if st.dp > 1:
                spec[0] = "dp"
            if st.cp > 1:
                spec[1] = "cp"
            sh = NamedSharding(self.mesh, P(*spec))
            host_batch = self._cp_reorder(host_batch)
            batch = {k: jax.device_put(v, sh) for k, v in host_batch.items()}
            with use_mesh(self.mesh), self._declared():
                lsum, csum = self._eval_fn(
                    self.params, batch,
                    strategy_id=self._plan_dispatch_key())
            total += float(lsum)
            count += float(csum)
        loss = total / max(count, 1.0)
        return {"loss": loss, "perplexity": float(np.exp(min(loss, 30.0))),
                "tokens": count}

    # ------------------------------------------------------------------
    def state(self):
        opt_state = self.opt_state
        if isinstance(opt_state, dict) and "ef" in opt_state:
            # the EF residuals ("ef") deliberately do NOT checkpoint: they
            # are a bounded one-step quantization memory, zero is a correct
            # cold start, and their [dp, L] layout would pin resumes to the
            # exact compress mode + dp degree — an elastic re-mesh or a
            # flag change must never brick a restore
            opt_state = {k: v for k, v in opt_state.items() if k != "ef"}
        s = {"params": self.params, "opt_state": opt_state,
             "step": self.global_step}
        if self.scaler_state is not None:
            s["scaler"] = self.scaler_state
        return s

    def save(self, wait: bool = False):
        assert self._ckpt is not None, "no ckpt_dir configured"
        self._ckpt.save(self.global_step, self.state(), wait=wait)

    def restore(self, step: Optional[int] = None):
        """Resume; reshards into the CURRENT strategy's shardings even if the
        checkpoint was written under a different one (reference:
        temp_load_split ht_safetensors.py:1147)."""
        assert self._ckpt is not None, "no ckpt_dir configured"
        if self.params is None:
            self.build()
        target = self.state()   # never carries "ef" — see state()
        fresh_ef = (self.opt_state.get("ef")
                    if isinstance(self.opt_state, dict) else None)
        try:
            restored = self._ckpt.restore(step, target=target)
        except ValueError:
            # scaler presence differs between the checkpoint and the current
            # config (bf16-saved -> fp16 resume or vice versa): retry with
            # the presence toggled; a missing scaler keeps its fresh init
            if "scaler" in target:
                target = {k: v for k, v in target.items() if k != "scaler"}
            else:
                from hetu_tpu.optim.grad_scaler import GradScaler
                target = dict(target, scaler=GradScaler().init())
            restored = self._ckpt.restore(step, target=target)
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        if fresh_ef is not None:
            # re-attach build()'s zero EF residuals (cold start; the
            # checkpoint intentionally excludes them — see state())
            self.opt_state["ef"] = fresh_ef
        self.global_step = int(restored["step"])
        if "scaler" in restored and self._scaler is not None:
            self.scaler_state = restored["scaler"]
        return self

    def restore_latest_valid(self):
        """Resume from the newest checkpoint whose manifest verifies,
        walking back past corrupt/torn saves (each skipped step counts
        `ckpt.fallbacks`; checksum-failed steps are quarantined so they
        cannot shadow later re-saves).  The walk is the CheckpointManager's
        (one copy of the fallback logic); each step restores through
        restore() so the scaler-presence retry and EF residual re-attach
        apply.  Raises FileNotFoundError when the directory has no
        checkpoints (fresh start) and CheckpointCorruptError when
        checkpoints exist but none is restorable."""
        assert self._ckpt is not None, "no ckpt_dir configured"

        def note_fallback(step, why):
            if self.run_log is not None:
                self.run_log.log("fault", fault="ckpt_corrupt",
                                 step=step, detail=why)

        _step, me = self._ckpt.restore_latest_valid(
            restore_fn=self.restore, on_fallback=note_fallback)
        return me
