"""Dynamic per-batch strategy dispatch.

Rebuild of the Hydraulis flow (reference: examples/hydraulis/strategy/
dynamic_pulp.py:179 `dynamic_strategy` ILP + cost_model.py +
train_hetu_with_kv_store.py — per-batch strategy chosen from the batch's
sequence-length distribution, strategies hot-switched via the KV store).

Here: the cost model scores each candidate strategy for the incoming batch's
(padded) shape and the dispatcher returns the fastest feasible one; pair it
with HotSwitchTrainer.train_step(batch, strategy_id=...) for the full loop.
The ILP of the reference is replaced by exact enumeration — strategy pools
are small (a handful of seq-len buckets).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from hetu_tpu.search.cost_model import CostModel, StrategyCandidate
from hetu_tpu.parallel.strategy import ParallelStrategy


@dataclasses.dataclass
class BatchStrategyDispatcher:
    """Choose a strategy id per batch by predicted step time under the
    hardware cost model."""

    cost: CostModel
    strategies: Sequence[ParallelStrategy]
    # model config for the envelope chokepoint (None = mesh-only rules)
    model_cfg: Optional[object] = None
    # the run's ACTUAL schedule/micro/dropout settings (defaults match
    # TrainingConfig) — validate must answer exactly like the Trainer's
    # own chokepoint call or the dispatcher rejects runnable plans
    pp_schedule: str = "gpipe"
    n_micro: Optional[int] = None       # None = trainer-resolved, unchecked
    deterministic: bool = True

    def __post_init__(self):
        # batch-independent envelope violations in the pool are a setup
        # bug: reject them loudly at construction, not per-batch
        for st in self.strategies:
            st.validate(self.model_cfg, pp_schedule=self.pp_schedule,
                        deterministic=self.deterministic)

    def _candidate(self, st: ParallelStrategy) -> StrategyCandidate:
        return StrategyCandidate(
            dp=st.dp, tp=st.tp, pp=st.pp, cp=st.cp,
            sequence_parallel=st.sequence_parallel, zero=st.zero,
            remat=True,
            n_micro=self.n_micro or (max(2 * st.pp, 1) if st.pp > 1 else 1),
            cp_tp_eff=st.cp_tp_eff, pp_tp_eff=st.pp_tp_eff,
            pp_schedule=self.pp_schedule)

    def choose(self, seq_lens: Sequence[int],
               global_batch: Optional[int] = None) -> int:
        """Strategy id minimizing predicted time for this batch shape.
        seq_lens: the batch's sequence lengths (max -> padded seq).
        Pool entries whose envelope rejects THIS batch shape (e.g. CP
        split divisibility) are skipped."""
        from hetu_tpu.parallel.strategy import StrategyValidationError
        seq = int(max(seq_lens))
        gb = global_batch or len(seq_lens)
        cost = dataclasses.replace(self.cost, seq_len=seq, global_batch=gb)
        hbm = cost.hw.hbm_gbytes * 1e9 * 0.9
        best, best_t = None, float("inf")
        for i, st in enumerate(self.strategies):
            c = self._candidate(st)
            try:
                # c.n_micro is only a cost heuristic — the feasibility
                # gate uses the run's actual n_micro (None = unchecked)
                st.validate(self.model_cfg, pp_schedule=self.pp_schedule,
                            n_micro=self.n_micro, global_batch=gb,
                            seq_len=seq, deterministic=self.deterministic)
            except StrategyValidationError:
                continue
            t, m = cost.evaluate(c)
            if m <= hbm and t < best_t:
                best, best_t = i, t
        if best is None:
            raise ValueError(
                f"no strategy in the pool fits memory (and the engine "
                f"envelope) for seq={seq}")
        return best
