"""Elastic training loop.

Rebuild of the reference's elastic recovery flow (reference: SURVEY §5.3 —
elastic gRPC server heartbeat monitor :463 + WorkerStop broadcast,
pssh relaunch with rewritten strategy args elastic_arg_parser.py, workers
re-entering the Trainer with the new ds config; trainer kills the process
group on RuntimeError trainer.py:317-322).

TPU flow here:
  1. every worker heartbeats the coordination server;
  2. on worker loss the server stop-flags everyone (split-brain-guarded);
  3. workers hit a named barrier, read the surviving membership, agree on a
     new plan via a consistency vote (planner runs on rank 0, broadcast via
     the KV store), rebuild the trainer under the new strategy, and resume
     from the latest checkpoint (reshard-on-load does the layout move).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from hetu_tpu.rpc.client import CoordinationClient
from hetu_tpu.utils.logging import get_logger

logger = get_logger("elastic")


class ElasticController:
    """Drives train -> detect-loss -> re-plan -> rebuild -> resume.

    trainer_factory(ds_config: dict) -> built Trainer (checkpoint-configured);
    planner_fn(alive: list[int]) -> ds-parallel config dict for the
    surviving membership (e.g. AmpelosPlanner with measured speeds).
    """

    def __init__(self, client: CoordinationClient,
                 trainer_factory: Callable[[Dict], object],
                 planner_fn: Callable[[list], Dict],
                 expected_world: Optional[int] = None,
                 rendezvous_timeout: float = 300.0):
        # checkpoint cadence belongs to TrainingConfig.ckpt_every; the
        # controller only saves at stop/exit boundaries
        self.client = client
        self.trainer_factory = trainer_factory
        self.planner_fn = planner_fn
        self.expected_world = expected_world
        self.rendezvous_timeout = rendezvous_timeout
        self.generation = 0
        self.trainer = None

    def _startup_rendezvous(self):
        """Wait for the full expected membership before the FIRST plan —
        without this the earliest worker plans for a partial cluster and the
        late joiners deadlock on a consumed vote round (reference: the
        elastic server knows the launch world size up front)."""
        if not self.expected_world:
            return
        deadline = time.time() + self.rendezvous_timeout
        while len(self.client.membership()) < self.expected_world:
            if time.time() > deadline:
                raise TimeoutError(
                    f"rendezvous: {len(self.client.membership())}/"
                    f"{self.expected_world} workers after "
                    f"{self.rendezvous_timeout}s")
            time.sleep(0.2)

    # ------------------------------------------------------------------
    def _replan(self) -> Dict:
        """Agree on a new plan for the survivors (rank order decides the
        proposer; everyone votes on the result's fingerprint)."""
        alive = self.client.membership()
        leader = min(alive)
        key = f"__elastic_plan_gen{self.generation}__"
        if self.client.rank == leader:
            plan = self.planner_fn(alive)
            self.client.put(key, plan)
        plan = self.client.get(key, block=True, timeout=120)
        # consistency vote on the plan fingerprint (reference: Consistent)
        fingerprint = str(sorted(plan.get("strategy", {}).items()))
        self.client.consistent(f"plan_gen{self.generation}", fingerprint,
                               count=len(alive))
        return plan

    def _rebuild(self):
        plan = self._replan()
        logger.info(f"[gen {self.generation}] rebuilding with strategy "
                    f"{plan.get('strategy')}")
        self.trainer = self.trainer_factory(plan)
        if getattr(self.trainer, "params", None) is None and \
                hasattr(self.trainer, "build"):
            self.trainer.build()   # accept unbuilt trainers from the factory
        if getattr(self.trainer, "_ckpt", None) is not None:
            try:
                self.trainer.restore()
                logger.info(f"[gen {self.generation}] resumed at step "
                            f"{self.trainer.global_step}")
            except FileNotFoundError:
                logger.info(f"[gen {self.generation}] fresh start "
                            "(no checkpoint yet)")
        else:
            logger.info(f"[gen {self.generation}] no ckpt_dir configured — "
                        "state will NOT survive re-meshing")
        self.client.resume()   # clear the server-side stop flag too
        self.generation += 1

    # ------------------------------------------------------------------
    def run(self, batches, num_steps: int) -> object:
        """The elastic loop (reference: workers re-entering Trainer after
        WorkerStop).  Returns the final trainer."""
        self._startup_rendezvous()
        self._rebuild()
        it = iter(batches)
        steps_done = self.trainer.global_step
        while steps_done < num_steps:
            # confirm via a fresh heartbeat — the cached flag can be stale
            # for one beat around resume()
            if self.client.should_stop and self.client.check_stop():
                logger.warning("membership change signaled; checkpointing "
                               "and re-meshing")
                if getattr(self.trainer, "_ckpt", None) is not None:
                    self.trainer.save(wait=True)
                self._rebuild()
                continue
            try:
                batch = next(it)
            except StopIteration:
                break
            self.trainer.train_step(batch)
            steps_done = self.trainer.global_step
        if getattr(self.trainer, "_ckpt", None) is not None:
            self.trainer.save(wait=True)
        return self.trainer
