"""Elastic training loop.

Rebuild of the reference's elastic recovery flow (reference: SURVEY §5.3 —
elastic gRPC server heartbeat monitor :463 + WorkerStop broadcast,
pssh relaunch with rewritten strategy args elastic_arg_parser.py, workers
re-entering the Trainer with the new ds config; trainer kills the process
group on RuntimeError trainer.py:317-322).

TPU flow here:
  1. every worker heartbeats the coordination server;
  2. on worker loss the server stop-flags everyone (split-brain-guarded);
  3. workers hit a named barrier, read the surviving membership, agree on a
     new plan via a consistency vote (planner runs on rank 0, broadcast via
     the KV store), rebuild the trainer under the new strategy, and resume
     from the latest checkpoint (reshard-on-load does the layout move).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from hetu_tpu.obs.metrics import get_registry
from hetu_tpu.rpc.client import (CoordinationClient, StaleRankError,
                                 VoteDisagreement)
from hetu_tpu.utils.logging import get_logger

logger = get_logger("elastic")


class ElasticController:
    """Drives train -> detect-loss -> re-plan -> rebuild -> resume.

    trainer_factory(ds_config: dict) -> built Trainer (checkpoint-configured);
    planner_fn(alive: list[int]) -> ds-parallel config dict for the
    surviving membership (e.g. AmpelosPlanner with measured speeds).

    recovery_budget: how many train_step exceptions may trigger a
    re-mesh-and-resume recovery before the exception surfaces (0 =
    emergency-checkpoint then re-raise — the conservative default: a
    deterministic model bug would otherwise re-mesh in a loop forever).

    straggler_hook(client) -> straggler report (or None): consulted every
    `straggler_interval` seconds in the run loop — normally
    obs.aggregate.snapshot_straggler_hook(), which asks the coordination
    server for its live report over the pushed telemetry.  A rank flagged
    in `straggler_patience` CONSECUTIVE checks is persistent; within
    `straggler_budget` re-meshes the controller then triggers the
    existing replan path (worker_stop broadcast) so the planner can route
    around it.  The default budget 0 means OBSERVE ONLY: gauges +
    accounting, no replans — automated re-meshing on a noisy signal is an
    operator opt-in, not a default.
    """

    def __init__(self, client: CoordinationClient,
                 trainer_factory: Callable[[Dict], object],
                 planner_fn: Callable[[list], Dict],
                 expected_world: Optional[int] = None,
                 rendezvous_timeout: float = 300.0,
                 recovery_budget: int = 0,
                 straggler_hook: Optional[Callable] = None,
                 straggler_budget: int = 0,
                 straggler_patience: int = 3,
                 straggler_interval: float = 2.0,
                 telemetry_interval: Optional[float] = None):
        # checkpoint cadence belongs to TrainingConfig.ckpt_every; the
        # controller only saves at stop/exit boundaries
        self.client = client
        self.trainer_factory = trainer_factory
        self.planner_fn = planner_fn
        self.expected_world = expected_world
        self.rendezvous_timeout = rendezvous_timeout
        self.recovery_budget = recovery_budget
        self.straggler_hook = straggler_hook
        self.straggler_budget = straggler_budget
        self.straggler_patience = max(1, straggler_patience)
        self.straggler_interval = straggler_interval
        self.generation = 0
        self.trainer = None
        self._consumed_epoch = 0   # newest plan round this worker took
        self._recoveries_used = 0
        # cluster telemetry push (obs/aggregate.py): the controller owns
        # the worker's pusher because it owns both the client and the
        # trainer (step times are measured around train_step, the RunLog
        # tail drains from whatever trainer generation is current).
        # Interval None -> the HETU_TPU_TELEMETRY_PUSH flag; 0/unset
        # means NO pusher exists and the step loop pays one None check.
        self._telemetry_interval = telemetry_interval
        self._telemetry = None
        self._straggler_strikes: Dict[int, int] = {}
        self._straggler_replans_used = 0
        self._straggler_next_check = 0.0

    def _startup_rendezvous(self):
        """Wait for the full expected membership before the FIRST plan —
        without this the earliest worker plans for a partial cluster and the
        late joiners deadlock on a consumed vote round (reference: the
        elastic server knows the launch world size up front)."""
        if not self.expected_world:
            return
        deadline = time.time() + self.rendezvous_timeout
        while len(self.client.membership()) < self.expected_world:
            if time.time() > deadline:
                raise TimeoutError(
                    f"rendezvous: {len(self.client.membership())}/"
                    f"{self.expected_world} workers after "
                    f"{self.rendezvous_timeout}s")
            time.sleep(0.2)

    def _current_epoch(self) -> int:
        try:
            return int(self.client.get("__elastic_epoch__"))
        except KeyError:
            return 0

    # ------------------------------------------------------------------
    def _replan(self) -> Dict:
        """Agree on a new plan for the current membership (rank order
        decides the proposer; everyone votes on the result's fingerprint).

        The round id is a cluster-wide EPOCH in the KV store, not a local
        counter: a worker that (re)joins mid-run (launcher restart,
        orchestrator slot respawn) has no idea how many re-plans happened
        before it — it adopts the round the leader publishes, so joiners
        and survivors always read/vote the SAME keys.

        No barrier: everyone POLLS the epoch key.  Barrier names derived
        from per-worker membership snapshots deadlock when two deaths are
        detected in different monitor sweeps (survivors end up in
        different barriers), so the loop instead re-reads membership
        every tick — the leader (min alive) publishes a round for its
        view, consumers take any round that INCLUDES them, and a worker
        excluded from a round keeps waiting (exclusion means the server
        declared it dead; its resume() is rejected anyway).  A joiner
        nobody plans in asks for a re-mesh itself (worker_stop broadcast)
        after a grace period — that is what integrates relaunched workers
        without an orchestrator."""
        deadline = time.time() + self.rendezvous_timeout
        ask_at = time.time() + 10.0
        while True:
            alive = self.client.membership()
            if self.client.rank not in alive:
                # the server declared this worker dead (heartbeat false-
                # positive, e.g. a long XLA compile): fail FAST — resume()
                # would be rejected anyway, and broadcasting re-mesh
                # requests from a dead-marked rank would thrash the
                # survivors with needless checkpoint+rebuild cycles
                raise self._split_brain_error()
            epoch = self._current_epoch()
            if epoch > self._consumed_epoch:
                members = self.client.get(f"__elastic_members_e{epoch}__",
                                          block=True, timeout=60)
                if self.client.rank in members:
                    plan = self.client.get(f"__elastic_plan_e{epoch}__",
                                           block=True, timeout=60)
                    self._consumed_epoch = epoch
                    fingerprint = str(sorted(
                        plan.get("strategy", {}).items()))
                    try:
                        self.client.consistent(f"plan_e{epoch}",
                                               fingerprint,
                                               count=len(members))
                    except TimeoutError:
                        # a round member died mid-vote; a newer round is
                        # coming — keep looping
                        get_registry().inc("elastic.vote_timeouts")
                        continue
                    except StaleRankError:
                        raise   # next membership() read raises the
                                # split-brain RuntimeError anyway
                    except ConnectionError:
                        # partition ate the vote even after the client's
                        # own same-round retries: survivable — a newer
                        # round (or this one, re-read) supersedes
                        get_registry().inc("elastic.vote_transport_errors")
                        logger.warning(
                            f"plan vote for epoch {epoch} lost to a "
                            "transport failure; waiting for a "
                            "superseding round")
                        continue
                    except VoteDisagreement:
                        # dual-leader race: two workers with divergent
                        # membership snapshots published the SAME epoch,
                        # interleaving the plan/members writes — a consumer
                        # can read one leader's members with the other's
                        # plan and the fingerprint vote disagrees.  The
                        # disagreement is survivable: a newer round
                        # supersedes, so keep polling instead of crashing
                        # the surviving worker.
                        get_registry().inc("elastic.vote_conflicts")
                        logger.warning(
                            f"plan vote for epoch {epoch} disagreed "
                            "(dual-leader race); waiting for a "
                            "superseding round")
                        continue
                    if self._current_epoch() == epoch:
                        return plan
                    continue   # superseded while voting: take the newer
                else:
                    # a round that predates/excludes this worker
                    self._consumed_epoch = epoch
            elif alive and self.client.rank == min(alive):
                new_epoch = epoch + 1
                plan = self.planner_fn(alive)
                self.client.put(f"__elastic_plan_e{new_epoch}__", plan)
                # membership of the round, for consumers and outside
                # observers (the orchestrator's convergence check)
                self.client.put(f"__elastic_members_e{new_epoch}__", alive)
                self.client.put("__elastic_epoch__", new_epoch)
                continue
            elif time.time() > ask_at:
                # joined a cluster that is NOT re-planning: request a
                # re-mesh so the leader publishes a round including us
                logger.info("no plan round includes this worker; "
                            "requesting a re-mesh")
                self.client.worker_stop()
                ask_at = time.time() + 15.0
            if time.time() > deadline:
                raise TimeoutError(
                    f"_replan: no usable plan round after "
                    f"{self.rendezvous_timeout}s (alive={alive})")
            time.sleep(0.1)

    def _rebuild(self):
        reg = get_registry()
        with reg.timer("elastic.replan_s"):
            plan = self._replan()
        reg.inc("elastic.replans")
        reg.set_gauge("elastic.epoch", self._consumed_epoch)
        reg.set_gauge("elastic.generation", self.generation)
        logger.info(f"[gen {self.generation}] rebuilding with strategy "
                    f"{plan.get('strategy')}")
        # release the OLD trainer's telemetry sinks before replacing it:
        # the PlanPool on_compile hook is a bound method, so the trainer
        # sits in a reference cycle refcounting can't reclaim — without an
        # explicit close() every re-mesh would leak an open runlog fd and
        # drop the generation's final summary record
        old_close = getattr(self.trainer, "close", None)
        if callable(old_close):
            try:
                old_close()
            except Exception as e:
                logger.warning(f"closing previous trainer failed: {e!r}")
        self.trainer = self.trainer_factory(plan)
        if getattr(self.trainer, "params", None) is None and \
                hasattr(self.trainer, "build"):
            self.trainer.build()   # accept unbuilt trainers from the factory
        if getattr(self.trainer, "_ckpt", None) is not None:
            try:
                # verified fallback: walk back past corrupt/torn saves to
                # the newest checkpoint that actually restores (trainers
                # without the method keep the plain restore)
                if hasattr(self.trainer, "restore_latest_valid"):
                    self.trainer.restore_latest_valid()
                else:
                    self.trainer.restore()
                logger.info(f"[gen {self.generation}] resumed at step "
                            f"{self.trainer.global_step}")
            except FileNotFoundError:
                logger.info(f"[gen {self.generation}] fresh start "
                            "(no checkpoint yet)")
            except Exception as e:
                # checkpoints exist but NONE restored
                # (CheckpointCorruptError, or any restore blow-up from a
                # fallback-less trainer): surviving with fresh state beats
                # crashing the whole surviving cluster — but loudly, and
                # accounted, because saved progress was lost
                reg.inc("elastic.restore_failures")
                logger.error(
                    f"[gen {self.generation}] no valid checkpoint "
                    f"({e!r}); FRESH START — saved progress was "
                    "unrecoverable")
                self._log_fault("restore_unrecoverable", error=repr(e))
        else:
            logger.info(f"[gen {self.generation}] no ckpt_dir configured — "
                        "state will NOT survive re-meshing")
        # elastic re-mesh epochs leave a run-event record (the trainer owns
        # the RunLog; a factory-built trainer without one logs nothing)
        run_log = getattr(self.trainer, "run_log", None)
        if run_log is not None:
            run_log.log("elastic_epoch", epoch=self._consumed_epoch,
                        generation=self.generation,
                        alive=self.client.membership(),
                        strategy=plan.get("strategy"))
        self.client.resume()   # clear the server-side stop flag too
        self.generation += 1

    # ------------------------------------------------------------------
    def _split_brain_error(self) -> RuntimeError:
        return RuntimeError(
            f"rank {self.client.rank} was declared dead by the "
            "coordination server; reconnect with a fresh client "
            "for a new rank (split-brain guard)")

    def _emergency_save(self) -> bool:
        """Best-effort synchronous checkpoint on a failure path: bank the
        local state so surfacing the failure loses at most one step, not a
        checkpoint interval.  Never raises; accounted either way."""
        if getattr(self.trainer, "_ckpt", None) is None:
            return False
        reg = get_registry()
        try:
            self.trainer.save(wait=True)
            reg.inc("elastic.emergency_saves")
            return True
        except Exception as se:
            reg.inc("elastic.emergency_save_failures")
            logger.error(f"emergency checkpoint failed: {se!r}")
            return False

    def _log_fault(self, kind: str, **fields):
        """Record an observed fault as a RunLog `fault` event (the chaos
        accounting surface; a trainer without a run log records nothing)."""
        run_log = getattr(self.trainer, "run_log", None)
        if run_log is not None:
            run_log.log("fault", fault=kind, generation=self.generation,
                        **fields)

    def _confirm_stop(self) -> bool:
        """Fresh-heartbeat confirmation of a cached stop flag.  If the
        control plane is unreachable the cached flag counts as real:
        re-meshing spuriously is safe; ignoring a true stop wedges the
        cluster."""
        try:
            return self.client.check_stop()
        except StaleRankError:
            # terminal, not transient: the rank is dead server-side —
            # take the same path as the run-loop stale check (bank state,
            # surface the split-brain error) instead of re-meshing into
            # a membership read that re-raises this anyway
            self._emergency_save()
            raise self._split_brain_error()
        except (ConnectionError, OSError):
            get_registry().inc("elastic.stop_unconfirmed")
            logger.warning("stop flag set but the control plane is "
                           "unreachable; treating it as real")
            return True

    def _on_step_failure(self, exc: BaseException):
        """A train_step raised.  Always: emergency checkpoint (a crash now
        loses at most this one step, not a checkpoint interval) + fault
        accounting.  Within recovery_budget: trigger a cluster re-mesh and
        resume from the newest valid checkpoint; past it: re-raise."""
        reg = get_registry()
        reg.inc("elastic.step_failures")
        step = getattr(self.trainer, "global_step", -1)
        logger.error(f"train_step raised at step {step}: {exc!r}")
        self._log_fault("step_exception", step=step, error=repr(exc))
        self._emergency_save()
        if self._recoveries_used >= self.recovery_budget:
            raise exc
        self._recoveries_used += 1
        reg.inc("elastic.recovery_attempts")
        logger.warning(f"attempting re-mesh recovery "
                       f"({self._recoveries_used}/{self.recovery_budget})")
        try:
            self.client.worker_stop()   # the whole cluster re-meshes
            self.client.check_stop()
            self._rebuild()
        except Exception as re_exc:
            logger.error(f"re-mesh recovery failed: {re_exc!r}")
            raise exc from re_exc
        reg.inc("elastic.recovery_success")

    def _setup_telemetry(self):
        """Start the telemetry pusher when pushing is enabled (the
        HETU_TPU_TELEMETRY_PUSH flag or an explicit interval); None
        otherwise — the run loop then does zero telemetry work."""
        from hetu_tpu.obs.aggregate import TelemetryPusher, push_interval
        interval = (push_interval() if self._telemetry_interval is None
                    else self._telemetry_interval)
        if interval <= 0 or self._telemetry is not None:
            return
        self._telemetry = TelemetryPusher(
            self.client, interval=interval,
            # the tail must follow trainer REBUILDS — resolve the runlog
            # at push time, not at pusher construction
            runlog_fn=lambda: getattr(self.trainer, "run_log", None))

    def _check_stragglers(self):
        """Consult the straggler hook; escalate a persistent straggler to
        a re-mesh within straggler_budget (0 = observe only)."""
        reg = get_registry()
        try:
            report = self.straggler_hook(self.client)
        except Exception as e:
            reg.inc("elastic.straggler_hook_errors")
            logger.warning(f"straggler hook failed: {e!r}")
            return
        if not report:
            return
        flagged = {int(r) for r in report.get("stragglers", [])}
        reg.set_gauge("elastic.stragglers", len(flagged))
        self._straggler_strikes = {
            r: self._straggler_strikes.get(r, 0) + 1 for r in flagged}
        persistent = sorted(r for r, n in self._straggler_strikes.items()
                            if n >= self.straggler_patience)
        if not persistent:
            return
        reg.inc("elastic.stragglers_persistent")
        if self._straggler_replans_used >= self.straggler_budget:
            return   # observation only (the default)
        # the straggler report is cluster-global but budgets are
        # per-controller: only the LEADER (min alive rank) escalates, so
        # one straggler costs at most straggler_budget re-meshes
        # cluster-wide — not straggler_budget x world_size
        try:
            alive = self.client.membership()
        except (ConnectionError, OSError):
            return   # can't establish leadership; try next check
        if alive and self.client.rank != min(alive):
            return
        self._straggler_replans_used += 1
        reg.inc("elastic.straggler_replans")
        logger.warning(
            f"persistent straggler(s) {persistent} "
            f"({self.straggler_patience} consecutive reports); triggering "
            f"a re-mesh ({self._straggler_replans_used}/"
            f"{self.straggler_budget})")
        run_log = getattr(self.trainer, "run_log", None)
        if run_log is not None:
            run_log.log("straggler", stragglers=persistent,
                        action="replan")
        self._straggler_strikes = {}
        try:
            self.client.worker_stop()   # the existing replan path
        except (ConnectionError, OSError) as e:
            logger.warning(f"straggler re-mesh request failed: {e!r}")

    def run(self, batches, num_steps: int,
            step_callback: Optional[Callable] = None) -> object:
        """The elastic loop (reference: workers re-entering Trainer after
        WorkerStop).  Returns the final trainer.
        step_callback(trainer, metrics): per-step hook (loss-curve
        logging in the elastic demos/tests)."""
        reg = get_registry()
        self._startup_rendezvous()
        self._rebuild()
        self._setup_telemetry()
        it = iter(batches)
        steps_done = self.trainer.global_step
        while steps_done < num_steps:
            if self.client.stale:
                # the heartbeat thread learned this rank was declared
                # dead (reattach rejected): no op on this client can ever
                # succeed again — surface instead of spinning, but first
                # bank the local state (same guarantee as step failures:
                # losing the rank must not also lose a checkpoint
                # interval of completed steps)
                self._emergency_save()
                raise self._split_brain_error()
            # transport turbulence is observable, not silent: the gauge
            # flips while the client reconnects / the beat thread retries
            reg.set_gauge("elastic.client_disconnected",
                          1.0 if (self.client.disconnected or
                                  self.client.heartbeat_lost) else 0.0)
            # confirm via a fresh heartbeat — the cached flag can be stale
            # for one beat around resume()
            if self.client.should_stop and self._confirm_stop():
                logger.warning("membership change signaled; checkpointing "
                               "and re-meshing")
                if getattr(self.trainer, "_ckpt", None) is not None:
                    try:
                        self.trainer.save(wait=True)
                    except Exception as e:
                        # a failed boundary save must not block the
                        # re-mesh: the rebuild restores the newest VALID
                        # checkpoint instead (losing <= one interval)
                        reg.inc("elastic.save_failures")
                        logger.error(
                            f"checkpoint before re-mesh failed: {e!r}")
                self._rebuild()
                steps_done = self.trainer.global_step
                continue
            if self.straggler_hook is not None and \
                    time.time() >= self._straggler_next_check:
                self._straggler_next_check = (time.time()
                                              + self.straggler_interval)
                self._check_stragglers()
            try:
                batch = next(it)
            except StopIteration:
                break
            try:
                if self._telemetry is not None:
                    t_step = time.perf_counter()
                metrics = self.trainer.train_step(batch)
            except Exception as e:
                self._on_step_failure(e)
                steps_done = self.trainer.global_step
                continue
            if self._telemetry is not None:
                # the worker-side step record the cluster straggler
                # scoring runs on; loss may be a device scalar — reading
                # it is a sync the telemetry flag opted into
                loss = metrics.get("loss") if isinstance(metrics, dict) \
                    else None
                self._telemetry.source.note_step(
                    self.trainer.global_step,
                    time.perf_counter() - t_step,
                    loss=None if loss is None else float(loss))
            if step_callback is not None:
                step_callback(self.trainer, metrics)
            steps_done = self.trainer.global_step
        if self._telemetry is not None:
            self._telemetry.close()   # flush the run's tail to the server
            self._telemetry = None
        if getattr(self.trainer, "_ckpt", None) is not None:
            self.trainer.save(wait=True)
        return self.trainer
