"""Compiled-executable plan pool.

The reference's DefineAndRunGraph keeps a pool of ExecGraphPlans keyed by
(strategy, shape plan) and instantiates/compiles lazily
(reference: hetu/graph/define_and_run_graph.cc:1174 Run — plan pool lookup,
DeduceShapePlan :303).  The TPU analog: one AOT-compiled pjit executable per
(strategy id, abstract input shapes), cached here.  Shape plans come from the
data pipeline's bucket ladder, so the pool stays small and step dispatch is
a dict lookup — the same amortization the reference gets from _execute_plan.

Retrace guard (reference: executable_graph.cc:1163-1313 HETU_SHAPE_MISMATCH
handling): every new shape signature is a full XLA compile.  The pool logs
each one (first at INFO, later ones at WARNING — a growing pool usually
means the data pipeline is feeding unbucketed shapes) and refuses to grow
past `max_plans`, so silent recompile-per-batch can't eat a training run.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from hetu_tpu.utils.logging import get_logger

logger = get_logger("plan_pool")


def _shape_key(tree) -> Tuple:
    # pytree STRUCTURE is part of the key: identical leaf shapes under
    # different field names (e.g. position_ids vs segment_ids riders) are
    # different programs.  treedef objects hash in C++ — no stringify.
    leaves, treedef = jax.tree.flatten(tree)
    return (treedef,) + tuple(
        (tuple(l.shape), str(l.dtype)) for l in leaves
        if hasattr(l, "shape"))


@dataclasses.dataclass
class PlanPool:
    """Caches AOT-compiled executables of one traceable step function per
    (strategy_id, input shape signature)."""

    fn: Callable
    jit_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # refuse to compile more than this many distinct plans (None = unbounded)
    max_plans: Optional[int] = None
    name: str = "step"
    # which positional args the dispatch key hashes (None = all).  The
    # Trainer keys on the batches arg alone: params/opt_state shapes are
    # invariant per pool, and flattening a million-leaf param tree every
    # step is hot-path host work jit's own cache never paid.
    key_argnums: Optional[Tuple[int, ...]] = None
    # observability hook: on_compile(pool_name, key, compiled_plan,
    # compile_seconds) after every NEW plan compile — the trainer feeds
    # compile events (+ estimated MFU) into its RunLog from here.  Hook
    # failures are logged, never fatal: telemetry must not kill a step.
    on_compile: Optional[Callable[[str, Tuple, Any, float], None]] = None

    def __post_init__(self):
        self._plans: Dict[Tuple, Any] = {}
        self._jitted = jax.jit(self.fn, **self.jit_kwargs)

    def lower(self, *args):
        """Passthrough to the jitted fn's AOT lowering (memory reports)."""
        return self._jitted.lower(*args)

    def get(self, strategy_id, *args) -> Any:
        keyed = (args if self.key_argnums is None
                 else tuple(args[i] for i in self.key_argnums))
        key = (strategy_id,) + _shape_key(keyed)
        plan = self._plans.get(key)
        if plan is None:
            n = len(self._plans)
            if self.max_plans is not None and n >= self.max_plans:
                raise RuntimeError(
                    f"plan pool '{self.name}' hit max_plans={self.max_plans} "
                    f"and a NEW shape signature arrived — every distinct "
                    f"batch shape is a full XLA recompile, so this usually "
                    f"means the data pipeline feeds unbucketed shapes. Pad "
                    f"through the bucket ladder (hetu_tpu.data.bucket) or "
                    f"raise HETU_TPU_MAX_PLANS. New signature: {key[1:]}")
            t0 = time.perf_counter()
            plan = self._jitted.lower(*args).compile()
            self._plans[key] = plan
            dt = time.perf_counter() - t0
            msg = (f"plan pool '{self.name}': compiled plan #{n + 1} "
                   f"(strategy {strategy_id}) in {dt:.1f}s")
            # plan #1 is expected; growth beyond it deserves visibility
            (logger.info if n == 0 else logger.warning)(msg)
            if self.on_compile is not None:
                try:
                    self.on_compile(self.name, key, plan, dt)
                except Exception as e:
                    # a broken telemetry hook must not cost the run —
                    # EXCEPT a declared perf-budget enforcement failure
                    # (obs.budget, "enforce": true): gating is the one
                    # hook outcome that exists to stop the run
                    from hetu_tpu.obs.budget import BudgetError
                    if isinstance(e, BudgetError):
                        raise
                    logger.warning(f"on_compile hook failed: {e!r}")
        return plan

    def __call__(self, *args, strategy_id=0):
        return self.get(strategy_id, *args)(*args)

    @property
    def num_plans(self) -> int:
        return len(self._plans)

    def compile_stats(self):
        out = {}
        for key, plan in self._plans.items():
            try:
                mem = plan.memory_analysis()
                out[key] = {
                    "output_bytes": getattr(mem, "output_size_in_bytes", None),
                    "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
                }
            except Exception:
                out[key] = {}
        return out
