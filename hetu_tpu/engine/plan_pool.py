"""Compiled-executable plan pool.

The reference's DefineAndRunGraph keeps a pool of ExecGraphPlans keyed by
(strategy, shape plan) and instantiates/compiles lazily
(reference: hetu/graph/define_and_run_graph.cc:1174 Run — plan pool lookup,
DeduceShapePlan :303).  The TPU analog: one AOT-compiled pjit executable per
(strategy id, abstract input shapes), cached here.  Shape plans come from the
data pipeline's bucket ladder, so the pool stays small and step dispatch is
a dict lookup — the same amortization the reference gets from _execute_plan.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax


def _shape_key(tree) -> Tuple:
    leaves = jax.tree.leaves(tree)
    return tuple((tuple(l.shape), str(l.dtype)) for l in leaves
                 if hasattr(l, "shape"))


@dataclasses.dataclass
class PlanPool:
    """Caches AOT-compiled executables of one traceable step function per
    (strategy_id, input shape signature)."""

    fn: Callable
    jit_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self._plans: Dict[Tuple, Any] = {}
        self._jitted = jax.jit(self.fn, **self.jit_kwargs)

    def get(self, strategy_id: int, *args) -> Any:
        key = (strategy_id,) + _shape_key(args)
        plan = self._plans.get(key)
        if plan is None:
            plan = self._jitted.lower(*args).compile()
            self._plans[key] = plan
        return plan

    def __call__(self, *args, strategy_id: int = 0):
        return self.get(strategy_id, *args)(*args)

    @property
    def num_plans(self) -> int:
        return len(self._plans)

    def compile_stats(self):
        out = {}
        for key, plan in self._plans.items():
            try:
                mem = plan.memory_analysis()
                out[key] = {
                    "output_bytes": getattr(mem, "output_size_in_bytes", None),
                    "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
                }
            except Exception:
                out[key] = {}
        return out
