from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.engine.trainer import Trainer
from hetu_tpu.engine.plan_pool import PlanPool
from hetu_tpu.engine.hot_switch import HotSwitchTrainer
from hetu_tpu.engine.sft_trainer import SFTTrainer, mask_prompt_labels
from hetu_tpu.engine.malleus import MalleusPlanner, StragglerProfile
from hetu_tpu.engine.ampelos import AmpelosPlanner
from hetu_tpu.engine.elastic import ElasticController
from hetu_tpu.engine.dispatch import BatchStrategyDispatcher
