"""Hot-switching trainer.

Rebuild of the reference's multi-strategy training flow
(reference: examples/hotspa/llama_hot_switch_trainer.py — per-seq-len-bucket
strategies selected per batch, --hot_switch :58; DefineAndRunGraph's plan
pool + SwitchExecGraph under the hood, define_and_run_graph.cc:1258-1272).

The trainer keeps one compiled train step per strategy (the plan pool) and
reshards (params, opt_state) with the switch engine whenever the requested
strategy differs from the live one.  Switch latency is one resharding
device_put — the reference's batched-P2P ParamSlice program, compiler-planned.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import numpy as np

import hetu_tpu  # noqa: F401  (package context)
from hetu_tpu.core.mesh import use_mesh
from hetu_tpu.engine.trainer import Trainer
from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.parallel.switch import StrategyHandle, StrategySwitcher, SwitchMode
from hetu_tpu.utils.logging import get_logger

logger = get_logger("hot_switch")


def param_handle(model_factory, strategy: ParallelStrategy) -> StrategyHandle:
    """Params-only plan-pool entry: a StrategyHandle with the strategy's
    mesh + param shardings and NO optimizer-state shardings.  The serving
    engine's reuse shim over the hot-switch machinery
    (hetu_tpu/serving/reshard.py) — inference moves params, never
    moments, so the handle stays cheap to build per load tier."""
    model = model_factory(strategy)
    mesh = strategy.build_mesh()
    return StrategyHandle(strategy, model, mesh, model.shardings(mesh), None)


class HotSwitchTrainer(Trainer):
    """Trainer over a pool of strategies (one model instance per strategy,
    same architecture/config, different layouts)."""

    def __init__(self, model_factory, config: TrainingConfig,
                 strategies: List[ParallelStrategy], **kw):
        """model_factory(strategy) -> model instance."""
        self.model_factory = model_factory
        self.strategies = list(strategies)
        self.active_id = 0
        self.last_switch_profile = None
        self._handles: Dict[int, StrategyHandle] = {}
        self._steps: Dict[int, object] = {}
        model0 = model_factory(strategies[0])
        super().__init__(model0, config, strategies[0], **kw)

    # ------------------------------------------------------------------
    def _handle(self, sid: int) -> StrategyHandle:
        h = self._handles.get(sid)
        if h is None:
            st = self.strategies[sid]
            model = (self.model if sid == self.active_id and self.params is not None
                     else self.model_factory(st))
            mesh = st.build_mesh()
            pshard = model.shardings(mesh)
            abstract = model.abstract_params()
            from jax.sharding import NamedSharding, PartitionSpec as P
            from hetu_tpu.optim.optimizer import zero_shardings
            if st.zero:
                sshard = {
                    "step": NamedSharding(mesh, P()),
                    "m": zero_shardings(pshard, abstract, mesh, "dp"),
                    "v": zero_shardings(pshard, abstract, mesh, "dp"),
                }
            else:
                sshard = {"step": NamedSharding(mesh, P()),
                          "m": pshard, "v": pshard}
            h = StrategyHandle(st, model, mesh, pshard, sshard)
            self._handles[sid] = h
        return h

    def switch_to(self, sid: int,
                  mode: SwitchMode = SwitchMode.PARAM_AND_OPTIMIZER):
        """Hot-switch the live training state to strategy `sid`
        (reference: SwitchExecGraph::SwitchParams)."""
        if sid == self.active_id:
            return self
        if self.params is None:
            raise RuntimeError("HotSwitchTrainer.build() must run before "
                               "switching strategies")
        t0 = time.perf_counter()
        from_id = self.active_id
        dst = self._handle(sid)
        # byte accounting BEFORE the move (needs the live src shardings) —
        # the reference's ProfileRunningDetails (switch_exec_graph.cc:1904)
        from hetu_tpu.parallel.switch import profile_switch
        from hetu_tpu.utils import flags
        prof = None
        if flags.bool_flag("HETU_TPU_SWITCH_PROFILE"):
            try:
                prof = profile_switch(
                    self.params,
                    jax.tree.map(lambda x: x.sharding, self.params),
                    dst.param_shardings)
            except Exception as e:
                logger.warning(f"switch byte profiling failed: {e!r}")
        self.last_switch_profile = prof  # reset even on failure (no stale reads)
        switcher = StrategySwitcher(self._handles)
        self.params, new_state = switcher.switch(
            self.params, self.opt_state, sid, mode=mode)
        if new_state is None:  # PARAM mode: rebuild optimizer moments
            old_step = self.opt_state["step"] if self.opt_state else None
            with use_mesh(dst.mesh):
                self.opt_state = jax.jit(
                    self.optimizer.init,
                    out_shardings=dst.state_shardings)(self.params)
            if old_step is not None:
                # keep the schedule position (the reference's param-mode
                # switch does not rewind training progress)
                self.opt_state["step"] = jax.device_put(
                    old_step, dst.state_shardings["step"])
        else:
            self.opt_state = new_state
        # eval pools are per strategy too: a plan compiled for the old
        # mesh/model would otherwise be fetched for a same-shape batch
        # (stash under the OLD id before active_id flips)
        if not hasattr(self, "_evals"):
            self._evals = {}
        if hasattr(self, "_eval_fn"):
            self._evals[self.active_id] = self._eval_fn
            del self._eval_fn
        if sid in self._evals:
            self._eval_fn = self._evals[sid]
        self.active_id = sid
        self.model = dst.model
        self.strategy = dst.strategy
        self.mesh = dst.mesh
        self._pshard, self._sshard = dst.param_shardings, dst.state_shardings
        self._step_fn = self._steps.get(sid)
        if self._step_fn is None:
            # one plan POOL per strategy (out_shardings differ): within it,
            # one compiled plan per batch-shape bucket — the full
            # (strategy, shape-plan) pool of define_and_run_graph.cc:1174
            with use_mesh(dst.mesh):
                self._step_fn = self._make_step_pool(
                    dst.param_shardings, dst.state_shardings)
            self._steps[sid] = self._step_fn
        detail = ""
        if prof is not None:
            prof.wall_s = time.perf_counter() - t0
            self.last_switch_profile = prof
            detail = f"; params {prof.describe()}"
        wall_s = time.perf_counter() - t0
        self._registry.inc("switch.count")
        self._registry.observe("switch.wall_s", wall_s)
        if self.run_log is not None:
            # switch phases become timeline spans via obs.trace_from_runlog
            self.run_log.log(
                "switch", from_id=from_id, to_id=sid, wall_s=wall_s,
                mode=mode.value,
                moved_bytes=(prof.moved_bytes if prof else None),
                total_bytes=(prof.total_bytes if prof else None))
        logger.info(f"hot-switch -> strategy {sid} ({dst.strategy.describe()}) "
                    f"in {wall_s:.3f}s{detail}")
        return self

    def build(self, rng=None):
        super().build(rng)
        self._handles[self.active_id] = StrategyHandle(
            self.strategy, self.model, self.mesh, self._pshard, self._sshard)
        self._steps[self.active_id] = self._step_fn
        return self

    def train_step(self, host_batch, strategy_id: Optional[int] = None):
        """Per-batch strategy dispatch (the Hydraulis/HotSPa pattern:
        pick the strategy for this batch's seq-len bucket, switch if needed,
        then step)."""
        if strategy_id is not None:
            self.switch_to(strategy_id)
        return super().train_step(host_batch)
