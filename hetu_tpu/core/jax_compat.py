"""jax version-portability shims.

The codebase targets the current jax API surface (`jax.shard_map`,
`lax.axis_size`, `lax.pvary`/`lax.pcast`, `jax.typeof` with `.vma`
varying-manual-axes tracking).  Older releases (e.g. 0.4.x, the one this
image bakes) predate all of those; `install()` adds each MISSING name as
a semantically-equivalent shim and never overrides an existing one, so
on a current jax this module is a no-op:

* `jax.shard_map`     -> `jax.experimental.shard_map.shard_map`, with
                         `axis_names=`/`check_vma=` translated to the old
                         `auto=`/`check_rep=` spelling.
* `lax.axis_size`     -> the bound-axis size via `jax.core.axis_frame`
                         (which returns either a frame or the size).
* `lax.pvary`/`pcast` -> identity: releases without vma tracking have no
                         varying-axes type to promote, so the promotion
                         IS a no-op there.
* `jax.typeof`        -> an aval view whose `.vma` is the empty set
                         (matching the identity pvary above).

Installed once from `hetu_tpu/__init__` (and tests/conftest.py, which
runs before any test module's own `from jax import shard_map`).
"""
from __future__ import annotations

import jax
from jax import lax


class _AvalView:
    """`jax.typeof(x)` stand-in: the aval plus an empty `.vma` set."""

    __slots__ = ("_aval",)
    vma: frozenset = frozenset()

    def __init__(self, aval):
        object.__setattr__(self, "_aval", aval)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_aval"), name)


def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_rep=None,
                      check_vma=None, axis_names=None, auto=None):
    from jax.experimental.shard_map import shard_map as esm
    kwargs = {}
    if check_vma is not None:
        # new-style vma checking has no old-jax equivalent: the legacy
        # check_rep pass is a DIFFERENT, stricter analysis with no rules
        # for e.g. checkpoint_name — run unchecked instead of mischecked
        kwargs["check_rep"] = False
    elif check_rep is not None:
        kwargs["check_rep"] = bool(check_rep)
    if auto is None and axis_names is not None:
        names = tuple(getattr(mesh, "axis_names", ()) or ())
        auto = frozenset(names) - frozenset(axis_names)
    if auto:
        kwargs["auto"] = frozenset(auto)
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kwargs)


def _axis_size_compat(axis_name):
    import jax.core as jc
    frame = jc.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def _pvary_compat(x, axis_names):  # noqa: ARG001 - signature parity
    return x


def _pcast_compat(x, axis_names, *, to=None):  # noqa: ARG001
    return x


def install():
    """Add the missing names (idempotent; never overrides present ones)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(jax, "typeof"):
        jax.typeof = lambda x: _AvalView(jax.core.get_aval(x))
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size_compat
    if not hasattr(lax, "pvary"):
        lax.pvary = _pvary_compat
    if not hasattr(lax, "pcast"):
        lax.pcast = _pcast_compat
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _get_abstract_mesh_compat
    try:
        from jax.experimental.pallas import tpu as pltpu
        if (not hasattr(pltpu, "CompilerParams")
                and hasattr(pltpu, "TPUCompilerParams")):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:  # pallas optional on some builds
        pass


def _get_abstract_mesh_compat():
    try:
        from jax._src import mesh as _mesh
        return _mesh.get_abstract_mesh()
    except Exception:
        return None
