"""Device-mesh model.

The reference models devices as flat world ranks grouped into DeviceGroups with
per-strategy DeviceGroupHierarchy (reference: hetu/core/device.h,
hetu/graph/distributed_states.h:360-573).  On TPU the idiomatic equivalent is a
named `jax.sharding.Mesh` whose axes are the parallelism dimensions; collectives
then ride ICI along mesh axes.  We standardize the axis vocabulary:

    dp  — data parallel (batch dim)
    cp  — context parallel (sequence dim, ring attention)
    tp  — tensor parallel (Megatron-style; also sequence-parallel axis)
    pp  — pipeline parallel (stage axis)
    ep  — expert parallel (MoE)

"dcp" in the reference (trainer.py:208-260: fused dp×cp input dim) corresponds
here to sharding the batch dim over ("dp","cp") jointly.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: pipeline outermost (cross-slice / DCN friendly), then
# data, context, expert, tensor innermost (tp wants the fastest ICI links).
AXIS_ORDER = ("pp", "dp", "cp", "ep", "tp")

DP_AXIS = "dp"
CP_AXIS = "cp"
TP_AXIS = "tp"
PP_AXIS = "pp"
EP_AXIS = "ep"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape; axes of size 1 are still materialized so that
    PartitionSpecs can always name them (XLA treats size-1 axes as free)."""

    dp: int = 1
    cp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.cp * self.tp * self.pp * self.ep

    def axis_sizes(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "cp": self.cp, "ep": self.ep, "tp": self.tp}

    def __str__(self):
        return "x".join(f"{k}{v}" for k, v in self.axis_sizes().items() if v > 1) or "single"


def create_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a Mesh from a MeshConfig or axis sizes (dp=, tp=, ...).

    Axes are laid out in AXIS_ORDER so that tp varies fastest over adjacent
    devices (best ICI locality), mirroring how the reference orders DS `order`
    vectors innermost-last (reference: distributed_states.h order semantics).
    """
    if config is None:
        config = MeshConfig(**{k: int(v) for k, v in axis_sizes.items()})
    if devices is None:
        devices = jax.devices()
    n = config.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh {config} needs {n} devices but only {len(devices)} available"
        )
    sizes = config.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    dev_array = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


# ---------------------------------------------------------------------------
# Current-mesh context (the analog of the reference graph context stack,
# reference: python/hetu/context.py:50-115).
# ---------------------------------------------------------------------------

_local = threading.local()


def current_mesh() -> Optional[Mesh]:
    return getattr(_local, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _local.mesh = prev


def mesh_axis_size(mesh: Optional[Mesh], axis: str) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))


def single_device_mesh() -> Mesh:
    return create_mesh(MeshConfig())
