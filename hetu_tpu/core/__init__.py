from hetu_tpu.core.mesh import MeshConfig, create_mesh, current_mesh, use_mesh
from hetu_tpu.core import dtypes
from hetu_tpu.core.symbol import IntSymbol
from hetu_tpu.core.distributed import distributed_init
