"""Multi-host bootstrap.

Rebuild of the reference's distributed_init (reference: python/hetu/utils/
parallel/distributed.py:9 — `ht.init_comm_group(ngpus, server_address)` via
the gRPC DeviceController: Connect/GetRank + device mapping).

TPU mapping: low-level process bootstrap is jax.distributed.initialize
(coordination service, NCCL-id-exchange equivalent handled by the runtime);
the framework-level services (KV, barriers, heartbeats, elastic membership)
ride our CoordinationServer/Client on top.  One call wires both.
"""
from __future__ import annotations

from typing import Optional, Tuple

from hetu_tpu.utils.logging import get_logger

logger = get_logger("distributed")


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     control_address: Optional[str] = None,
                     heartbeat_interval: float = 2.0):
    """Initialize multi-host JAX + connect the coordination client.

    coordinator_address: host:port for jax.distributed (every process).
    control_address: host:port of the hetu_tpu CoordinationServer (optional —
      enables KV/barrier/heartbeat/elastic services).
    Env fallbacks: HETU_TPU_COORDINATOR / HETU_TPU_NUM_PROCESSES /
    HETU_TPU_PROCESS_ID / HETU_TPU_CONTROL.

    Returns (num_devices_total, coordination_client_or_None).
    """
    import jax

    from hetu_tpu.utils import flags
    coordinator_address = (coordinator_address
                           or flags.str_flag("HETU_TPU_COORDINATOR") or None)
    env_set = flags.active()
    if num_processes is None and env_set.get("HETU_TPU_NUM_PROCESSES"):
        num_processes = flags.int_flag("HETU_TPU_NUM_PROCESSES")
    if process_id is None and env_set.get("HETU_TPU_PROCESS_ID"):
        process_id = flags.int_flag("HETU_TPU_PROCESS_ID")
    control_address = (control_address
                       or flags.str_flag("HETU_TPU_CONTROL") or None)

    if coordinator_address and (num_processes or 1) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        logger.info(f"jax.distributed up: process {jax.process_index()} of "
                    f"{jax.process_count()}")

    client = None
    if control_address:
        from hetu_tpu.rpc import CoordinationClient
        host, port = control_address.rsplit(":", 1)
        client = CoordinationClient(
            host, int(port),
            info={"process_id": jax.process_index(),
                  "local_devices": len(jax.local_devices())},
            heartbeat_interval=heartbeat_interval)
        logger.info(f"coordination client connected as rank {client.rank}")

    return len(jax.devices()), client
