"""Canonical dtype policy for the framework.

The reference carries its own float16/bfloat16 host types and a per-graph
autocast context (reference: hetu/core/dtype.h, hetu/graph/autocast/autocast.h).
On TPU the natural policy is: parameters and optimizer state in float32,
compute (activations, matmuls) in bfloat16, reductions/softmax/loss in float32.
This module centralizes that policy so models and the trainer agree.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

# Mirrors reference DataType surface (hetu/core/dtype.h) where meaningful on TPU.
float32 = jnp.float32
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float64 = jnp.float64
int32 = jnp.int32
int64 = jnp.int64
int8 = jnp.int8
uint8 = jnp.uint8
bool_ = jnp.bool_


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """Mixed-precision policy (the TPU analog of reference autocast.h:17).

    param_dtype:   dtype parameters are stored in (and optimizer runs in).
    compute_dtype: dtype activations/matmuls run in.
    reduce_dtype:  dtype for softmax / loss / large reductions.
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    reduce_dtype: Any = jnp.float32

    def cast_to_compute(self, x):
        if x.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
            return x.astype(self.compute_dtype)
        return x


# Default policy used by models unless overridden (bf16 AMP, fp32 master).
DEFAULT_POLICY = DTypePolicy()
FULL_PRECISION = DTypePolicy(compute_dtype=jnp.float32)


def finfo(dtype):
    return jnp.finfo(dtype)


def is_floating(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.floating)
