"""Symbolic integers for shape plans.

The reference uses a lazy integer-expression DAG (`SymbolDef`/`IntSymbol`,
reference: hetu/core/symbol.h:19) so one compiled graph serves many dynamic
sequence lengths.  XLA wants static shapes, so on TPU the same role is played
by a *shape plan*: symbols are set per step (from the data pipeline's bucket
choice) and the resolved integer tuple keys a cache of compiled executables
(see hetu_tpu.engine.plan_pool).  The expression DAG is retained so configs can
express derived quantities (e.g. seq_len // cp) exactly like the reference.
"""
from __future__ import annotations

import operator
from typing import Callable, Optional


class IntSymbol:
    """A lazily-evaluated integer.  Leaf symbols are `set_data(v)` at runtime;
    composite symbols evaluate their expression DAG on demand
    (reference: symbol.h leaf/expression semantics)."""

    __slots__ = ("_value", "_fn", "_args", "name")

    def __init__(self, value: Optional[int] = None, *, name: str = ""):
        self._value: Optional[int] = None if value is None else int(value)
        self._fn: Optional[Callable] = None
        self._args: tuple = ()
        self.name = name

    @classmethod
    def _expr(cls, fn: Callable, *args) -> "IntSymbol":
        s = cls()
        s._fn = fn
        s._args = args
        return s

    def set_data(self, value: int) -> "IntSymbol":
        if self._fn is not None:
            raise ValueError("cannot set data on a composite IntSymbol")
        self._value = int(value)
        return self

    def is_leaf(self) -> bool:
        return self._fn is None

    def get(self) -> int:
        if self._fn is None:
            if self._value is None:
                raise ValueError(f"IntSymbol {self.name or id(self)} is unset")
            return self._value
        return int(self._fn(*[a.get() if isinstance(a, IntSymbol) else a for a in self._args]))

    # Python int protocol — resolves eagerly.
    def __int__(self) -> int:
        return self.get()

    def __index__(self) -> int:
        return self.get()

    def _binop(self, other, fn):
        return IntSymbol._expr(fn, self, other)

    def __add__(self, o):
        return self._binop(o, operator.add)

    def __radd__(self, o):
        return IntSymbol._expr(operator.add, o, self)

    def __sub__(self, o):
        return self._binop(o, operator.sub)

    def __rsub__(self, o):
        return IntSymbol._expr(operator.sub, o, self)

    def __mul__(self, o):
        return self._binop(o, operator.mul)

    def __rmul__(self, o):
        return IntSymbol._expr(operator.mul, o, self)

    def __floordiv__(self, o):
        return self._binop(o, operator.floordiv)

    def __mod__(self, o):
        return self._binop(o, operator.mod)

    # Identity-based eq/hash (symbols are nodes in an expression DAG; value
    # comparison is explicit via int(sym) — keeps dict/set semantics sane for
    # the shape-plan pool, which keys on resolved integer tuples, not symbols).
    def __repr__(self):
        try:
            return f"IntSymbol({self.get()})"
        except ValueError:
            return f"IntSymbol(<unset{':' + self.name if self.name else ''}>)"
