"""Varying-manual-axes (vma) helpers for partial-manual shard_map code.

One home for the check-then-promote idiom that hetero-TP, the pipeline
stage bodies and the 1f1b round bodies all need, so the two load-bearing
workarounds live in exactly one place:

* `pvary_missing` routes 16-bit values through f32 on the CPU backend —
  pvary's TRANSPOSE is a psum of the cotangent in the value's dtype, and a
  16-bit all-reduce emitted from a partial-manual region check-fails
  XLA:CPU's AllReducePromotion pass (CreateBinary on a `copy` reducer
  root; minimal repro: bf16 psum inside a shard_map with any auto axis).
  TPU keeps 16-bit collectives: the pass doesn't run there and half the
  bytes ride the ICI.
* `cast_varying` is the branch-agreement promotion (`lax.pcast` to
  varying) used so both `lax.cond` branches and scan carries type-check;
  it does not touch dtypes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def vma_of(x) -> frozenset:
    try:
        return frozenset(getattr(jax.typeof(x), "vma", frozenset()))
    except Exception:
        return frozenset()


def _widen_16bit() -> bool:
    return jax.default_backend() == "cpu"


def pvary_missing(x, axes):
    """pvary x onto any of `axes` not already in its vma set (see module
    docstring for the CPU 16-bit widening)."""
    need = tuple(a for a in axes if a not in vma_of(x))
    if not need:
        return x
    if _widen_16bit() and x.dtype in (jnp.bfloat16, jnp.float16):
        return lax.pvary(x.astype(jnp.float32), need).astype(x.dtype)
    return lax.pvary(x, need)


def align(*xs):
    """Align the vma sets of xs to their union so elementwise/contraction
    ops type-check under check_vma=True."""
    union = set()
    for x in xs:
        union |= set(vma_of(x))
    union = tuple(union)
    return tuple(pvary_missing(x, union) for x in xs)


def cast_varying(x, axes):
    """Promote x to varying over any missing `axes` (lax.pcast) — the
    cond-branch / scan-carry agreement cast."""
    need = tuple(a for a in axes if a not in vma_of(x))
    return lax.pcast(x, need, to="varying") if need else x
