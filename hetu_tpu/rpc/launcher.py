"""Elastic cluster launcher: spawn, monitor and relaunch worker processes.

Rebuild of the reference's launch tooling (reference: python/hetu/rpc/
pssh_start.py — parallel-ssh worker start with env plumbed through,
pssh_start_elastic.py — relaunch loop, heturpc_elastic_server.py:497 node
re-detection + worker restart).  TPU-single-host realization: workers are
local subprocesses (multi-host launch is this launcher invoked per host by
the operator's scheduler — on TPU pods that is usually the platform's own
pod runtime, so ssh fan-out stays out of scope by design); the coordination
server (hetu_tpu.rpc.server) does heartbeat death detection and stop-flag
broadcast, and THIS launcher owns the process lifecycle: spawn, reap,
restart-with-backoff, kill (failure injection for elastic tests).

Worker contract (env):
  HETU_TPU_COORD      host:port of the coordination server
  HETU_TPU_WORKER_ID  stable launcher slot id (0..n-1; a relaunched worker
                      keeps its slot but gets a FRESH coordination rank —
                      the server's split-brain guard demands it)
  HETU_TPU_NUM_WORKERS
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from hetu_tpu.rpc.server import CoordinationServer
from hetu_tpu.utils.logging import get_logger

logger = get_logger("launcher")


class WorkerProc:
    """One launcher slot: the current process + restart accounting."""

    def __init__(self, worker_id: int, popen: subprocess.Popen):
        self.worker_id = worker_id
        self.popen = popen
        self.restarts = 0
        self.exit_code: Optional[int] = None
        self.killed_by_launcher = False


class ElasticLauncher:
    """pssh_start_elastic analog (local processes instead of pssh)."""

    def __init__(self, worker_cmd: Sequence[str], num_workers: int,
                 env: Optional[Dict[str, str]] = None,
                 server: Optional[CoordinationServer] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_restarts: int = 0, restart_backoff: float = 1.0,
                 heartbeat_timeout: float = 10.0,
                 log_dir: Optional[str] = None,
                 coord_address: Optional[str] = None,
                 world_size: Optional[int] = None,
                 worker_id_base: int = 0):
        """Single-host mode: owns (or is handed) the CoordinationServer.

        Per-host mode (the pssh_start.py per-node invocation): pass
        `coord_address` of the CENTRAL coordination server — this launcher
        then only owns its local process slots.  `world_size` is the TOTAL
        worker count across hosts (what workers rendezvous on) and
        `worker_id_base` offsets this host's slot ids so every slot id is
        cluster-unique (the reference rewrites per-host rank offsets in its
        pssh args, elastic_arg_parser.py)."""
        self.worker_cmd = list(worker_cmd)
        self.num_workers = num_workers
        self.extra_env = dict(env or {})
        self.max_restarts = max_restarts
        self.restart_backoff = restart_backoff
        self.log_dir = log_dir
        self.world_size = world_size or num_workers
        self.worker_id_base = worker_id_base
        self._coord_address = coord_address
        if coord_address is not None:
            if server is not None:
                raise ValueError("pass either server= or coord_address=")
            self._owns_server = False
            self.server = None
        else:
            self._owns_server = server is None
            self.server = server or CoordinationServer(
                host=host, port=port, heartbeat_timeout=heartbeat_timeout)
        self.workers: Dict[int, WorkerProc] = {}
        self._log_files: List = []

    # ------------------------------------------------------------------
    @property
    def coord_address(self) -> str:
        if self._coord_address is not None:
            return self._coord_address
        return f"{self.server.host}:{self.server.port}"

    def _spawn(self, worker_id: int, restarts: int = 0) -> WorkerProc:
        env = dict(os.environ)
        env.update(self.extra_env)
        env["HETU_TPU_COORD"] = self.coord_address
        env["HETU_TPU_WORKER_ID"] = str(worker_id)
        env["HETU_TPU_NUM_WORKERS"] = str(self.world_size)
        stdout = stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            f = open(os.path.join(
                self.log_dir, f"worker{worker_id}.log"), "ab")
            self._log_files.append(f)
            stdout = stderr = f
        popen = subprocess.Popen(self.worker_cmd, env=env,
                                 stdout=stdout, stderr=stderr)
        wp = WorkerProc(worker_id, popen)
        wp.restarts = restarts
        logger.info(f"spawned worker {worker_id} pid={popen.pid}"
                    + (f" (restart #{restarts})" if restarts else ""))
        return wp

    def start(self) -> "ElasticLauncher":
        for i in range(self.num_workers):
            wid = self.worker_id_base + i
            self.workers[wid] = self._spawn(wid)
        return self

    # ------------------------------------------------------------------
    def poll(self) -> Dict[int, Optional[int]]:
        """Reap exits; relaunch eligible crashed workers (reference:
        pssh_start_elastic relaunch loop).  Returns worker_id -> exit code
        (None = still running)."""
        out: Dict[int, Optional[int]] = {}
        for wid, wp in list(self.workers.items()):
            rc = wp.popen.poll()
            if rc is None:
                out[wid] = None
                continue
            if wp.exit_code is None:
                wp.exit_code = rc
                logger.info(f"worker {wid} exited rc={rc}")
                if (rc != 0 and not wp.killed_by_launcher
                        and wp.restarts < self.max_restarts):
                    time.sleep(self.restart_backoff)
                    self.workers[wid] = self._spawn(wid, wp.restarts + 1)
                    out[wid] = None
                    continue
            out[wid] = rc
        return out

    def kill(self, worker_id: int, sig: int = signal.SIGKILL,
             relaunch: bool = False):
        """Failure injection: kill a worker (reference: the Malleus/elastic
        experiments kill ranks mid-run).  relaunch=False marks the kill as
        launcher-intended so poll() does not restart it."""
        wp = self.workers[worker_id]
        wp.killed_by_launcher = not relaunch
        try:
            wp.popen.send_signal(sig)
        except ProcessLookupError:
            pass

    def wait(self, timeout: float = 300.0,
             poll_interval: float = 0.5) -> Dict[int, int]:
        """Until every slot has exited (post-relaunch). Returns exit codes."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            codes = self.poll()
            if all(c is not None for c in codes.values()):
                return {k: int(v) for k, v in codes.items()}
            time.sleep(poll_interval)
        raise TimeoutError(
            f"workers still running at timeout: "
            f"{[k for k, v in self.poll().items() if v is None]}")

    def shutdown(self):
        for wp in self.workers.values():
            if wp.popen.poll() is None:
                wp.killed_by_launcher = True
                wp.popen.terminate()
        deadline = time.time() + 5
        for wp in self.workers.values():
            while wp.popen.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if wp.popen.poll() is None:
                wp.popen.kill()
        for f in self._log_files:
            try:
                f.close()
            except OSError:
                pass
        if self._owns_server:
            self.server.close()


def main(argv: Optional[Sequence[str]] = None):
    """CLI: python -m hetu_tpu.rpc.launcher -n 4 [--max-restarts 1] --
    python worker.py args...  (reference: pssh_start.py CLI)."""
    import argparse
    ap = argparse.ArgumentParser(prog="hetu_tpu.rpc.launcher")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="workers on THIS host")
    ap.add_argument("--max-restarts", type=int, default=0)
    ap.add_argument("--heartbeat-timeout", type=float, default=10.0)
    ap.add_argument("--log-dir", default=None)
    ap.add_argument("--coord-address", default=None,
                    help="central coordination server host:port (per-host "
                         "mode; omit to own a local server)")
    ap.add_argument("--world-size", type=int, default=None,
                    help="total workers across hosts (default: -n)")
    ap.add_argument("--worker-id-base", type=int, default=0,
                    help="this host's slot-id offset")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("missing worker command")
    launcher = ElasticLauncher(
        cmd, args.num_workers, max_restarts=args.max_restarts,
        heartbeat_timeout=args.heartbeat_timeout, log_dir=args.log_dir,
        coord_address=args.coord_address, world_size=args.world_size,
        worker_id_base=args.worker_id_base)
    launcher.start()
    try:
        codes = launcher.wait(timeout=10 ** 9)
    finally:
        launcher.shutdown()
    # signal-killed workers report NEGATIVE return codes; any nonzero
    # (either sign) must fail the launch
    sys.exit(1 if any(c != 0 for c in codes.values()) else 0)


if __name__ == "__main__":
    main()
