"""Cluster coordination server.

Rebuild of the reference's gRPC DeviceController service
(reference: protos/heturpc.proto:10-69 — Connect, GetRank, Commit/GetHostName,
Commit/GetDeviceInfo, Barrier, Consistent, HeartBeat, Put/Get KV, Exit,
WorkerStop; python servers rpc/heturpc_polling_server.py:17 and the elastic
variant heturpc_elastic_server.py:39 with heartbeat monitor :463).

TPU-native role: jax.distributed handles low-level multi-host bootstrap; this
service supplies what the reference layers ON TOP over DCN — a KV store,
named barriers, liveness (heartbeats + dead-worker detection), consistency
votes, and stop/relaunch signaling for the elastic trainer.  Implemented as
length-prefixed JSON over TCP (stdlib-only; the reference's proto surface,
minus protoc codegen).
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional, Set

import numpy as np

from hetu_tpu.obs.aggregate import ClusterAggregator
from hetu_tpu.obs.metrics import get_registry
from hetu_tpu.rpc.wire import decode_rows, decode_telemetry, encode_rows
from hetu_tpu.utils.logging import get_logger

logger = get_logger("rpc.server")


def _send(conn: socket.socket, obj: Any):
    data = json.dumps(obj).encode()
    conn.sendall(struct.pack("<I", len(data)) + data)


def _recv(conn: socket.socket) -> Optional[Any]:
    hdr = b""
    while len(hdr) < 4:
        chunk = conn.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return json.loads(buf.decode())


class CoordinationServer:
    """One instance per cluster (reference: DeviceController server)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 world_size: Optional[int] = None,
                 heartbeat_timeout: float = 10.0,
                 reattach_grace: Optional[float] = None,
                 telemetry_window_s: float = 60.0):
        self.world_size = world_size
        self.heartbeat_timeout = heartbeat_timeout
        # how long a rank whose connection tore may `reattach` before it
        # is declared dead (None -> min(heartbeat_timeout, 2s)).  0 =
        # legacy behavior: any connection loss is instant worker death.
        self.reattach_grace = (min(heartbeat_timeout, 2.0)
                               if reattach_grace is None else reattach_grace)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()

        self._lock = threading.Lock()
        self._next_rank = 0
        self._workers: Dict[int, Dict[str, Any]] = {}   # rank -> info
        self._kv: Dict[str, Any] = {}
        self._barriers: Dict[str, Set[int]] = {}
        self._barrier_gen: Dict[str, int] = {}
        self._votes: Dict[str, Dict[int, Any]] = {}
        self._stop_flags: Set[int] = set()
        # PS embedding tables live under their OWN lock: a large pull's
        # base64 encode must not stall heartbeats on the coordination lock
        # (the monitor would mark every worker lost mid-transfer)
        self._ps: Dict[str, np.ndarray] = {}
        self._ps_lock = threading.Lock()
        # cluster telemetry aggregation (hetu_tpu/obs/aggregate.py): folds
        # workers' telemetry_push payloads into the time-windowed
        # ClusterSnapshot.  Owns its own lock — ingest/snapshot must not
        # stall heartbeats on the coordination lock.  Idle (no pushes —
        # HETU_TPU_TELEMETRY_PUSH unset on the workers) it holds no state
        # and costs nothing.
        self.telemetry = ClusterAggregator(window_s=telemetry_window_s)
        self._shutdown = False
        self._threads = []
        self._conns = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self._monitor_thread = threading.Thread(target=self._monitor_loop,
                                                daemon=True)
        self._monitor_thread.start()

    # ------------------------------------------------------------------
    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            # prune finished connection threads (and their sockets) before
            # tracking the new one: long elastic runs see thousands of
            # reconnects, and append-only lists grow without bound
            live = [(x, c) for x, c in zip(self._threads, self._conns)
                    if x.is_alive()]
            self._threads = [x for x, _ in live] + [t]
            self._conns = [c for _, c in live] + [conn]

    def _monitor_loop(self):
        """Dead-worker detection (reference: elastic server HeartBeat monitor
        :463 — on loss, mark dead and signal WorkerStop to the others)."""
        sweep = min(self.heartbeat_timeout / 4,
                    max(self.reattach_grace / 2, 0.05)
                    if self.reattach_grace > 0 else float("inf"))
        while not self._shutdown:
            time.sleep(sweep)
            now = time.time()
            with self._lock:
                # sweep completed vote rounds whose collectors never returned
                # (rounds are client-versioned name#N keys, so deleting an
                # orphan cannot poison a later round)
                for vname in list(self._votes):
                    st = self._votes[vname]
                    if st.get("done_at") and now - st["done_at"] > 60.0:
                        del self._votes[vname]
                    elif st.get("done_at") is None and \
                            now - st.get("started_at", now) > 300.0:
                        # abandoned mid-vote (a member died before count
                        # was reached; clients timed out and moved to a
                        # newer round) — without this the elastic retry
                        # path leaks one entry per interrupted vote
                        del self._votes[vname]
                for rank, info in list(self._workers.items()):
                    if not info.get("alive"):
                        continue
                    if now - info["last_beat"] > self.heartbeat_timeout:
                        # stop BOTH the dead worker (if it resurrects, it must
                        # not rejoin the old mesh — split-brain guard) and the
                        # survivors so they can re-mesh
                        # (reference: WorkerStop broadcast on worker loss)
                        self._mark_lost_locked(rank, "heartbeat timeout")
                    elif info.get("conn_lost_at") is not None and \
                            now - info["conn_lost_at"] > self.reattach_grace:
                        # its connection tore and no reattach arrived
                        # within the grace window: that IS process death
                        self._mark_lost_locked(
                            rank, "connection lost (reattach grace expired)")

    # ------------------------------------------------------------------
    def _serve_conn(self, conn: socket.socket):
        # each client holds ONE persistent socket, so a broken connection is
        # STRONG evidence of process death — but reconnecting clients get a
        # short `reattach_grace` to re-attach their rank before it is
        # declared dead (far shorter than the heartbeat timeout, which can
        # false-positive when a worker's GIL is pinned inside a long XLA
        # compile).  Heartbeats stay as the backstop for network partitions
        # (reference: gRPC channel-break detection).
        state = {"rank": None, "clean": False, "gen": 0}
        try:
            with conn:
                while not self._shutdown:
                    try:
                        req = _recv(conn)
                    except OSError as e:
                        logger.debug(f"conn recv error: {e}")
                        return
                    if req is None:
                        return
                    try:
                        resp = self._handle(req, state)
                    except Exception as e:  # never die on bad input
                        logger.warning(
                            f"handler error for {req.get('op')}: {e!r}")
                        resp = {"ok": False, "error": str(e)}
                    try:
                        _send(conn, resp)
                    except OSError as e:
                        logger.warning(f"conn send error: {e}")
                        return
        finally:
            if state["rank"] is not None and not state["clean"]:
                self._conn_lost(state["rank"], state["gen"])

    def _conn_lost(self, rank: int, gen: int):
        """A worker's connection tore without a clean exit.  With a
        reattach grace window the rank gets that long to come back on a
        new socket (auto-reconnecting client); without one, this is
        instant worker death (legacy behavior)."""
        with self._lock:
            w = self._workers.get(rank)
            if w is None or not w.get("alive"):
                return
            if w.get("conn_gen", 0) != gen:
                return   # a newer connection already took over this rank
            if self.reattach_grace <= 0:
                self._mark_lost_locked(rank, "connection lost")
                return
            w["conn_lost_at"] = time.time()
            logger.info(f"worker {rank} connection lost; "
                        f"{self.reattach_grace:.1f}s reattach grace")

    def _mark_lost(self, rank: int, why: str):
        with self._lock:
            self._mark_lost_locked(rank, why)

    def broadcast_stop(self):
        """Stop-flag every alive worker (the WorkerStop broadcast, from
        the server side).  The orchestrator uses this to force a re-mesh
        when membership GROWS — replacement slots joining after a host
        loss — since growth alone does not trip the loss monitor."""
        with self._lock:
            for r, w in self._workers.items():
                if w.get("alive"):
                    self._stop_flags.add(r)
            self._kv["__membership_change__"] = time.time()

    def alive_ranks(self):
        with self._lock:
            return sorted(r for r, w in self._workers.items()
                          if w.get("alive"))

    def kv_get(self, key, default=None):
        with self._lock:
            return self._kv.get(key, default)

    def _mark_lost_locked(self, rank: int, why: str):
        info = self._workers.get(rank)
        if info is None or not info.get("alive"):
            return
        info["alive"] = False
        info.pop("conn_lost_at", None)
        reg = get_registry()
        reg.inc("rpc.workers_lost", reason=why)
        reg.set_gauge("rpc.alive_workers", sum(
            1 for w in self._workers.values() if w.get("alive")))
        logger.warning(f"worker {rank} lost ({why}); signaling stop "
                       "to survivors")
        self._kv["__membership_change__"] = time.time()
        self._stop_flags.add(rank)
        for r, w in self._workers.items():
            if w.get("alive"):
                self._stop_flags.add(r)

    def _handle(self, req: Dict[str, Any],
                conn_state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        op = req.get("op")
        if isinstance(op, str) and op.startswith("ps_"):
            return self._handle_ps(op, req)
        if op in ("telemetry_push", "telemetry_snapshot"):
            # the aggregator has its own lock; a fat push/snapshot must
            # not stall heartbeats on the coordination lock (same policy
            # as the PS tables)
            return self._handle_telemetry(op, req)
        with self._lock:
            if op == "connect":        # Connect + GetRank
                rank = self._next_rank
                self._next_rank += 1
                self._workers[rank] = {
                    "info": req.get("info", {}), "alive": True,
                    "last_beat": time.time(), "conn_gen": 0}
                reg = get_registry()
                reg.inc("rpc.connects")
                reg.set_gauge("rpc.alive_workers", sum(
                    1 for w in self._workers.values() if w.get("alive")))
                if conn_state is not None:
                    conn_state["rank"] = rank
                    conn_state["gen"] = 0
                return {"ok": True, "rank": rank,
                        "world_size": self.world_size}
            if op == "reattach":       # reconnecting client re-claims rank
                rank = req["rank"]
                w = self._workers.get(rank)
                if w is None:
                    # a RESTARTED server has no membership: accept the
                    # claimed rank (each client claims only the rank it
                    # held, so claims are unique) and grow _next_rank past
                    # it so fresh connects never collide
                    w = self._workers[rank] = {
                        "info": req.get("info", {}), "alive": True,
                        "last_beat": time.time(), "conn_gen": 0}
                    self._next_rank = max(self._next_rank, rank + 1)
                if not w.get("alive"):
                    # declared dead: resurrecting would re-enter the old
                    # mesh (split-brain) — the client must connect fresh
                    return {"ok": True, "accepted": False}
                w["conn_gen"] = w.get("conn_gen", 0) + 1
                w["last_beat"] = time.time()
                w.pop("conn_lost_at", None)
                if conn_state is not None:
                    conn_state["rank"] = rank
                    conn_state["gen"] = w["conn_gen"]
                get_registry().inc("rpc.reattaches")
                return {"ok": True, "accepted": True}
            if op == "heartbeat":      # HeartBeat
                rank = req["rank"]
                stop = rank in self._stop_flags
                if rank in self._workers:
                    now = time.time()
                    prev = self._workers[rank]["last_beat"]
                    self._workers[rank]["last_beat"] = now
                    # straggler visibility: per-worker inter-beat gap
                    # histogram + last-seen gauge (a worker whose gap
                    # creeps toward heartbeat_timeout is about to be
                    # declared dead — see tools_straggler.py)
                    reg = get_registry()
                    reg.observe("rpc.heartbeat_gap_s", now - prev,
                                rank=rank)
                    reg.set_gauge("rpc.worker_last_beat_t", now, rank=rank)
                    # a stop-flagged worker is NOT resurrected by a late
                    # heartbeat — it must re-connect for a fresh rank
                    if not stop:
                        self._workers[rank]["alive"] = True
                return {"ok": True, "stop": stop}
            if op == "put":            # PutJson/PutBytes...
                self._kv[req["key"]] = req["value"]
                return {"ok": True}
            if op == "get":            # GetJson (blocking handled client-side)
                key = req["key"]
                if key in self._kv:
                    return {"ok": True, "found": True, "value": self._kv[key]}
                return {"ok": True, "found": False}
            if op == "barrier":        # Barrier
                name, rank, count = req["name"], req["rank"], req["count"]
                gen = self._barrier_gen.setdefault(name, 0)
                # round pinning makes the enter idempotent: a retried or
                # duplicated enter whose round already RELEASED must not
                # leak into the next round's member set (it would release
                # that round one entrant early and hang this client)
                expect = req.get("gen_expect")
                if expect is not None and gen != expect:
                    return {"ok": True, "released": gen > expect,
                            "gen": gen}
                members = self._barriers.setdefault(name, set())
                members.add(rank)
                if len(members) >= count:
                    self._barrier_gen[name] = gen + 1
                    self._barriers[name] = set()
                    return {"ok": True, "released": True, "gen": gen + 1}
                return {"ok": True, "released": False, "gen": gen}
            if op == "barrier_poll":
                name, gen = req["name"], req["gen"]
                cur = self._barrier_gen.get(name, 0)
                return {"ok": True, "released": cur > gen, "gen": cur}
            if op == "consistent":     # Consistent consensus (:389)
                name, rank, value, count = (req["name"], req["rank"],
                                            req["value"], req["count"])
                st = self._votes.setdefault(
                    name, {"votes": {}, "result": None, "collected": set(),
                           "done_at": None, "started_at": time.time()})
                if st["result"] is not None:
                    # a completed round: hand out the result.  The round
                    # is NOT deleted eagerly on full collection — if the
                    # last collector's response is lost in transit, its
                    # client-side retry must still read the result here
                    # (deleting would recreate a phantom single-vote
                    # round that can never complete).  The monitor's
                    # done_at sweep reclaims it; names are
                    # client-versioned (name#N) so lingering cannot
                    # poison a later round.
                    st["collected"].add(rank)
                    agreed, val = st["result"]
                    return {"ok": True, "done": True, "agreed": agreed,
                            "value": val}
                st["votes"][rank] = value
                if len(st["votes"]) >= count:
                    vals = list(st["votes"].values())
                    agreed = all(v == vals[0] for v in vals)
                    st["result"] = (agreed, vals[0] if agreed else None)
                    st["collected"] = {rank}
                    st["done_at"] = time.time()
                    return {"ok": True, "done": True, "agreed": agreed,
                            "value": vals[0] if agreed else None}
                return {"ok": True, "done": False}
            if op == "membership":     # alive set (elastic re-mesh input)
                return {"ok": True, "alive": sorted(
                    r for r, w in self._workers.items() if w["alive"])}
            if op == "worker_stop":    # WorkerStop broadcast
                ranks = req.get("ranks")
                if ranks is None:
                    ranks = list(self._workers)
                for r in ranks:
                    self._stop_flags.add(r)
                return {"ok": True}
            if op == "resume":        # worker acknowledges the stop and
                                       # rejoins under the new plan
                rank = req["rank"]
                w = self._workers.get(rank)
                if w is None or not w.get("alive"):
                    # a dead-marked worker must reconnect for a fresh rank —
                    # letting it resume would re-enter the old mesh
                    return {"ok": True, "accepted": False}
                self._stop_flags.discard(rank)
                return {"ok": True, "accepted": True}
            if op == "exit":
                rank = req["rank"]
                if rank in self._workers:
                    self._workers[rank]["alive"] = False
                if conn_state is not None:
                    conn_state["clean"] = True
                return {"ok": True}
        raise ValueError(f"unknown op {op!r}")

    def _handle_telemetry(self, op: str, req: Dict[str, Any]) -> Dict[str, Any]:
        """Cluster telemetry plane (docs/observability.md):

        telemetry_push      fold one worker's delta-encoded payload
                            (wire: zlib+base64 JSON — wire.decode_telemetry)
                            into the aggregator.  Idempotent per
                            (worker, boot, seq): retried/duplicated
                            deliveries ack without re-applying, which is
                            what makes the op safe to transport-retry.
        telemetry_snapshot  the live ClusterSnapshot (heartbeat-gap
                            enriched) + the straggler report.  Pure read;
                            observers (tools_cluster.py) may call it on a
                            raw connection without ever joining
                            membership.
        """
        if op == "telemetry_push":
            ack = self.telemetry.ingest(decode_telemetry(req["data"]))
            return {"ok": True, **ack}
        snap = self.cluster_snapshot(window_s=req.get("window_s"))
        return {"ok": True, "snapshot": snap,
                "straggler": self.telemetry.straggler_report(snap)}

    def cluster_snapshot(self, window_s: Optional[float] = None):
        """The live ClusterSnapshot, enriched with per-worker heartbeat
        gaps from the coordination bookkeeping."""
        now = time.time()
        with self._lock:
            hb = {r: now - w["last_beat"] for r, w in self._workers.items()
                  if w.get("alive")}
        return self.telemetry.snapshot(window_s=window_s, heartbeats=hb,
                                       now=now)

    @staticmethod
    def _ps_ids(table, ids) -> np.ndarray:
        """Validated row ids: numpy's negative-index wrapping would silently
        hit the WRONG rows, so reject out-of-range ids of either sign."""
        ids = np.asarray(ids, np.int64)
        if len(ids) and (ids.min() < 0 or ids.max() >= table.shape[0]):
            raise ValueError(
                f"row ids out of range [0, {table.shape[0]}): "
                f"min={ids.min()} max={ids.max()}")
        return ids

    def _handle_ps(self, op: str, req: Dict[str, Any]) -> Dict[str, Any]:
        """Parameter-server embedding tables (reference: v1 PS — hetu/v1
        ps-lite server PSFhandle_embedding.cc pull/push handlers and
        server-side sparse SGD; the HET-paper backing store behind client
        LRU caches, data/embedding_cache.py).  Runs under _ps_lock, NOT the
        coordination lock — see __init__."""
        with self._ps_lock:
            if op == "ps_init":        # idempotent table create
                name = req["name"]
                created = name not in self._ps
                if created:
                    rows, dim = int(req["rows"]), int(req["dim"])
                    kind = req.get("init", "zeros")
                    if kind == "zeros":
                        tab = np.zeros((rows, dim), np.float32)
                    elif kind == "normal":
                        rng = np.random.default_rng(int(req.get("seed", 0)))
                        tab = (rng.standard_normal((rows, dim)) *
                               float(req.get("scale", 0.02))).astype(
                                   np.float32)
                    else:
                        raise ValueError(f"unknown init {kind!r}")
                    self._ps[name] = tab
                t = self._ps[name]
                return {"ok": True, "created": created,
                        "rows": t.shape[0], "dim": t.shape[1]}
            if op == "ps_pull":        # ids -> base64 float32 rows
                t = self._ps[req["name"]]
                ids = self._ps_ids(t, req["ids"])
                data = np.ascontiguousarray(t[ids]) if len(ids) else \
                    np.zeros((0, t.shape[1]), np.float32)
            elif op == "ps_push":      # assign / add / server-side sgd
                t = self._ps[req["name"]]
                ids = self._ps_ids(t, req["ids"])
                rows = decode_rows(req["data"], len(ids), t.shape[1])
                mode = req.get("mode", "assign")
                if mode == "assign":
                    t[ids] = rows          # last write wins per duplicate
                elif mode == "add":        # duplicates accumulate
                    np.add.at(t, ids, rows)
                elif mode == "sgd":        # row -= lr * grad, duplicates sum
                    np.add.at(t, ids, -float(req.get("lr", 0.01)) * rows)
                else:
                    raise ValueError(f"unknown push mode {mode!r}")
                return {"ok": True}
            else:
                raise ValueError(f"unknown op {op!r}")
        # encode OUTSIDE the ps lock too: only the gather needs the table
        return {"ok": True, "dim": int(data.shape[1]),
                "data": encode_rows(data)}

    def close(self):
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        # also tear down the serving connections: a closed server must not
        # keep absorbing (and acking!) writes on old sockets — clients
        # should see the break and fail over / reconnect
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._conns = []
