"""Multi-host orchestration: spawn per-host launchers, detect HOST loss,
respawn lost slots on the survivors.

Rebuild of the reference's cross-host elastic tooling (reference:
python/hetu/rpc/pssh_start.py — per-node worker launch over parallel-ssh;
pssh_start_elastic.py — the relaunch loop; heturpc_elastic_server.py:497
`detect_node_info` — survivor re-detection and strategy-arg rewrite for the
remaining nodes).  TPU realization: a "host" is a launcher subprocess
(`python -m hetu_tpu.rpc.launcher --coord-address ...`) started in its own
process group, so killing the group is a whole-host crash; on a real pod
each spawn line would go through `ssh <host> ...` instead — the ssh
transport is the ONLY thing this module leaves to the platform.

The division of labor (all automatic, no operator action):
  * the coordination server (owned here) detects WORKER loss by heartbeat
    and stop-flags everyone; survivors re-plan in place and resume from
    checkpoint (engine/elastic.py ElasticController) — the reference
    instead restarts workers with rewritten args, which costs a full
    process restart per re-mesh;
  * THIS orchestrator detects HOST loss (the launcher process group died),
    and — when `respawn_lost_slots` — respawns the lost worker slots on a
    surviving host with fresh cluster-unique slot ids, then broadcasts a
    stop so the grown membership re-meshes (the joiners adopt the cluster
    epoch from the KV store — ElasticController._replan).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from hetu_tpu.rpc.server import CoordinationServer
from hetu_tpu.utils.logging import get_logger

logger = get_logger("orchestrator")


class HostProc:
    """One 'host': a launcher subprocess in its own process group."""

    def __init__(self, name: str, popen: subprocess.Popen,
                 slots: Sequence[int]):
        self.name = name
        self.popen = popen
        self.slots = list(slots)
        self.lost = False
        self.killed_by_orchestrator = False


class MultiHostOrchestrator:
    """pssh_start_elastic analog, one level above ElasticLauncher."""

    def __init__(self, worker_cmd: Sequence[str], hosts: Dict[str, int],
                 env: Optional[Dict[str, str]] = None,
                 heartbeat_timeout: float = 10.0,
                 log_dir: Optional[str] = None,
                 respawn_lost_slots: bool = False,
                 max_respawns: int = 1):
        """hosts: name -> worker count on that host.  Slot ids are assigned
        contiguously in dict order (cluster-unique; the reference rewrites
        per-host rank offsets in its pssh args)."""
        self.worker_cmd = list(worker_cmd)
        self.hosts_spec = dict(hosts)
        self.extra_env = dict(env or {})
        self.log_dir = log_dir
        self.respawn_lost_slots = respawn_lost_slots
        self.max_respawns = max_respawns
        self.world_size = sum(hosts.values())
        self.server = CoordinationServer(heartbeat_timeout=heartbeat_timeout)
        self.hosts: Dict[str, HostProc] = {}
        self._next_slot = self.world_size
        self._respawns = 0
        self.events: List[Dict] = []

    # ------------------------------------------------------------------
    @property
    def coord_address(self) -> str:
        return f"{self.server.host}:{self.server.port}"

    def _spawn_host(self, name: str, slots: Sequence[int]) -> HostProc:
        """One launcher subprocess == one host (ssh-equivalent line in
        `HostProc.popen.args` for a real deployment)."""
        cmd = [sys.executable, "-m", "hetu_tpu.rpc.launcher",
               "-n", str(len(slots)),
               "--coord-address", self.coord_address,
               "--world-size", str(self.world_size),
               "--worker-id-base", str(min(slots))]
        if self.log_dir:
            cmd += ["--log-dir", os.path.join(self.log_dir, f"host_{name}")]
        cmd += ["--"] + self.worker_cmd
        env = dict(os.environ)
        env.update(self.extra_env)
        popen = subprocess.Popen(cmd, env=env, start_new_session=True)
        hp = HostProc(name, popen, slots)
        logger.info(f"host {name}: launcher pid={popen.pid} slots={slots}")
        self.events.append({"event": "host_spawn", "host": name,
                            "slots": list(slots)})
        return hp

    def start(self) -> "MultiHostOrchestrator":
        base = 0
        for name, n in self.hosts_spec.items():
            slots = list(range(base, base + n))
            self.hosts[name] = self._spawn_host(name, slots)
            base += n
        return self

    # ------------------------------------------------------------------
    def kill_host(self, name: str):
        """Failure injection: crash the WHOLE host (launcher + workers, the
        process group) — the reference's node-loss experiment."""
        hp = self.hosts[name]
        hp.killed_by_orchestrator = True
        try:
            os.killpg(os.getpgid(hp.popen.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def membership(self) -> List[int]:
        return self.server.alive_ranks()

    # ------------------------------------------------------------------
    def poll(self) -> Dict[str, Optional[int]]:
        """Reap host exits; on an UNEXPECTED host loss, optionally respawn
        its slots on a surviving host (fresh cluster-unique slot ids) and
        broadcast a stop so the grown membership re-meshes."""
        out: Dict[str, Optional[int]] = {}
        for name, hp in list(self.hosts.items()):
            rc = hp.popen.poll()
            out[name] = rc
            if rc is None or hp.lost:
                continue
            hp.lost = True
            logger.warning(f"host {name} gone (rc={rc}); "
                           f"slots {hp.slots} lost")
            self.events.append({"event": "host_loss", "host": name,
                                "slots": list(hp.slots), "rc": rc})
            clean_exit = rc == 0 and not hp.killed_by_orchestrator
            if (self.respawn_lost_slots and not clean_exit
                    and self._respawns < self.max_respawns):
                survivor = next((n for n, h in self.hosts.items()
                                 if not h.lost and h.popen.poll() is None),
                                None)
                if survivor is None:
                    logger.error("no surviving host to respawn on")
                    continue
                self._respawns += 1
                slots = list(range(self._next_slot,
                                   self._next_slot + len(hp.slots)))
                self._next_slot += len(hp.slots)
                newname = f"{survivor}+{name}"
                # in a real deployment this spawn line runs over
                # `ssh <survivor>` — detect_node_info + relaunch analog
                self.hosts[newname] = self._spawn_host(newname, slots)
                self.events.append({"event": "respawn", "host": newname,
                                    "on": survivor, "slots": slots})
                # the joined-worker target is re-derived each tick from
                # the SLOT layout (live hosts' slot counts): membership
                # sampled here can still count the just-killed host's
                # workers whose socket-close the server hasn't processed
                self._pending_remesh = {
                    "deadline": time.time() + 180.0,
                    "next_cast": 0.0, "casts": 0}
        self._drive_pending_remesh()
        return out

    def _remesh_converged(self) -> bool:
        """True when the LATEST re-plan epoch covered every alive rank —
        the ElasticController publishes each round's membership."""
        epoch = int(self.server.kv_get("__elastic_epoch__", 0))
        members = self.server.kv_get(f"__elastic_members_e{epoch}__", [])
        alive = self.server.alive_ranks()
        return bool(alive) and set(alive) <= set(members)

    def _drive_pending_remesh(self):
        """Non-blocking remesh driver, stepped from poll(): once the
        replacement workers have connected, stop-flag everyone until a
        re-plan epoch covers the grown membership.  Growth does not trip
        the server's loss monitor, and a single broadcast can race a
        survivor's in-flight rebuild (its resume() clears the flag) — so
        this RE-broadcasts until the published epoch membership shows
        convergence.  Runs as a state machine so poll() keeps reaping
        other hosts' exits meanwhile."""
        pr = getattr(self, "_pending_remesh", None)
        if pr is None:
            return
        now = time.time()
        # live slot count by layout, not by a frozen membership sample
        want = sum(len(hp.slots) for hp in self.hosts.values()
                   if not hp.lost and hp.popen.poll() is None)
        joined = len(self.membership()) >= want > 0
        done = joined and self._remesh_converged()
        if done or now > pr["deadline"]:
            self._pending_remesh = None
            self.events.append({"event": "remesh_broadcast",
                                "alive": self.membership(),
                                "broadcasts": pr["casts"],
                                "converged": done})
            return
        if joined and now >= pr["next_cast"]:
            self.server.broadcast_stop()
            pr["casts"] += 1
            pr["next_cast"] = now + 3.0

    # ------------------------------------------------------------------
    def monitor(self, poll_interval: float = 0.5,
                until: Optional[float] = None):
        """Poll until every host's launcher has exited (or `until`)."""
        deadline = time.time() + until if until else None
        while True:
            codes = self.poll()
            if all(c is not None for c in codes.values()):
                return codes
            if deadline and time.time() > deadline:
                return codes
            time.sleep(poll_interval)

    def shutdown(self):
        for hp in self.hosts.values():
            if hp.popen.poll() is None:
                hp.killed_by_orchestrator = True
                try:
                    os.killpg(os.getpgid(hp.popen.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + 5
        for hp in self.hosts.values():
            while hp.popen.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if hp.popen.poll() is None:
                try:
                    os.killpg(os.getpgid(hp.popen.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self.server.close()
