"""Wire codecs shared by the rpc client and server, so the formats cannot
drift (dtype/endianness/compression changes happen in exactly one place):

* PS row payloads — contiguous float32 + base64;
* telemetry push payloads — zlib-compressed compact JSON + base64 (a
  worker's delta-encoded metrics snapshot + RunLog tail is repetitive
  key-heavy JSON; compression cuts the bytes-on-wire of the periodic
  push by ~5-10x so telemetry stays negligible next to heartbeats).
"""
from __future__ import annotations

import base64
import json
import zlib

import numpy as np


def encode_rows(rows) -> str:
    return base64.b64encode(
        np.ascontiguousarray(rows, np.float32).tobytes()).decode()


def decode_rows(data: str, n: int, dim: int) -> np.ndarray:
    return np.frombuffer(base64.b64decode(data),
                         np.float32).reshape(n, dim).copy()


def encode_telemetry(payload: dict) -> str:
    """Telemetry push payload -> compressed base64 string (the `data`
    field of the `telemetry_push` op)."""
    raw = json.dumps(payload, separators=(",", ":")).encode()
    return base64.b64encode(zlib.compress(raw)).decode()


def decode_telemetry(data: str) -> dict:
    return json.loads(zlib.decompress(base64.b64decode(data)).decode())
