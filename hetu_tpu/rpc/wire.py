"""Wire codec for PS row payloads: contiguous float32 + base64.
One definition shared by client and server so the format cannot drift
(dtype/endianness changes happen in exactly one place)."""
from __future__ import annotations

import base64

import numpy as np


def encode_rows(rows) -> str:
    return base64.b64encode(
        np.ascontiguousarray(rows, np.float32).tobytes()).decode()


def decode_rows(data: str, n: int, dim: int) -> np.ndarray:
    return np.frombuffer(base64.b64decode(data),
                         np.float32).reshape(n, dim).copy()
