from hetu_tpu.rpc.server import CoordinationServer
from hetu_tpu.rpc.client import CoordinationClient
