"""Coordination client (reference: hetu/impl/communication/rpc_client.cc —
the C++ DeviceClient with Connect/GetRank/Barrier/KV/HeartBeat; and
python/hetu/rpc/kv_store/client.py:101 KeyValueStoreClient).

Worker-side API used by distributed_init, the elastic trainer, and the
Hydraulis-style dynamic dispatch (KV producer/consumer).

Transport hardening (docs/fault_tolerance.md): every exchange carries a
per-op deadline (the socket timeout); on a torn/hung connection the client
auto-reconnects with exponential backoff + full jitter and re-attaches its
rank (`reattach` op — the server keeps the rank alive across a short
reconnect grace window).  Only idempotent ops are re-issued after a
reconnect — `connect` (allocates a rank) and `consistent` vote submissions
(round-versioned; retried by `consistent()` itself, which pins the round)
are not.  The chaos wire hook (`hetu_tpu.chaos`) injects message
drop/delay/duplicate faults here; with no plan installed it is identity.
"""
from __future__ import annotations

import random
import socket
import threading
import time
from typing import Any, Dict, Optional

from hetu_tpu import chaos
from hetu_tpu.rpc.server import _recv, _send
from hetu_tpu.utils.logging import get_logger

logger = get_logger("rpc.client")

#: ops safe to re-issue after a transparent reconnect: reads, last-write-
#: wins writes, and set-insert style membership ops.  `barrier` qualifies
#: only because barrier() pins every enter to its round via gen_expect —
#: the re-sent payload carries the pin, so a retry spanning a release
#: reads the release instead of leaking into the next round.  NOT here:
#: `connect` (allocates a fresh rank per call), `reattach` (issued by the
#: reconnect path itself), `consistent` (vote rounds are
#: client-versioned; blind transport retry could double-submit across
#: rounds — consistent() retries internally with the round pinned),
#: `ps_push` (add/sgd modes accumulate — double-apply corrupts the
#: table).
_RETRYABLE_OPS = frozenset({
    "heartbeat", "get", "put", "membership", "barrier", "barrier_poll",
    "worker_stop", "resume", "ps_init", "ps_pull", "exit",
    # telemetry_push is idempotent by construction: the server folds each
    # (worker, boot, seq) exactly once, so a retry whose first delivery
    # DID land just acks without re-applying; telemetry_snapshot is a read
    "telemetry_push", "telemetry_snapshot"})

#: re-issue budget per op after reconnects (each retry means the transport
#: was re-established in between; a chaos partition window of N dropped
#: messages needs N retries to drain)
_MAX_OP_RETRIES = 8


class VoteDisagreement(RuntimeError):
    """A `consistent` vote completed and the participants DISAGREED — a
    real consensus conflict (e.g. the elastic dual-leader race), distinct
    from the generic RuntimeError `_call` raises for any rpc failure.
    Catchers recovering from vote conflicts must match this type, not
    bare RuntimeError, or they misclassify transport/server errors."""


class StaleRankError(ConnectionError):
    """Reconnect succeeded at the TCP level but the server refused to
    re-attach this rank: it was declared dead (split-brain guard).  The
    only way forward is a fresh CoordinationClient (new rank) — retrying
    with this one can never work, so this is terminal, not transient."""


class CoordinationClient:
    def __init__(self, host: str, port: int, info: Optional[Dict] = None,
                 heartbeat_interval: float = 2.0, auto_heartbeat: bool = True,
                 op_timeout: float = 30.0, reconnect: bool = True,
                 max_reconnect_wait: float = 60.0):
        self._addr = (host, port)
        self._lock = threading.Lock()
        self._info = info or {}
        self._op_timeout = op_timeout
        self._reconnect_enabled = reconnect
        self._max_reconnect_wait = max_reconnect_wait
        self._rng = random.Random()     # backoff jitter only
        self._shutdown = False
        self._conn_gen = 0
        self.rank: Optional[int] = None
        #: observable transport state (the elastic controller reads these
        #: instead of discovering a silently dead heartbeat thread):
        self.disconnected = False       # no live socket right now
        self.heartbeat_lost = False     # beat thread saw a transport error
        self.stale = False              # rank declared dead server-side
        self.reconnects = 0
        self._conn = self._open_socket()
        resp = self._call({"op": "connect", "info": self._info})
        self.rank = resp["rank"]
        self.world_size = resp.get("world_size")
        self.should_stop = False
        self._vote_round: Dict[str, int] = {}
        self._hb_interval = heartbeat_interval
        if auto_heartbeat:
            self._hb = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True)
            self._hb.start()

    # ------------------------------------------------------------------
    def _open_socket(self,
                     connect_timeout: Optional[float] = None
                     ) -> socket.socket:
        # connect deadline defaults to the per-op deadline; the reconnect
        # loop passes its REMAINING budget instead, so a black-hole
        # partition (SYNs dropped, no RST) cannot pin one attempt — and
        # the lock — for longer than the caller's whole budget
        if connect_timeout is None:
            connect_timeout = self._op_timeout or 30.0
        conn = socket.create_connection(self._addr, timeout=connect_timeout)
        # per-op deadline: every send/recv on this socket times out on its
        # own instead of hanging a caller forever on a wedged server
        conn.settimeout(self._op_timeout if self._op_timeout else None)
        return conn

    def _exchange_locked(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """One request/response on the current socket (caller holds the
        lock).  The chaos wire hook sits here — identity when no plan."""
        plan = chaos.get_plan()
        if plan is not None:
            spec = plan.wire_fault(req.get("op", ""), self.rank)
            if spec is not None:
                if spec.kind == "rpc_delay":
                    time.sleep(spec.delay_s)
                elif spec.kind == "rpc_drop":
                    # the message vanishes: tear the socket so the loss is
                    # observable NOW (the torn-TCP analog of a dropped
                    # datagram) instead of hanging out a full deadline
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    raise ConnectionError(
                        f"chaos: dropped {req.get('op')!r} in transit")
                elif spec.kind == "rpc_dup":
                    # duplicate delivery: the server must handle the same
                    # request twice (idempotency check); framing stays
                    # aligned because both responses are consumed here
                    _send(self._conn, req)
                    _send(self._conn, req)
                    if _recv(self._conn) is None:
                        raise ConnectionError(
                            "server closed on duplicated request")
                    resp = _recv(self._conn)
                    if resp is None:
                        raise ConnectionError(
                            "server closed on duplicated request")
                    return resp
        _send(self._conn, req)
        resp = _recv(self._conn)
        if resp is None:
            raise ConnectionError("coordination server closed the connection")
        return resp

    def _call(self, req: Dict[str, Any],
              _max_wait: Optional[float] = None) -> Dict[str, Any]:
        from hetu_tpu.obs.metrics import get_registry
        op = req.get("op", "")
        attempts = 0
        while True:
            err: Optional[BaseException] = None
            with self._lock:
                gen = self._conn_gen
                try:
                    resp = self._exchange_locked(req)
                except (ConnectionError, OSError) as e:   # incl. timeouts
                    err = e
            if err is None:
                break
            get_registry().inc("rpc.transport_errors", op=op)
            if self._shutdown or not self._reconnect_enabled or \
                    self.rank is None:
                raise err
            # re-establish the transport regardless of the op — later ops
            # need a live socket — but only re-ISSUE idempotent ops
            self._reconnect(gen, err, max_wait=_max_wait)
            attempts += 1
            if op not in _RETRYABLE_OPS:
                raise ConnectionError(
                    f"rpc op {op!r} failed in transit ({err!r}); not "
                    "retried (non-idempotent) — connection re-established"
                ) from err
            if attempts > _MAX_OP_RETRIES:
                raise ConnectionError(
                    f"rpc op {op!r} still failing after "
                    f"{_MAX_OP_RETRIES} reconnect+retry cycles") from err
            get_registry().inc("rpc.op_retries", op=op)
        if not resp.get("ok"):
            raise RuntimeError(f"rpc error: {resp.get('error')}")
        return resp

    def _reconnect(self, gen: int, why: BaseException,
                   max_wait: Optional[float] = None):
        """Replace a torn connection: exponential backoff + full jitter,
        then `reattach` so the server keeps this rank alive.  Raises
        StaleRankError if the server already declared the rank dead, or
        ConnectionError when the budget (`max_wait`) runs out."""
        from hetu_tpu.obs.metrics import get_registry
        budget = self._max_reconnect_wait if max_wait is None else max_wait
        with self._lock:
            if self._conn_gen != gen:
                return   # another thread already re-established transport
            was_down = self.disconnected
            self.disconnected = True
            reg = get_registry()
            if not was_down:
                reg.inc("rpc.disconnects")
                logger.warning(f"connection to {self._addr} lost "
                               f"({why!r}); reconnecting with backoff")
            try:
                self._conn.close()
            except OSError:
                pass
            delay = 0.05
            deadline = time.monotonic() + budget
            last: BaseException = why
            while not self._shutdown:
                try:
                    conn = self._open_socket(connect_timeout=max(
                        0.05, min(self._op_timeout or 5.0,
                                  deadline - time.monotonic())))
                    _send(conn, {"op": "reattach", "rank": self.rank,
                                 "info": self._info})
                    resp = _recv(conn)
                    if resp is None:
                        raise ConnectionError("server closed during reattach")
                    if not resp.get("ok"):
                        raise ConnectionError(
                            f"reattach error: {resp.get('error')}")
                    if not resp.get("accepted", False):
                        conn.close()
                        self.stale = True
                        raise StaleRankError(
                            f"reattach rejected: rank {self.rank} was "
                            "declared dead — a fresh CoordinationClient "
                            "(new rank) is required")
                    self._conn = conn
                    self._conn_gen += 1
                    self.disconnected = False
                    self.reconnects += 1
                    reg.inc("rpc.reconnects")
                    logger.info(f"reconnected to {self._addr} "
                                f"(rank {self.rank} reattached)")
                    return
                except StaleRankError:
                    raise
                except (ConnectionError, OSError) as e:
                    last = e
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"reconnect to {self._addr} gave up after "
                        f"{budget:.1f}s: {last!r}") from last
                time.sleep(delay * (0.5 + self._rng.random()))
                delay = min(delay * 2.0, 2.0)
            raise ConnectionError("client shut down during reconnect")

    def _heartbeat_loop(self):
        from hetu_tpu.obs.metrics import get_registry
        reg = get_registry()
        beat = 0
        while not self._shutdown:
            plan = chaos.get_plan()
            if plan is not None:
                stall = plan.heartbeat_stall(beat, self.rank)
                if stall > 0:
                    time.sleep(stall)   # a GIL-pinned XLA compile, faked
            try:
                t0 = time.perf_counter()
                # short per-call reconnect budget: a dead server must not
                # wedge one beat for minutes — the LOOP is the retry, at
                # the beat cadence, so long partitions are still survived
                resp = self._call({"op": "heartbeat", "rank": self.rank},
                                  _max_wait=min(5.0,
                                                self._max_reconnect_wait))
                # heartbeat RTT is the cheapest coordination-health probe
                # each worker has: a climbing p95 here means the control
                # plane (not the compute) is the straggler
                reg.observe("rpc.heartbeat_rtt_s",
                            time.perf_counter() - t0, rank=self.rank)
                if resp.get("stop"):
                    self.should_stop = True
                self.heartbeat_lost = False
            except StaleRankError:
                # the server declared this rank dead: beating can never
                # help — flag it (self.stale) so the elastic layer can
                # surface "reconnect with a fresh client" and stop
                if not self.heartbeat_lost:
                    self.heartbeat_lost = True
                    reg.inc("rpc.heartbeat_lost")
                logger.warning(
                    f"heartbeat stopped: rank {self.rank} declared dead "
                    "by the server (stale rank)")
                return
            except (ConnectionError, OSError, RuntimeError) as e:
                # a broken socket must NEVER silently kill the beat
                # thread: flag + count, keep beating — _call already
                # attempted reconnect-with-backoff for this beat
                if not self.heartbeat_lost:
                    self.heartbeat_lost = True
                    reg.inc("rpc.heartbeat_lost")
                    logger.warning(f"heartbeat failed ({e!r}); transport "
                                   "flagged, retrying at beat cadence")
            beat += 1
            time.sleep(self._hb_interval)

    # -- KV store (reference: KeyValueStoreClient) ----------------------
    def put(self, key: str, value: Any):
        self._call({"op": "put", "key": key, "value": value})

    def get(self, key: str, block: bool = False,
            timeout: float = 60.0) -> Any:
        deadline = time.time() + timeout
        while True:
            resp = self._call({"op": "get", "key": key})
            if resp["found"]:
                return resp["value"]
            if not block:
                raise KeyError(key)
            if time.time() > deadline:
                raise TimeoutError(f"kv key {key!r} not available")
            time.sleep(0.05)

    # -- barrier / consensus -------------------------------------------
    def barrier(self, name: str, count: int, timeout: float = 120.0):
        # snapshot the round id first, and pin the enter to it
        # (gen_expect): a transport-retried enter whose round released
        # while the response was in flight reads the release instead of
        # silently joining — and poisoning — the NEXT round
        gen0 = self._call({"op": "barrier_poll", "name": name,
                           "gen": -1}).get("gen", 0)
        resp = self._call({"op": "barrier", "name": name, "rank": self.rank,
                           "count": count, "gen_expect": gen0})
        if resp["released"]:
            return
        gen = resp["gen"]
        deadline = time.time() + timeout
        while time.time() < deadline:
            resp = self._call({"op": "barrier_poll", "name": name, "gen": gen})
            if resp["released"]:
                return
            time.sleep(0.02)
        raise TimeoutError(f"barrier {name!r} timed out")

    def consistent(self, name: str, value: Any, count: int,
                   timeout: float = 60.0) -> Any:
        """All `count` participants must agree on `value`
        (reference: elastic server Consistent :389).  Each call advances a
        per-name round counter so reusing a name never mixes rounds (all
        participants must call the same number of times — the natural
        once-per-decision usage)."""
        rnd = self._vote_round.get(name, 0)
        self._vote_round[name] = rnd + 1
        name = f"{name}#{rnd}"
        deadline = time.time() + timeout
        while True:
            try:
                resp = self._call({"op": "consistent", "name": name,
                                   "rank": self.rank, "value": value,
                                   "count": count})
            except StaleRankError:
                raise
            except ConnectionError:
                # the generic layer won't blindly re-send votes, but HERE
                # the round identity is pinned: re-submitting the same
                # (name#round, rank, value) is idempotent server-side (a
                # dict insert keyed by rank), so retry within the deadline
                if time.time() > deadline:
                    raise TimeoutError(
                        f"consistent {name!r} timed out (transport)")
                time.sleep(0.05)
                continue
            if resp["done"]:
                if not resp["agreed"]:
                    raise VoteDisagreement(
                        f"consistency vote {name!r} failed")
                return resp["value"]
            if time.time() > deadline:
                raise TimeoutError(f"consistent {name!r} timed out")
            time.sleep(0.05)

    # -- elastic membership --------------------------------------------
    def membership(self):
        return self._call({"op": "membership"})["alive"]

    def worker_stop(self, ranks=None):
        self._call({"op": "worker_stop", "ranks": ranks})

    def resume(self):
        """Acknowledge a stop signal after re-meshing (clears the server's
        stop flag for this rank).  Raises if this rank was declared dead —
        a zombie must reconnect for a fresh rank (split-brain guard)."""
        resp = self._call({"op": "resume", "rank": self.rank})
        if not resp.get("accepted", True):
            raise RuntimeError(
                "resume rejected: this rank was declared dead — reconnect "
                "with a new CoordinationClient for a fresh rank")
        self.should_stop = False

    def check_stop(self) -> bool:
        """Synchronous, race-free stop check (a fresh heartbeat op) — the
        cached should_stop can be momentarily stale around resume()."""
        resp = self._call({"op": "heartbeat", "rank": self.rank})
        stop = bool(resp.get("stop"))
        self.should_stop = stop
        return stop

    # -- cluster telemetry (hetu_tpu/obs/aggregate.py) ------------------
    def telemetry_push(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Ship one delta-encoded telemetry payload (a TelemetrySource
        product) to the coordination server.  Safe to transport-retry:
        the server dedupes on the payload's (worker, boot, seq)."""
        from hetu_tpu.rpc.wire import encode_telemetry
        resp = self._call({"op": "telemetry_push", "rank": self.rank,
                           "data": encode_telemetry(payload)})
        return {"applied": resp.get("applied"), "seq": resp.get("seq")}

    def telemetry_snapshot(self,
                           window_s: Optional[float] = None
                           ) -> Dict[str, Any]:
        """The coordinator's live ClusterSnapshot + straggler report."""
        req: Dict[str, Any] = {"op": "telemetry_snapshot"}
        if window_s is not None:
            req["window_s"] = float(window_s)
        resp = self._call(req)
        return {"snapshot": resp.get("snapshot"),
                "straggler": resp.get("straggler")}

    # -- parameter-server embedding tables (reference: v1 ps-lite worker
    # ops ParameterServerCommunicate.py pull/push; server side handlers in
    # rpc/server.py ps_init/ps_pull/ps_push) ---------------------------
    def ps_init(self, name: str, rows: int, dim: int, init: str = "zeros",
                scale: float = 0.02, seed: int = 0) -> Dict[str, Any]:
        """Create (idempotently) a server-resident embedding table."""
        return self._call({"op": "ps_init", "name": name, "rows": rows,
                           "dim": dim, "init": init, "scale": scale,
                           "seed": seed})

    def ps_pull(self, name: str, ids):
        """ids [n] -> float32 rows [n, dim] (the PS pull)."""
        import numpy as np

        from hetu_tpu.rpc.wire import decode_rows
        ids = np.asarray(ids, np.int64)
        resp = self._call({"op": "ps_pull", "name": name,
                           "ids": ids.tolist()})
        return decode_rows(resp["data"], len(ids), int(resp["dim"]))

    def ps_push(self, name: str, ids, rows, mode: str = "assign",
                lr: float = 0.01):
        """Write rows back: mode 'assign' (last write wins), 'add'
        (duplicates accumulate), or 'sgd' (row -= lr * grad, server-side
        sparse update — the reference PS optimizer path)."""
        import numpy as np

        from hetu_tpu.rpc.wire import encode_rows
        ids = np.asarray(ids, np.int64)
        self._call({"op": "ps_push", "name": name, "ids": ids.tolist(),
                    "data": encode_rows(rows), "mode": mode, "lr": lr})

    def exit(self):
        self._shutdown = True   # before the call: no reconnect spin on a
        try:                    # dead server during teardown
            self._call({"op": "exit", "rank": self.rank})
        except (ConnectionError, OSError, RuntimeError):
            pass
        try:
            self._conn.close()
        except OSError:
            pass


def fetch_cluster_snapshot(host: str, port: int,
                           window_s: Optional[float] = None,
                           timeout: float = 10.0) -> Dict[str, Any]:
    """One-shot OBSERVER fetch of the ClusterSnapshot + straggler report.

    Deliberately NOT a CoordinationClient: connecting one allocates a
    rank and joins membership, so a dashboard poll would look like a
    worker (and its disconnect like a worker death, stop-flagging the
    whole cluster).  This opens a bare connection, exchanges a single
    telemetry_snapshot, and leaves no trace — tools_cluster.py's path."""
    conn = socket.create_connection((host, port), timeout=timeout)
    try:
        conn.settimeout(timeout)
        req: Dict[str, Any] = {"op": "telemetry_snapshot"}
        if window_s is not None:
            req["window_s"] = float(window_s)
        _send(conn, req)
        resp = _recv(conn)
        if resp is None:
            raise ConnectionError("server closed during telemetry_snapshot")
        if not resp.get("ok"):
            raise RuntimeError(f"telemetry_snapshot error: "
                               f"{resp.get('error')}")
        return {"snapshot": resp.get("snapshot"),
                "straggler": resp.get("straggler")}
    finally:
        try:
            conn.close()
        except OSError:
            pass
