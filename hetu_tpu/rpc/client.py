"""Coordination client (reference: hetu/impl/communication/rpc_client.cc —
the C++ DeviceClient with Connect/GetRank/Barrier/KV/HeartBeat; and
python/hetu/rpc/kv_store/client.py:101 KeyValueStoreClient).

Worker-side API used by distributed_init, the elastic trainer, and the
Hydraulis-style dynamic dispatch (KV producer/consumer)."""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional

from hetu_tpu.rpc.server import _recv, _send


class VoteDisagreement(RuntimeError):
    """A `consistent` vote completed and the participants DISAGREED — a
    real consensus conflict (e.g. the elastic dual-leader race), distinct
    from the generic RuntimeError `_call` raises for any rpc failure.
    Catchers recovering from vote conflicts must match this type, not
    bare RuntimeError, or they misclassify transport/server errors."""


class CoordinationClient:
    def __init__(self, host: str, port: int, info: Optional[Dict] = None,
                 heartbeat_interval: float = 2.0, auto_heartbeat: bool = True):
        self._addr = (host, port)
        self._lock = threading.Lock()
        self._conn = socket.create_connection(self._addr, timeout=30)
        resp = self._call({"op": "connect", "info": info or {}})
        self.rank = resp["rank"]
        self.world_size = resp.get("world_size")
        self.should_stop = False
        self._vote_round: Dict[str, int] = {}
        self._hb_interval = heartbeat_interval
        self._shutdown = False
        if auto_heartbeat:
            self._hb = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True)
            self._hb.start()

    # ------------------------------------------------------------------
    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            _send(self._conn, req)
            resp = _recv(self._conn)
        if resp is None:
            raise ConnectionError("coordination server closed the connection")
        if not resp.get("ok"):
            raise RuntimeError(f"rpc error: {resp.get('error')}")
        return resp

    def _heartbeat_loop(self):
        from hetu_tpu.obs.metrics import get_registry
        reg = get_registry()
        while not self._shutdown:
            try:
                t0 = time.perf_counter()
                resp = self._call({"op": "heartbeat", "rank": self.rank})
                # heartbeat RTT is the cheapest coordination-health probe
                # each worker has: a climbing p95 here means the control
                # plane (not the compute) is the straggler
                reg.observe("rpc.heartbeat_rtt_s",
                            time.perf_counter() - t0, rank=self.rank)
                if resp.get("stop"):
                    self.should_stop = True
            except (ConnectionError, OSError, RuntimeError):
                return
            time.sleep(self._hb_interval)

    # -- KV store (reference: KeyValueStoreClient) ----------------------
    def put(self, key: str, value: Any):
        self._call({"op": "put", "key": key, "value": value})

    def get(self, key: str, block: bool = False,
            timeout: float = 60.0) -> Any:
        deadline = time.time() + timeout
        while True:
            resp = self._call({"op": "get", "key": key})
            if resp["found"]:
                return resp["value"]
            if not block:
                raise KeyError(key)
            if time.time() > deadline:
                raise TimeoutError(f"kv key {key!r} not available")
            time.sleep(0.05)

    # -- barrier / consensus -------------------------------------------
    def barrier(self, name: str, count: int, timeout: float = 120.0):
        resp = self._call({"op": "barrier", "name": name, "rank": self.rank,
                           "count": count})
        if resp["released"]:
            return
        gen = resp["gen"]
        deadline = time.time() + timeout
        while time.time() < deadline:
            resp = self._call({"op": "barrier_poll", "name": name, "gen": gen})
            if resp["released"]:
                return
            time.sleep(0.02)
        raise TimeoutError(f"barrier {name!r} timed out")

    def consistent(self, name: str, value: Any, count: int,
                   timeout: float = 60.0) -> Any:
        """All `count` participants must agree on `value`
        (reference: elastic server Consistent :389).  Each call advances a
        per-name round counter so reusing a name never mixes rounds (all
        participants must call the same number of times — the natural
        once-per-decision usage)."""
        rnd = self._vote_round.get(name, 0)
        self._vote_round[name] = rnd + 1
        name = f"{name}#{rnd}"
        deadline = time.time() + timeout
        while True:
            resp = self._call({"op": "consistent", "name": name,
                               "rank": self.rank, "value": value,
                               "count": count})
            if resp["done"]:
                if not resp["agreed"]:
                    raise VoteDisagreement(
                        f"consistency vote {name!r} failed")
                return resp["value"]
            if time.time() > deadline:
                raise TimeoutError(f"consistent {name!r} timed out")
            time.sleep(0.05)

    # -- elastic membership --------------------------------------------
    def membership(self):
        return self._call({"op": "membership"})["alive"]

    def worker_stop(self, ranks=None):
        self._call({"op": "worker_stop", "ranks": ranks})

    def resume(self):
        """Acknowledge a stop signal after re-meshing (clears the server's
        stop flag for this rank).  Raises if this rank was declared dead —
        a zombie must reconnect for a fresh rank (split-brain guard)."""
        resp = self._call({"op": "resume", "rank": self.rank})
        if not resp.get("accepted", True):
            raise RuntimeError(
                "resume rejected: this rank was declared dead — reconnect "
                "with a new CoordinationClient for a fresh rank")
        self.should_stop = False

    def check_stop(self) -> bool:
        """Synchronous, race-free stop check (a fresh heartbeat op) — the
        cached should_stop can be momentarily stale around resume()."""
        resp = self._call({"op": "heartbeat", "rank": self.rank})
        stop = bool(resp.get("stop"))
        self.should_stop = stop
        return stop

    # -- parameter-server embedding tables (reference: v1 ps-lite worker
    # ops ParameterServerCommunicate.py pull/push; server side handlers in
    # rpc/server.py ps_init/ps_pull/ps_push) ---------------------------
    def ps_init(self, name: str, rows: int, dim: int, init: str = "zeros",
                scale: float = 0.02, seed: int = 0) -> Dict[str, Any]:
        """Create (idempotently) a server-resident embedding table."""
        return self._call({"op": "ps_init", "name": name, "rows": rows,
                           "dim": dim, "init": init, "scale": scale,
                           "seed": seed})

    def ps_pull(self, name: str, ids):
        """ids [n] -> float32 rows [n, dim] (the PS pull)."""
        import numpy as np

        from hetu_tpu.rpc.wire import decode_rows
        ids = np.asarray(ids, np.int64)
        resp = self._call({"op": "ps_pull", "name": name,
                           "ids": ids.tolist()})
        return decode_rows(resp["data"], len(ids), int(resp["dim"]))

    def ps_push(self, name: str, ids, rows, mode: str = "assign",
                lr: float = 0.01):
        """Write rows back: mode 'assign' (last write wins), 'add'
        (duplicates accumulate), or 'sgd' (row -= lr * grad, server-side
        sparse update — the reference PS optimizer path)."""
        import numpy as np

        from hetu_tpu.rpc.wire import encode_rows
        ids = np.asarray(ids, np.int64)
        self._call({"op": "ps_push", "name": name, "ids": ids.tolist(),
                    "data": encode_rows(rows), "mode": mode, "lr": lr})

    def exit(self):
        try:
            self._call({"op": "exit", "rank": self.rank})
        except (ConnectionError, OSError):
            pass
        self._shutdown = True
        self._conn.close()
