"""Context-parallel attention.

The reference's CP engine is ring attention with hetero rings
(reference: hetu/graph/ops/ParallelAttention.{h,cc} — AttnCommRing ring
KV-passing with online-softmax LSE merge, overlap, and STRIPE/SYM causal
balance).  Two TPU implementations live here:

1. `ring_attention` (shard_map + ppermute + per-block flash attention with
   LSE accumulation) — the faithful ring, comm overlapped by XLA's async
   collective-permute.  [M4]
2. `ring_attention_gspmd` — global-view fallback: computation is written
   globally and GSPMD materializes KV via all-gather over the cp axis.
   Correct for any layout; O(seq) memory for KV on each cp shard, so it is
   the fallback, not the destination.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from hetu_tpu import ops
from hetu_tpu.parallel.strategy import ParallelStrategy


def ring_attention_gspmd(q, k, v, *, strategy: ParallelStrategy,
                         segment_ids: Optional[jnp.ndarray] = None):
    """Global-view CP attention: inputs seq-sharded over cp; GSPMD inserts
    the all-gather of K/V. Output constrained back to cp-sharded."""
    out = ops.attention(q, k, v, causal=True, segment_ids=segment_ids)
    return strategy.constrain(out, strategy.act_attn())
