"""Ring-attention context parallelism.

Rebuild of the reference CP engine (reference: hetu/graph/ops/
ParallelAttention.{h,cc} — AttnCommRing ring KV-passing :945, online-softmax
LSE merge ExecCorr :606, comm/compute overlap, piggyback dKV on the backward
ring AttnBlock :172, causal balance via head+tail splits).

TPU mapping:
- the ring lives inside a shard_map over the `cp` mesh axis; KV blocks rotate
  with `lax.ppermute` (XLA compiles async collective-permutes that overlap
  the per-block flash kernel — the reference overlaps rounds by hand on a
  dedicated stream, ExecComm :849).
- per-block attention is the Pallas flash kernel with **global positions +
  segment ids** doing all masking, so arbitrary CP layouts (the head+tail
  symmetric split of hetu_tpu.data.bucket.cp_split_batch, packed varlen rows)
  need no special ring-step mask enumeration (the reference precomputes
  per-rank-pair AttnInfo mask kinds :212 — positions subsume that table).
- backward is a second ring: each rank computes its (dq; dk,dv-of-the-passing
  -block) with the flash-attn2 global-LSE trick, and dk/dv accumulate ON the
  rotating block until it returns home — exactly the reference's
  piggyback_grad.
- merge numerics follow ExecCorr: out = sum_i out_i * exp(lse_i - lse_tot),
  lse_tot = logsumexp_i lse_i, with empty blocks at lse = -inf.

`ring_attention` is the shard_map-internal function; `ring_attention_gspmd`
wraps it for use from global-view (jit) model code.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from hetu_tpu.ops.pallas.flash_attention import (NEG_INF, _bwd, _fwd,
                                                 causal_block_mask,
                                                 fit_block, full_block_mask)
from hetu_tpu.parallel.strategy import ParallelStrategy


def _merge(o_acc, lse_acc, o_i, lse_i):
    """Online-softmax merge of two partial attentions (ExecCorr :606).
    o: [b, h, s, d]; lse: [b, h, s]."""
    lse_new = jnp.logaddexp(lse_acc, lse_i)
    # exp(-inf - -inf) -> nan; empty rows keep weight 0
    w_acc = jnp.where(lse_acc == NEG_INF, 0.0, jnp.exp(lse_acc - lse_new))
    w_i = jnp.where(lse_i == NEG_INF, 0.0, jnp.exp(lse_i - lse_new))
    o_new = o_acc * w_acc[..., None] + o_i * w_i[..., None]
    return o_new, lse_new


def _rotate(xs, axis_name):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return [lax.ppermute(x, axis_name, perm) for x in xs]


def _pick_block(seq: int, want: int) -> int:
    """Largest block <= want that divides seq — the kernel's fit_block
    rule (one shared block-geometry policy; avoids the silent-tail-drop
    hazard of a non-dividing block)."""
    return fit_block(want, seq)


# ---------------------------------------------------------------------------
# Ring-step live-tile masks (AttnInfo analog).
#
# The reference precomputes per-(rank, origin) mask kinds — causal / full /
# EMPTY — so dead blocks never execute (ParallelAttention.cc:212
# GenerateAttnInfo). In one-program SPMD the per-rank mask choice becomes a
# lax.cond on the rank index: for each ring step i>0 there are at most two
# mask patterns across ranks ("origin before me" vs "origin after me",
# predicate r >= i), each branch running the Pallas kernel on a compressed
# tile grid. The in-kernel position masks stay on as the exact per-token
# guard; the static masks only bound which TILES get scheduled, so they must
# be (and are) conservative supersets.
#
# Per split pattern (data/bucket.py cp_split_batch):
#   normal — step 0 is the within-chunk causal triangle; steps from later
#            chunks are fully dead (skipped without running the kernel).
#            No lockstep wall-clock win (the ring waits on the busiest
#            rank), but dead steps stop burning MXU.
#   stripe — every (rank, origin) pair reduces to the SAME stripe-granular
#            triangle: uniform mask, no cond, ~2x tile reduction per step.
#   sym    — head+tail chunks: 2 of 4 quadrants are dead at every step
#            (which 2 depends on r vs origin -> the cond), so every rank
#            schedules exactly half the tiles every step: a true 2x.
# ---------------------------------------------------------------------------

# The process-wide declared CP data layout (the analog of the reference's
# HETU_PARALLEL_ATTN_SPLIT env flag, ParallelAttention.cc:196-204). Set by
# whoever reorders the data (the Trainer); consulted by ring_attention_gspmd
# when the strategy doesn't declare cp_split explicitly. None = undeclared =
# no static skipping.
_DECLARED_CP_SPLIT: Optional[str] = None


def declare_cp_split(split: Optional[str]):
    """Declare the CP split pattern of the batches this process feeds to
    ring attention (must match the actual seq reorder, or tiles holding live
    scores get skipped)."""
    global _DECLARED_CP_SPLIT
    if split not in (None, "normal", "stripe", "sym"):
        raise ValueError(f"split must be sym|stripe|normal|None, got {split!r}")
    _DECLARED_CP_SPLIT = split


@contextlib.contextmanager
def declared_cp_split(split: Optional[str]):
    """Scoped declare_cp_split — the Trainer wraps its (traced) step calls
    so its declaration cannot leak onto unrelated ring users in the same
    process (mask choice is captured at trace time)."""
    global _DECLARED_CP_SPLIT
    prev = _DECLARED_CP_SPLIT
    declare_cp_split(split)
    try:
        yield
    finally:
        _DECLARED_CP_SPLIT = prev


def _stripe_mask(s: int, bq: int, bk: int, g: int):
    """Union-over-ranks live tiles for the stripe split at granularity g:
    tile (qi, ki) can contain a visible pair for SOME (rank, origin) iff its
    max q stripe is >= its min k stripe."""
    return tuple(
        tuple((qi * bq + bq - 1) // g >= (ki * bk) // g
              for ki in range(s // bk))
        for qi in range(s // bq))


def _stripe_granularity(s_loc: int, cp: int):
    """cp_split_batch's stripe granularity, from the shared rule (which
    takes the GLOBAL seq = s_loc * cp)."""
    from hetu_tpu.data.bucket import stripe_granularity
    return stripe_granularity(s_loc * cp, cp)


def ring_step_masks(split, s_loc: int, bq: int, bk: int, cp: int,
                    causal: bool):
    """(mask_step0, mask_origin_before, mask_origin_after) static tile grids,
    or None to disable skipping. mask_origin_after=None = step fully dead."""
    if not causal or split is None or cp == 1:
        return None
    if s_loc % bq or s_loc % bk:
        return None
    tri = causal_block_mask(s_loc, s_loc, bq, bk, q_offset=0, k_offset=0)
    if split == "normal":
        return (tri, full_block_mask(s_loc, s_loc, bq, bk), None)
    if split == "stripe":
        g = _stripe_granularity(s_loc, cp)
        if g is None:
            return None
        m = _stripe_mask(s_loc, bq, bk, g)
        return (m, m, m)
    if split == "sym":
        half = s_loc // 2
        if s_loc % 2 or half % bq or half % bk:
            return None
        nk, hk = s_loc // bk, half // bk
        hq = half // bq
        tri_h = causal_block_mask(half, half, bq, bk, q_offset=0, k_offset=0)
        # step 0 (origin == me): [qh|kh] diag, [qh|kt] dead, [qt|kh] full,
        # [qt|kt] diag
        c = tuple(tri_h[qi] + (False,) * (nk - hk) for qi in range(hq)) + \
            tuple((True,) * hk + tri_h[qi] for qi in range(hq))
        # origin strictly before me: k head chunk fully visible, k tail dead
        a = tuple((True,) * hk + (False,) * (nk - hk)
                  for _ in range(s_loc // bq))
        # origin strictly after me: my head rows dead, my tail rows full
        b = tuple((False,) * nk for _ in range(hq)) + \
            tuple((True,) * nk for _ in range(hq))
        return (c, a, b)
    raise ValueError(f"split must be sym|stripe|normal|None, got {split!r}")


def _masked_fwd(i, masks, axis_name, q, k_i, v_i, q_pos, kpos_i, q_seg,
                kseg_i, *, scale, causal, block_q, block_k):
    """One ring step's forward with static tile skipping (cond on rank)."""
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    if masks is None:
        return _fwd(q, k_i, v_i, q_pos, kpos_i, q_seg, kseg_i, **kw)
    if i == 0:
        return _fwd(q, k_i, v_i, q_pos, kpos_i, q_seg, kseg_i,
                    block_mask=masks[0], **kw)
    if masks[1] == masks[2]:            # uniform across ranks (stripe)
        return _fwd(q, k_i, v_i, q_pos, kpos_i, q_seg, kseg_i,
                    block_mask=masks[1], **kw)
    b, h, sq, d = q.shape

    def before():
        return _fwd(q, k_i, v_i, q_pos, kpos_i, q_seg, kseg_i,
                    block_mask=masks[1], **kw)

    def after():
        if masks[2] is None:            # entirely dead step for these ranks
            return (jnp.zeros((b, h, sq, d), q.dtype),
                    jnp.full((b, h, sq), NEG_INF, jnp.float32))
        return _fwd(q, k_i, v_i, q_pos, kpos_i, q_seg, kseg_i,
                    block_mask=masks[2], **kw)

    r = lax.axis_index(axis_name)
    return lax.cond(r >= i, before, after)


def _masked_bwd(i, masks, axis_name, q, k_i, v_i, o, lse, do, q_pos, kpos_i,
                q_seg, kseg_i, *, scale, causal, block_q, block_k, delta):
    """One ring step's backward with static tile skipping (cond on rank)."""
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    if masks is None:
        return _bwd(q, k_i, v_i, o, lse, do, q_pos, kpos_i, q_seg, kseg_i,
                    delta=delta, **kw)
    if i == 0:
        return _bwd(q, k_i, v_i, o, lse, do, q_pos, kpos_i, q_seg, kseg_i,
                    delta=delta, block_mask=masks[0], **kw)
    if masks[1] == masks[2]:
        return _bwd(q, k_i, v_i, o, lse, do, q_pos, kpos_i, q_seg, kseg_i,
                    delta=delta, block_mask=masks[1], **kw)

    def before():
        return _bwd(q, k_i, v_i, o, lse, do, q_pos, kpos_i, q_seg, kseg_i,
                    delta=delta, block_mask=masks[1], **kw)

    def after():
        if masks[2] is None:
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.zeros(k_i.shape, jnp.float32),
                    jnp.zeros(v_i.shape, jnp.float32))
        return _bwd(q, k_i, v_i, o, lse, do, q_pos, kpos_i, q_seg, kseg_i,
                    delta=delta, block_mask=masks[2], **kw)

    r = lax.axis_index(axis_name)
    return lax.cond(r >= i, before, after)


# All arrays here are LOCAL shards: q/k/v [b, h, s_loc, d] (head-major, the
# kernel's native layout); positions/segments [b, s_loc].

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _ring(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name, scale, causal,
          block_sizes, masks):
    o, _ = _ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name,
                          scale, causal, block_sizes, masks)
    return o


def _ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name, scale,
                   causal, block_sizes, masks):
    b, h, sq, d = q.shape
    cp = lax.axis_size(axis_name)
    block_q = _pick_block(sq, block_sizes[0])
    block_k = _pick_block(k.shape[2], block_sizes[1])
    use_seg = q_seg is not None
    o = jnp.zeros((b, h, sq, d), jnp.float32)
    lse = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    k_i, v_i, kpos_i = k, v, kv_pos
    kseg_i = kv_seg
    for i in range(cp):
        o_i, lse_i = _masked_fwd(
            i, masks, axis_name, q, k_i, v_i, q_pos, kpos_i,
            q_seg if use_seg else None, kseg_i if use_seg else None,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        o, lse = _merge(o, lse, o_i.astype(jnp.float32), lse_i)
        if i != cp - 1:
            if use_seg:
                k_i, v_i, kpos_i, kseg_i = _rotate(
                    [k_i, v_i, kpos_i, kseg_i], axis_name)
            else:
                k_i, v_i, kpos_i = _rotate([k_i, v_i, kpos_i], axis_name)
    return o.astype(q.dtype), lse


def _ring_vjp_fwd(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name, scale,
                  causal, block_sizes, masks):
    o, lse = _ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name,
                            scale, causal, block_sizes, masks)
    return o, (q, k, v, o, lse, q_pos, kv_pos, q_seg, kv_seg)


def _ring_vjp_bwd(axis_name, scale, causal, block_sizes, masks, res, do):
    q, k, v, o, lse, q_pos, kv_pos, q_seg, kv_seg = res
    b, h, sq, d = q.shape
    cp = lax.axis_size(axis_name)
    block_q = _pick_block(sq, block_sizes[0])
    block_k = _pick_block(k.shape[2], block_sizes[1])
    use_seg = q_seg is not None
    # loop-invariant across ring steps: compute once
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dq = jnp.zeros(q.shape, jnp.float32)
    # the rotating block: (k, v, their metadata, their accumulating grads)
    k_i, v_i, kpos_i, kseg_i = k, v, kv_pos, kv_seg
    dk_i = jnp.zeros(k.shape, jnp.float32)
    dv_i = jnp.zeros(v.shape, jnp.float32)
    for i in range(cp):
        dq_c, dk_c, dv_c = _masked_bwd(
            i, masks, axis_name, q, k_i, v_i, o, lse, do, q_pos, kpos_i,
            q_seg if use_seg else None, kseg_i if use_seg else None,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            delta=delta)
        dq = dq + dq_c
        dk_i = dk_i + dk_c
        dv_i = dv_i + dv_c
        # rotate the block + piggybacked grads; after cp rotations total the
        # block (with its full dk/dv) is home again
        rot = [k_i, v_i, kpos_i, dk_i, dv_i] + ([kseg_i] if use_seg else [])
        rot = _rotate(rot, axis_name)
        if use_seg:
            k_i, v_i, kpos_i, dk_i, dv_i, kseg_i = rot
        else:
            k_i, v_i, kpos_i, dk_i, dv_i = rot
    return (dq.astype(q.dtype), dk_i.astype(k.dtype), dv_i.astype(v.dtype),
            None, None, None, None)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, *, axis_name: str = "cp",
                   q_positions=None, kv_positions=None,
                   segment_ids=None, kv_segment_ids=None,
                   causal: bool = True, softmax_scale: Optional[float] = None,
                   block_q: int = 512, block_k: int = 512,
                   split: Optional[str] = "auto"):
    """Ring attention over `axis_name`. shard_map-internal: all args are the
    LOCAL shard, layout [b, s_loc, heads_loc, d]; positions are GLOBAL token
    positions of the local tokens (per-segment positions for packed rows).

    `split` names the CP split pattern the data pipeline used
    (data/bucket.py cp_split_batch: normal|stripe|sym) and turns on static
    ring-step tile skipping (the AttnInfo analog — see ring_step_masks).
    "auto": "normal" when positions are generated here (contiguous chunks),
    no skipping when the caller supplied positions (their layout is unknown).
    The positions remain the exact mask; a wrong `split` can only be wrong
    by skipping live tiles, so pass None if unsure."""
    b, s, hh, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    cp_rank = lax.axis_index(axis_name)
    if split == "auto":
        split = "normal" if (q_positions is None and kv_positions is None) \
            else None
    if q_positions is None:
        # contiguous chunks: global offset = rank * s_loc
        base = cp_rank * s + jnp.arange(s, dtype=jnp.int32)
        q_positions = jnp.broadcast_to(base, (b, s))
    if kv_positions is None:
        kv_positions = q_positions
    if kv_segment_ids is None:
        kv_segment_ids = segment_ids
    cp = lax.axis_size(axis_name)
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    if split == "sym" and s % 2 == 0:
        # blocks must respect the head/tail chunk boundary
        bq = _pick_block(s // 2, block_q)
        bk = _pick_block(s // 2, block_k)
    masks = ring_step_masks(split, s, bq, bk, cp, causal)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _ring(qt, kt, vt, q_positions.astype(jnp.int32),
              kv_positions.astype(jnp.int32),
              segment_ids.astype(jnp.int32) if segment_ids is not None else None,
              kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None else None,
              axis_name, scale, causal, (bq, bk), masks)
    return o.transpose(0, 2, 1, 3)


def ring_attention_gspmd(q, k, v, *, strategy: ParallelStrategy,
                         segment_ids=None, position_ids=None,
                         causal: bool = True, mesh=None,
                         split: Optional[str] = "auto"):
    """Global-view wrapper: q/k/v [b, s, h, d] logically sharded
    (dp, cp, tp, -) — runs the ring inside a shard_map over the strategy mesh
    (reference: ParallelAttentionOpImpl::DoCompute dispatching AttnCommRing).

    position_ids: per-segment positions (packed rows) or None for contiguous;
    combined with segment_ids they encode exactly the causal+membership mask.

    split: CP split pattern for static ring-step tile skipping. "auto" =
    the HETU_TPU_CP_SPLIT flag when position_ids came from the data pipeline
    (whose cp_split_batch uses the same flag default — the single source of
    truth, like the reference's HETU_PARALLEL_ATTN_SPLIT), "normal" when
    positions are contiguous. Pass None for custom position layouts.
    """
    from hetu_tpu.core.mesh import current_mesh
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ring_attention_gspmd needs a mesh "
                         "(use hetu_tpu.use_mesh)")
    # inside a partial-manual region (e.g. the hetero-exec pipeline's
    # shard_map over pp) the inner shard_map must be built against the
    # tracing context's AbstractMesh — its axis_types record which axes are
    # already Manual; handing it the concrete Mesh is a mesh mismatch
    abstract = jax.sharding.get_abstract_mesh()
    if abstract is not None and any(
            "Manual" in str(t) for t in getattr(abstract, "axis_types", ())):
        mesh = abstract

    # layouts come from the strategy — one source of truth with the model
    qkv_spec = strategy.act_attn().partition_spec()
    tok_spec = strategy.act_tokens().partition_spec()
    use_seg = segment_ids is not None
    use_pos = position_ids is not None
    if split == "auto":
        # the split must DESCRIBE the caller's data layout (None = not
        # declared -> no static skipping); internally-generated positions
        # are contiguous chunks = "normal" by construction.  The SCOPED
        # declaration wins over strategy.cp_split: it is set by whoever
        # actually reordered the data (the Trainer, incl. its
        # incompatible-seq fallback to 'normal'), so it is the ground truth
        # about the layout even when the strategy asked for another split.
        split = ((_DECLARED_CP_SPLIT or strategy.cp_split) if use_pos
                 else "normal")

    tp_eff = strategy.cp_tp_eff

    def local(q, k, v, seg, pos):
        if tp_eff is not None:
            # hetero ring: no static step masks yet (uneven per-member
            # shapes make the tile grids per-origin; positions still mask)
            return hetero_ring_attention(
                q, k, v, tp_eff=tp_eff, axis_name="cp", tp_axis="tp",
                segment_ids=seg if use_seg else None,
                q_positions=pos if use_pos else None,
                kv_positions=pos if use_pos else None,
                causal=causal)
        return ring_attention(
            q, k, v, axis_name="cp",
            segment_ids=seg if use_seg else None,
            q_positions=pos if use_pos else None,
            kv_positions=pos if use_pos else None,
            causal=causal, split=split)

    if not use_seg:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)
    if not use_pos:
        position_ids = jnp.zeros(q.shape[:2], jnp.int32)

    fn = jax.shard_map(
        local, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, tok_spec, tok_spec),
        out_specs=qkv_spec, check_vma=False)
    return fn(q, k, v, segment_ids, position_ids)


def ring_attention_fallback(q, k, v, *, strategy: ParallelStrategy,
                            segment_ids=None, position_ids=None,
                            causal: bool = True):
    """Global-view CP attention: GSPMD materializes KV via all-gather over
    cp — O(seq) KV memory per shard.  An explicit alternative to the ring
    (the ring is the default everywhere, including inside the pipeline);
    useful when ring latency loses to one big all-gather (short sequences).

    position_ids (per-segment positions, e.g. from cp_split_batch's
    reordered layout) drive the causal mask exactly like the ring path —
    masking by array index would let reordered tokens see their future."""
    from hetu_tpu import ops
    import jax.numpy as jnp
    if position_ids is not None and causal:
        neg = jnp.finfo(jnp.float32).min
        bias = jnp.where(
            position_ids[:, :, None] >= position_ids[:, None, :], 0.0, neg)
        out = ops.attention(q, k, v, causal=False, bias=bias[:, None],
                            segment_ids=segment_ids)
    else:
        out = ops.attention(q, k, v, causal=causal, segment_ids=segment_ids)
    return strategy.constrain(out, strategy.act_attn())


# ---------------------------------------------------------------------------
# Hetero ring: ring members with UNEQUAL effective TP degrees
# (reference: ParallelAttention.cc:949-1050 — kv head-dim resplit between
# ring neighbors with different tp).
#
# TPU mapping: the mesh stays rectangular (cp, tp); a rank with effective
# degree e < tp physically holds its kv heads e-way sharded with tp/e-fold
# replication, BLOCK-MAJOR: device t of that rank stores sender-block
# t // (tp/e) (heads [blk*H/e, (blk+1)*H/e)).  Block-major assignment makes
# every device's stored block a SUPERSET of its own q-head block, so the
# reference's head-resplit all-to-all at each ring hop degenerates into a
# LOCAL head slice: for a block of origin rank o, device (r, t) computes
# with heads at sub-offset (t % (tp/e_o)) * H/tp of the traveling buffer.
# The price is the same one the reference pays: blocks of low-tp ranks are
# tp/e-fold larger on the wire (replication) — bandwidth, not correctness.
#
# Backward: dk/dv piggyback on the rotating (padded) buffer; each device
# column t only ever touches the head range of q-block t, so when a block
# arrives home it carries the COMPLETE grads for the owner's q-block heads
# at one known sub-offset — sliced back out to the uniform [H/tp] layout
# with no grouped collectives.
# ---------------------------------------------------------------------------

def _head_slice(x, off, n):
    """dynamic_slice of n heads at (traced) head-offset `off`; x [b,h,s,d]."""
    return lax.dynamic_slice_in_dim(x, off, n, axis=1)


def _head_add(buf, upd, off):
    cur = lax.dynamic_slice_in_dim(buf, off, upd.shape[1], axis=1)
    return lax.dynamic_update_slice_in_dim(buf, cur + upd, off, axis=1)


def _hetero_pad(full, h_loc, m_max):
    pad = h_loc * m_max
    return jnp.pad(full, ((0, 0), (0, pad), (0, 0), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _hetero_ring(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name, tp_axis,
                 scale, causal, block_sizes, tp_eff):
    o, _ = _hetero_ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg,
                                 axis_name, tp_axis, scale, causal,
                                 block_sizes, tp_eff)
    return o


def _hetero_geometry(axis_name, tp_axis, tp_eff):
    cp = lax.axis_size(axis_name)
    tp = lax.axis_size(tp_axis)
    if len(tp_eff) != cp:
        raise ValueError(f"tp_eff has {len(tp_eff)} entries for cp={cp}")
    for e in tp_eff:
        if tp % e:
            raise ValueError(f"tp_eff {e} must divide tp={tp}")
    m = tuple(tp // e for e in tp_eff)          # replication per rank
    return cp, tp, m, max(m)


def _hetero_blk_build(x, t, m_r, m_max, h_loc, tp_axis):
    if m_max == 1:      # fully homogeneous: the block IS the local shard
        return x
    full = lax.all_gather(x, tp_axis, axis=1, tiled=True)
    full = _hetero_pad(full, h_loc, m_max)
    return _head_slice(full, (t // m_r) * (h_loc * m_r), h_loc * m_max)


def _hetero_ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name,
                          tp_axis, scale, causal, block_sizes, tp_eff):
    b, h_loc, sq, d = q.shape
    h_kv = k.shape[1]        # GQA: kv heads per device can differ from q's
    cp, tp, m, m_max = _hetero_geometry(axis_name, tp_axis, tp_eff)
    r = lax.axis_index(axis_name)
    t = lax.axis_index(tp_axis)
    m_arr = jnp.asarray(m, jnp.int32)
    m_r = m_arr[r]
    block_q = _pick_block(sq, block_sizes[0])
    block_k = _pick_block(k.shape[2], block_sizes[1])
    use_seg = q_seg is not None

    k_blk = _hetero_blk_build(k, t, m_r, m_max, h_kv, tp_axis)
    v_blk = _hetero_blk_build(v, t, m_r, m_max, h_kv, tp_axis)
    kpos_i, kseg_i = kv_pos, kv_seg

    o = jnp.zeros((b, h_loc, sq, d), jnp.float32)
    lse = jnp.full((b, h_loc, sq), NEG_INF, jnp.float32)
    k_i, v_i = k_blk, v_blk
    for i in range(cp):
        origin = (r - i) % cp
        sub = (t % m_arr[origin]) * h_kv        # head-resplit = local slice
        k_c = _head_slice(k_i, sub, h_kv)
        v_c = _head_slice(v_i, sub, h_kv)
        o_i, lse_i = _fwd(q, k_c, v_c, q_pos, kpos_i,
                          q_seg if use_seg else None,
                          kseg_i if use_seg else None,
                          scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k)
        o, lse = _merge(o, lse, o_i.astype(jnp.float32), lse_i)
        if i != cp - 1:
            rot = [k_i, v_i, kpos_i] + ([kseg_i] if use_seg else [])
            rot = _rotate(rot, axis_name)
            if use_seg:
                k_i, v_i, kpos_i, kseg_i = rot
            else:
                k_i, v_i, kpos_i = rot
    return o.astype(q.dtype), lse


def _hetero_vjp_fwd(q, k, v, q_pos, kv_pos, q_seg, kv_seg, axis_name,
                    tp_axis, scale, causal, block_sizes, tp_eff):
    o, lse = _hetero_ring_fwd_impl(q, k, v, q_pos, kv_pos, q_seg, kv_seg,
                                   axis_name, tp_axis, scale, causal,
                                   block_sizes, tp_eff)
    return o, (q, k, v, o, lse, q_pos, kv_pos, q_seg, kv_seg)


def _hetero_vjp_bwd(axis_name, tp_axis, scale, causal, block_sizes, tp_eff,
                    res, do):
    q, k, v, o, lse, q_pos, kv_pos, q_seg, kv_seg = res
    b, h_loc, sq, d = q.shape
    h_kv = k.shape[1]        # GQA: kv heads per device can differ from q's
    cp, tp, m, m_max = _hetero_geometry(axis_name, tp_axis, tp_eff)
    r = lax.axis_index(axis_name)
    t = lax.axis_index(tp_axis)
    m_arr = jnp.asarray(m, jnp.int32)
    m_r = m_arr[r]
    block_q = _pick_block(sq, block_sizes[0])
    block_k = _pick_block(k.shape[2], block_sizes[1])
    use_seg = q_seg is not None

    k_blk = _hetero_blk_build(k, t, m_r, m_max, h_kv, tp_axis)
    v_blk = _hetero_blk_build(v, t, m_r, m_max, h_kv, tp_axis)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dq = jnp.zeros(q.shape, jnp.float32)
    dk_blk = jnp.zeros(k_blk.shape, jnp.float32)
    dv_blk = jnp.zeros(v_blk.shape, jnp.float32)
    k_i, v_i, kpos_i, kseg_i = k_blk, v_blk, kv_pos, kv_seg
    for i in range(cp):
        origin = (r - i) % cp
        sub = (t % m_arr[origin]) * h_kv
        k_c = _head_slice(k_i, sub, h_kv)
        v_c = _head_slice(v_i, sub, h_kv)
        dq_c, dk_c, dv_c = _bwd(
            q, k_c, v_c, o, lse, do, q_pos, kpos_i,
            q_seg if use_seg else None, kseg_i if use_seg else None,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            delta=delta)
        dq = dq + dq_c
        dk_blk = _head_add(dk_blk, dk_c, sub)
        dv_blk = _head_add(dv_blk, dv_c, sub)
        rot = [k_i, v_i, kpos_i, dk_blk, dv_blk] + \
            ([kseg_i] if use_seg else [])
        rot = _rotate(rot, axis_name)
        if use_seg:
            k_i, v_i, kpos_i, dk_blk, dv_blk, kseg_i = rot
        else:
            k_i, v_i, kpos_i, dk_blk, dv_blk = rot
    # home again: this device column only ever touched q-block t's head
    # range, whose complete grads sit at sub-offset (t % m_r) * h_loc
    sub_home = (t % m_r) * h_kv
    dk = _head_slice(dk_blk, sub_home, h_kv)
    dv = _head_slice(dv_blk, sub_home, h_kv)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None, None)


_hetero_ring.defvjp(_hetero_vjp_fwd, _hetero_vjp_bwd)


def hetero_ring_attention(q, k, v, *, tp_eff, axis_name: str = "cp",
                          tp_axis: str = "tp", q_positions=None,
                          kv_positions=None, segment_ids=None,
                          kv_segment_ids=None, causal: bool = True,
                          softmax_scale: Optional[float] = None,
                          block_q: int = 512, block_k: int = 512):
    """Ring attention where ring member r runs at effective TP degree
    tp_eff[r] (each a divisor of the mesh tp size).  shard_map-internal;
    local layout [b, s_loc, heads_loc, d] like ring_attention.  With all
    tp_eff == tp this is numerically the homogeneous ring."""
    b, s, hh, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    cp_rank = lax.axis_index(axis_name)
    if q_positions is None:
        base = cp_rank * s + jnp.arange(s, dtype=jnp.int32)
        q_positions = jnp.broadcast_to(base, (b, s))
    if kv_positions is None:
        kv_positions = q_positions
    if kv_segment_ids is None:
        kv_segment_ids = segment_ids
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _hetero_ring(
        qt, kt, vt, q_positions.astype(jnp.int32),
        kv_positions.astype(jnp.int32),
        segment_ids.astype(jnp.int32) if segment_ids is not None else None,
        kv_segment_ids.astype(jnp.int32) if kv_segment_ids is not None
        else None,
        axis_name, tp_axis, scale, causal, (block_q, block_k),
        tuple(int(e) for e in tp_eff))
    return o.transpose(0, 2, 1, 3)
