"""1F1B (PipeDream-flush) pipeline schedule.

Rebuild of the reference's default training schedule (reference: hetu/graph/
executable_graph.cc:836 GeneratePipedreamFlushSchedule — warmup forwards,
steady-state 1-forward-1-backward, cooldown backwards; GPipe at :803 is the
fallback this repo's `pipeline.py` implements via lax.scan + autodiff).

TPU-first realization — ONE compiled GSPMD program, manual per-stage VJP:

- Stages are vmapped over the `pp` mesh axis exactly like the GPipe path
  (`jax.vmap(..., spmd_axis_name="pp")`), so TP/SP/CP/DP constraints inside
  the stage body compose unchanged.
- Each scan round is one 1F1B steady-state slot: EVERY stage runs one
  forward micro AND one backward micro (fill/drain rounds run masked).
  Forward activations shift DOWN the stage dim, backward cotangents shift
  UP; under the pp sharding XLA lowers both to neighbor collective-permutes
  (the reference's kP2PStream sends/recvs).
- Backward is a per-round `jax.vjp` of the stage function seeded with the
  incoming cotangent — activations between the fwd and bwd visit of a micro
  are NOT kept: only the stage INPUT is saved, in a ring buffer of
  2*pp-1 slots, and the stage forward is recomputed inside the bwd-round
  vjp (the reference's 1F1B + recompute memory class).  Peak saved
  activations drop from O(n_micro) stage-inputs (GPipe scan autodiff) to
  O(pp), independent of n_micro.
- The token embedding folds into stage 0 and the LM head (+ loss) into the
  last stage — both executed by every stage slot under a `where`/mask so
  the vmapped program stays uniform; wrong-stage results carry exactly-zero
  cotangent seeds, so gradients are exact.  This keeps the pipeline's
  carried state at [pp, mb, s, h] activations + int token ids, never a
  whole-batch [B, s, h] buffer.

Schedule-length accounting: the scan runs R = n_micro + 2*(pp-1) lockstep
rounds, but each stage's DEAD schedule half (no forward work in cooldown,
no backward work in warmup) is an untaken `lax.cond` branch under the
shard_map round bodies, so the 2*(pp-1) fill/drain rounds cost one half
each and the makespan is the true PipeDream-flush
(n_micro + pp - 1) * (F + B) — matching the GPipe scan's tick count with
O(pp) instead of O(n_micro) activation memory.
skip_dead_halves="auto" enables this on meshes where pp is the only >1
axis; with sharded dp/tp/cp axes the vmap realization runs instead
(masked halves execute, (pp-1) extra full rounds) because XLA's SPMD
partitioner currently check-fails partitioning the tp-sharded embedding
gather inside a partial-manual region (spmd_partitioner_util.cc:495
ExpandDeviceGroupsWithIota).

Ring-buffer mechanics: the buffer is rolled by one slot each round (a
static concat — no scatter, partitioner-friendly) so the write always
lands at slot 0 and the read index is the per-stage CONSTANT
2*(pp-1-stage): stage s backs up the micro it forwarded 2*(pp-1-s) rounds
earlier, the PipeDream-flush in-flight depth.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


def schedule_validity(pp: int, n_micro: int):
    """The 1F1B lockstep-round structure as [R, pp] numpy masks.

    Round r's forward half runs micro r-s on stage s where fwd[r, s];
    its backward half runs micro r-2(pp-1)+s where bwd[r, s].  This is
    exactly what pipeline_train_1f1b scans over (fwd_valid/bwd_valid) —
    factored out so the Chrome-trace exporter (hetu_tpu.obs.trace) renders
    the schedule the engine actually executes.  Under skip_dead_halves the
    invalid halves cost ~nothing; they render as bubble lanes either way.
    """
    R = n_micro + 2 * (pp - 1)
    r_ = np.arange(R)[:, None]
    s_ = np.arange(pp)[None, :]
    fwd = (r_ - s_ >= 0) & (r_ - s_ < n_micro)
    m_b = r_ - 2 * (pp - 1) + s_
    bwd = (m_b >= 0) & (m_b < n_micro)
    return fwd, bwd


def _shardmap_round_bodies(stage_fn: Callable, mesh, pp_axis: str):
    """(vfwd, vbwd) with per-stage dead-half skipping.

    Manual over ONLY pp (tp/dp/cp stay auto, the body's own sharding
    constraints compose via GSPMD — same pattern as
    pipeline._shard_map_stage_body); the per-round validity scalar picks a
    real `lax.cond` branch per stage, so fill/drain rounds execute only
    their live half."""
    from hetu_tpu.core.vma import cast_varying
    Ppp = P(pp_axis)

    def _vary(v):
        return cast_varying(v, (pp_axis,))

    def _vary_tree(t):
        return jax.tree.map(_vary, t)

    def _first(t):
        return jax.tree.map(lambda a: a[0], t)

    def _stack1(t):
        return jax.tree.map(lambda a: a[None], t)

    def _varied_stage(fb1, fs1, fl1):
        """stage_fn with every output cast f32-where-scalar AND pp-varying,
        so vjp seeds (which arrive per-stage, vma {pp}) type-check even for
        outputs that trace invariant (e.g. a constant zero aux)."""
        def fn(sp_, ep_, x_):
            y, ce, aux = stage_fn(sp_, ep_, x_, fb1, fs1, fl1)
            return (_vary(y), _vary(jnp.asarray(ce, jnp.float32)),
                    _vary(jnp.asarray(aux, jnp.float32)))
        return fn

    def manual_fwd(sp, ep, x, fb, fs, fl, fv):
        sp1, x1 = _first(sp), x[0]
        fs1 = {k: v[0] for k, v in fs.items()}
        fl1 = {k: v[0] for k, v in fl.items()}
        # replicated args enter varying so the vjp/cotangent bookkeeping
        # stays per-stage (summed once after the schedule, not per round)
        ep1, fb1 = _vary_tree(ep), _vary_tree(fb)

        def live(_):
            return _varied_stage(fb1, fs1, fl1)(sp1, ep1, x1)

        def dead(_):
            return (_vary(jnp.zeros_like(x1)),
                    _vary(jnp.zeros((), jnp.float32)),
                    _vary(jnp.zeros((), jnp.float32)))

        y, ce, aux = lax.cond(fv[0] > 0, live, dead, 0)
        return y[None], jnp.reshape(ce, (1,)), jnp.reshape(aux, (1,))

    def manual_bwd(sp, ep, x, fb, fs, fl, dy, dce, daux, bv):
        sp1, x1 = _first(sp), x[0]
        fs1 = {k: v[0] for k, v in fs.items()}
        fl1 = {k: v[0] for k, v in fl.items()}
        ep1, fb1 = _vary_tree(ep), _vary_tree(fb)
        dy1, dce1, daux1 = dy[0], dce[0], daux[0]

        def live(_):
            _, vjp = jax.vjp(_varied_stage(fb1, fs1, fl1), sp1, ep1, x1)
            dsp, dep, dx = vjp((_vary(dy1), _vary(dce1), _vary(daux1)))
            return _vary_tree(dsp), _vary_tree(dep), _vary(dx)

        def dead(_):
            return (_vary_tree(jax.tree.map(jnp.zeros_like, sp1)),
                    _vary_tree(jax.tree.map(jnp.zeros_like, ep1)),
                    _vary(jnp.zeros_like(x1)))

        dsp, dep, dx = lax.cond(bv[0] > 0, live, dead, 0)
        return _stack1(dsp), _stack1(dep), dx[None]

    vfwd = jax.shard_map(
        manual_fwd, mesh=mesh,
        in_specs=(Ppp, P(), Ppp, P(), Ppp, Ppp, Ppp),
        out_specs=(Ppp, Ppp, Ppp),
        axis_names=frozenset({pp_axis}))
    vbwd = jax.shard_map(
        manual_bwd, mesh=mesh,
        in_specs=(Ppp, P(), Ppp, P(), Ppp, Ppp, Ppp, Ppp, Ppp, Ppp),
        out_specs=(Ppp, Ppp, Ppp),
        axis_names=frozenset({pp_axis}))
    return vfwd, vbwd


def build_dropout_ride(rng, n_micro: int, ids_shape, stage_layers):
    """(dropout_rng rider [B, s], stage_offset row [pp]) for pipeline
    dropout: per-micro uint32 seed bits ride the token stream (saved with
    the stage inputs, so the backward visit replays the SAME masks), and
    each stage's first global layer index seeds the per-layer fold_in.
    One implementation for every model family."""
    B, s = ids_shape
    mb = B // n_micro
    bits = jax.random.bits(rng, (n_micro,), dtype=jnp.uint32)
    rider = jnp.broadcast_to(jnp.repeat(bits, mb)[:, None], (B, s))
    offs = np.concatenate([[0], np.cumsum(stage_layers)[:-1]])
    return rider, jnp.asarray(offs, jnp.uint32)


def pipeline_train_1f1b(stage_fn: Callable, stage_params, edge_params,
                        ids, labels, ride_data: Dict, *,
                        n_micro: int, mesh, hidden_size: int,
                        compute_dtype, pp_axis: str = "pp",
                        aux_seed=1.0, state_spec: Optional[P] = None,
                        flags_extra: Optional[Dict] = None,
                        loss_scale=1.0, skip_dead_halves="auto",
                        custom_rounds=None):
    """Run the 1F1B schedule and return loss pieces + gradients.

    stage_fn(stage_params_slice, edge_params, x_in, feed_bcast, feed_stage,
             flags) -> (y [mb, s, h], ce_sum scalar, aux scalar)
      - must embed `feed_bcast["ids"]` when flags["is_first"] > 0 (ignoring
        x_in) and run the loss head on its output when flags["is_last"] > 0;
      - feed_bcast = {"ids", "labels"} (same value on every stage),
        feed_stage = per-stage token riders (positions/segments),
        flags = {"is_first", "is_last"} scalars (+ flags_extra rows).
    stage_params: pytree with leading [pp, ...] dims (see build_stage_stack).
    edge_params: embedding/head params (broadcast; grads accumulated with a
      leading pp dim and summed once after the schedule).
    ids/labels: [B, s]; ride_data: dict of [B, s] arrays that must travel
      with each micro (positions/segments).
    aux_seed: d(total_loss)/d(aux) — the token count when the model folds
      aux losses as `aux * count` (must be computed from labels up front).
    custom_rounds: optional (vfwd, vbwd) replacing the built-in round-body
      realizations (vmap / shard_map) — used by the hetero-TP pipeline
      (hetero_pp.hetero_tp_1f1b_rounds), whose stages need manual-(pp, tp)
      switch bodies.  Signatures:
        vfwd(sp, ep, x, feed_b, feed_s, flags, fv) -> (y, ce_row, aux_row)
        vbwd(sp, ep, x, feed_b, feed_s, flags, dy, dce, daux, bv)
          -> (d_stage, d_edge [pp-leading], dx)

    Returns (ce_sum, aux_sum, d_stage_params, d_edge_params).
    """
    pp = mesh.shape[pp_axis]
    B, s = ids.shape
    n = n_micro
    assert B % n == 0, (B, n)
    mb = B // n
    R = n + 2 * (pp - 1)
    n_slots = 2 * pp - 1
    spec = state_spec if state_spec is not None else P(pp_axis)
    buf_spec = P(*((spec[0], None) + tuple(spec[1:])))
    ride_spec = P(*((spec[0],) + tuple(spec[1:3])))

    # ---- per-round feed streams (static front-padding = schedule offsets) --
    def micros(a):
        return a.reshape((n, mb) + a.shape[1:])

    def stream(a, front: int):
        back = R - front - n
        z = [jnp.zeros((k,) + a.shape[1:], a.dtype) for k in (front, back)
             if k > 0]
        parts = ([z[0]] if front > 0 else []) + [a] + \
            ([z[-1]] if back > 0 else [])
        return jnp.concatenate(parts) if len(parts) > 1 else a

    ids_m = micros(ids)
    xs_ids_f = stream(ids_m, 0)                 # stage 0 fwd: micro r
    xs_ids_b = stream(ids_m, 2 * (pp - 1))      # stage 0 bwd: micro r-2(pp-1)
    xs_labels = stream(micros(labels), pp - 1)  # last stage f+b: micro r-(pp-1)
    xs_ride = {k: stream(micros(v), 0) for k, v in ride_data.items()}

    # ---- validity masks [R, pp] -------------------------------------------
    fwd_np, bwd_np = schedule_validity(pp, n)
    fwd_valid = jnp.asarray(fwd_np, jnp.float32)
    bwd_valid = jnp.asarray(bwd_np, jnp.float32)

    is_first = jnp.asarray(np.arange(pp) == 0, jnp.float32)
    is_last = jnp.asarray(np.arange(pp) == pp - 1, jnp.float32)
    flags = {"is_first": is_first, "is_last": is_last}
    flag_axes = {"is_first": 0, "is_last": 0}
    if flags_extra:
        flags.update(flags_extra)
        flag_axes.update({k: 0 for k in flags_extra})

    # ring read offset per stage: 2*(pp-1-s) rounds after its fwd visit
    read_oh = jax.nn.one_hot(2 * (pp - 1 - np.arange(pp)), n_slots,
                             dtype=jnp.float32)                  # [pp, slots]

    # ---- vmapped fwd / bwd round bodies -----------------------------------
    ride_axes = {k: 0 for k in ride_data}

    def tick_fwd(sp, ep, x_in, feed_b, feed_s, flg):
        return stage_fn(sp, ep, x_in, feed_b, feed_s, flg)

    def tick_bwd(sp, ep, x_in, feed_b, feed_s, flg, dy, dce, daux):
        fn = lambda sp_, ep_, x_: stage_fn(sp_, ep_, x_, feed_b, feed_s, flg)
        _, vjp = jax.vjp(fn, sp, ep, x_in)
        return vjp((dy, dce, daux))            # (d_stage, d_edge, dx)

    if custom_rounds is not None:
        skip_dead_halves = False   # masked execution; bodies are external
    elif skip_dead_halves == "auto":
        # the shard_map bodies trip an XLA SPMD-partitioner check-fail
        # (ExpandDeviceGroupsWithIota inside PartitionGather...) when a
        # SHARDED gather — the tp-vocab embedding — is partitioned inside
        # the partial-manual pp region, so auto-enable only on meshes
        # where pp is the sole >1 axis; multi-axis layouts keep the vmap
        # realization until the upstream partitioner handles it
        skip_dead_halves = all(int(mesh.shape[a]) == 1
                               for a in mesh.axis_names if a != pp_axis)
    if custom_rounds is not None:
        vfwd, vbwd = custom_rounds
    elif skip_dead_halves:
        # shard_map manual over ONLY pp: each stage's dead schedule half
        # (warmup rounds have no backward work, cooldown rounds no forward)
        # is an UNTAKEN lax.cond branch, so the 2(pp-1) fill/drain rounds
        # cost one half each and the makespan drops from
        # (n + 2(pp-1))(F+B) to the true PipeDream-flush
        # (n + pp - 1)(F + B) (reference: executable_graph.cc:836 —
        # warmup runs forwards only, cooldown backwards only).  Under the
        # vmap realization below both halves always execute masked.
        vfwd, vbwd = _shardmap_round_bodies(stage_fn, mesh, pp_axis)
    else:
        _vf = jax.vmap(tick_fwd,
                       in_axes=(0, None, 0, None, ride_axes, flag_axes),
                       spmd_axis_name=pp_axis)
        _vb = jax.vmap(tick_bwd,
                       in_axes=(0, None, 0, None, ride_axes, flag_axes,
                                0, 0, 0),
                       spmd_axis_name=pp_axis)
        vfwd = lambda sp, ep, x, fb, fs, fl, fv: _vf(sp, ep, x, fb, fs, fl)
        vbwd = (lambda sp, ep, x, fb, fs, fl, dy, dce, daux, bv:
                _vb(sp, ep, x, fb, fs, fl, dy, dce, daux))

    def shift_down(prev):
        out = jnp.concatenate([jnp.zeros_like(prev[:1]), prev[:-1]], axis=0)
        return lax.with_sharding_constraint(out, spec)

    def shift_down_ride(new, prev):
        out = jnp.concatenate([new[None], prev[:-1]], axis=0)
        return lax.with_sharding_constraint(out, ride_spec)

    def shift_up(prev):
        out = jnp.concatenate([prev[1:], jnp.zeros_like(prev[:1])], axis=0)
        return lax.with_sharding_constraint(out, spec)

    def push(buf, val, bspec=None):
        out = jnp.concatenate([val[:, None], buf[:, :-1]], axis=1)
        if bspec is not None:
            out = lax.with_sharding_constraint(out, bspec)
        return out

    read_slots = jnp.asarray(2 * (pp - 1 - np.arange(pp)), jnp.int32)

    def read(buf):
        if jnp.issubdtype(buf.dtype, jnp.integer):
            # the one-hot einsum promotes through f32, which rounds ints
            # >= 2^24 — fatal for the uint32 dropout seeds (a corrupted
            # seed makes the backward visit replay DIFFERENT masks);
            # integer buffers take an exact per-stage gather instead
            idx = read_slots.reshape((pp,) + (1,) * (buf.ndim - 1))
            return jnp.take_along_axis(buf, idx, axis=1)[:, 0]
        # constant one-hot gather: slot index is static per stage (exact
        # for floats: x*1 + 0 sums reproduce the stored values bit-exactly)
        return jnp.einsum("pk,pk...->p...", read_oh, buf).astype(buf.dtype)

    # ---- init carries ------------------------------------------------------
    def zero_state():
        z = jnp.zeros((pp, mb, s, hidden_size), compute_dtype)
        return lax.with_sharding_constraint(z, spec)

    buf_x0 = jnp.zeros((pp, n_slots, mb, s, hidden_size), compute_dtype)
    buf_x0 = lax.with_sharding_constraint(buf_x0, buf_spec)
    buf_ride0 = {k: jnp.zeros((pp, n_slots, mb, s), v.dtype)
                 for k, v in ride_data.items()}
    g_stage0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                            stage_params)
    g_edge0 = jax.tree.map(lambda a: jnp.zeros((pp,) + a.shape, jnp.float32),
                           edge_params)
    ride_state0 = {k: jnp.zeros((pp, mb, s), v.dtype)
                   for k, v in ride_data.items()}

    carry0 = (zero_state(), zero_state(), ride_state0, buf_x0, buf_ride0,
              g_stage0, g_edge0,
              jnp.zeros((pp,), jnp.float32), jnp.zeros((pp,), jnp.float32))
    aux_seed = jnp.asarray(aux_seed, jnp.float32)
    loss_scale = jnp.asarray(loss_scale, jnp.float32)

    def step(carry, xs):
        (prev_y, prev_dx, ride_st, buf_x, buf_ride,
         g_stage, g_edge, ce_acc, aux_acc) = carry
        ids_f, ids_b, lab, ride_new, fv, bv = xs

        # ---- forward half: stage s runs micro r-s -------------------------
        x_in = shift_down(prev_y)
        ride_cur = {k: shift_down_ride(ride_new[k], ride_st[k])
                    for k in ride_st}
        feed_b = {"ids": ids_f, "labels": lab}
        y, ce, aux = vfwd(stage_params, edge_params, x_in, feed_b,
                          ride_cur, flags, fv)
        y = lax.with_sharding_constraint(y, spec)
        ce_acc = ce_acc + ce * fv * is_last
        aux_acc = aux_acc + aux * fv

        # save this round's stage inputs for the backward visit
        buf_x = push(buf_x, x_in, buf_spec)
        buf_ride = {k: push(buf_ride[k], ride_cur[k]) for k in buf_ride}

        # ---- backward half: stage s runs micro r-2(pp-1)+s ----------------
        x_b = read(buf_x)
        ride_b = {k: read(buf_ride[k]) for k in buf_ride}
        dy = shift_up(prev_dx)
        # loss seed fires at the last stage; loss_scale multiplies BOTH seeds
        # (fp16 GradScaler: the scaled-loss cotangents flow through the f16
        # chain, the trainer unscales the returned grads — gradscaler.h:33)
        dce = bv * is_last * loss_scale
        daux = aux_seed * bv * loss_scale
        feed_bb = {"ids": ids_b, "labels": lab}
        dsp, dep, dx = vbwd(stage_params, edge_params, x_b, feed_bb,
                            ride_b, flags, dy, dce, daux, bv)
        dx = lax.with_sharding_constraint(dx.astype(compute_dtype), spec)
        g_stage = jax.tree.map(lambda g, d: g + d.astype(jnp.float32),
                               g_stage, dsp)
        g_edge = jax.tree.map(lambda g, d: g + d.astype(jnp.float32),
                              g_edge, dep)

        return (y, dx, ride_cur, buf_x, buf_ride, g_stage, g_edge,
                ce_acc, aux_acc), None

    (_, _, _, _, _, g_stage, g_edge, ce_acc, aux_acc), _ = lax.scan(
        step, carry0, (xs_ids_f, xs_ids_b, xs_labels, xs_ride,
                       fwd_valid, bwd_valid))

    d_edge = jax.tree.map(lambda a: jnp.sum(a, axis=0), g_edge)
    return jnp.sum(ce_acc), jnp.sum(aux_acc), g_stage, d_edge
