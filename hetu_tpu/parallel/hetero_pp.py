"""Per-stage sub-mesh heterogeneity inside ONE pipeline program.

The last structural hetero capability of the reference: a pipeline whose
stages run at UNEQUAL tensor-parallel degrees, expressed as
DistributedStatesUnions over unequal device groups and deduced per stage
(reference: hetu/graph/distributed_states.h:158-321 + define_and_run_graph.cc
:159 DeducePipeline). On a rectangular TPU mesh the per-stage degree becomes
an EFFECTIVE degree e_s (a divisor of the mesh tp extent) with
m_s = tp/e_s-fold block-major replication — the same trick the hetero CP
ring uses for unequal-TP ring members (parallel/ring_attention.py
_hetero_blk_build): device t of a stage computes head/channel block
t // m_s, so every needed weight block is a LOCAL slice of an all-gathered
buffer, and the row-parallel reduction is psum(partial)/m_s (each distinct
block contributes m_s identical copies).

Execution model: ONE jit program, `jax.shard_map` manual over (pp, tp) —
dp/cp stay automatic — with a `lax.switch` on the stage index choosing that
stage's static (e_s, layer_count) branch. Stage layer counts compose with
the degree heterogeneity (a Malleus plan sets both).

The price is the reference's own price for hetero TP: replicated compute on
low-degree stages (m_s-fold) + the per-layer weight all-gather. The planner
weighs that against what it buys (e.g. smaller TP collectives on the
latency-bound stages); this module only makes the layout EXECUTABLE in one
program.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel.pipeline import build_stage_stack


# check_vma=True is load-bearing here, not just a lint: with it off, JAX
# wraps every op in the manual body in unspecified-sharding constraints,
# and the one landing INSIDE a bf16 psum's reducer region becomes a `copy`
# HLO that crashes XLA:CPU's AllReducePromotion pass (CloneAllReduce ->
# CreateBinary(copy) check-fail) under the full dp+ZeRO+remat train step.
# The pvary/align/16-bit-widening idiom lives in core.vma (shared with the
# pipeline stage bodies).
from hetu_tpu.core.vma import align as _al
from hetu_tpu.core.vma import pvary_missing as _pv
from hetu_tpu.core.vma import vma_of as _vma_of
from hetu_tpu.core.vma import _widen_16bit


def _psum_wide(x, axis):
    """psum with f32 accumulation for 16-bit inputs.

    Two birds: wider reduction numerics, and a hard guarantee that no 16-bit
    all-reduce is emitted from this partial-manual region — XLA:CPU's
    AllReducePromotion pass check-fails (CreateBinary on a `copy` reducer
    root) on 16-bit all-reduces whose reducer carries the partial-manual
    sdy constraint (see _pv docstring; minimal repro: bf16 psum inside a
    shard_map with any auto axis)."""
    if _widen_16bit() and x.dtype in (jnp.bfloat16, jnp.float16):
        return lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return lax.psum(x, axis)


def _sp_compress_mode() -> str:
    """HETU_TPU_SP_COMPRESS routing for the SP edges below: int8/int4
    move the seq gathers/scatters as quantized payloads
    (comm/collectives.py custom-vjp collectives — backward transports
    quantize too); "none" keeps the exact lax calls byte-identical."""
    from hetu_tpu.comm.collectives import sp_mode
    return sp_mode()


def _reduce_out(x, axis, *, sp: bool, seq_dim: int = 1):
    """The row-parallel output reduction: all-reduce (plain TP) or
    reduce-scatter onto the seq dim (Megatron-SP) — same 16-bit widening
    guard as _psum_wide."""
    if not sp:
        return _psum_wide(x, axis)
    mode = _sp_compress_mode()
    if mode != "none":
        # the quantized scatter is f32-wire by construction (int payload,
        # f32 scales, f32 dequant) so the 16-bit widening guard below is
        # moot on this path
        from hetu_tpu.comm.collectives import reduce_scatter_q
        return reduce_scatter_q(
            x.astype(jnp.float32), axis, scatter_dimension=seq_dim,
            tiled=True, mode=mode).astype(x.dtype)
    if _widen_16bit() and x.dtype in (jnp.bfloat16, jnp.float16):
        return lax.psum_scatter(
            x.astype(jnp.float32), axis, scatter_dimension=seq_dim,
            tiled=True).astype(x.dtype)
    return lax.psum_scatter(x, axis, scatter_dimension=seq_dim, tiled=True)


def _gather_seq(x, axis, *, sp: bool, seq_dim: int = 1):
    """SP regions enter the projections through a seq all-gather.

    On the cpu backend 16-bit inputs gather in f32: the TRANSPOSE of a
    tiled all-gather is a psum_scatter of the cotangent, and a 16-bit
    reduce-scatter from a partial-manual region hits the same XLA:CPU
    AllReducePromotion check-fail as 16-bit psums (see _psum_wide) —
    widening around the gather keeps that transpose f32."""
    if not sp:
        return x
    mode = _sp_compress_mode()
    if mode != "none":
        from hetu_tpu.comm.collectives import all_gather_q
        return all_gather_q(
            x.astype(jnp.float32), axis, axis=seq_dim, tiled=True,
            mode=mode).astype(x.dtype)
    if _widen_16bit() and x.dtype in (jnp.bfloat16, jnp.float16):
        return lax.all_gather(x.astype(jnp.float32), axis, axis=seq_dim,
                              tiled=True).astype(x.dtype)
    return lax.all_gather(x, axis, axis=seq_dim, tiled=True)


def _blk(w, dim: int, t, e: int, m: int, tp_axis: str):
    """Block-major effective-degree weight slice: the [dim]-sharded weight's
    block t//m of e, as a LOCAL slice of the tp all-gather (m==1: the local
    shard IS the block)."""
    if m == 1:
        return w
    full = lax.all_gather(w, tp_axis, axis=dim, tiled=True)
    size_e = full.shape[dim] // e
    idx = _pv((t // m) * size_e, jax.typeof(full).vma)
    return lax.dynamic_slice_in_dim(full, idx, size_e, axis=dim)


def llama_block_maker(cfg, cos, sin, *, tp: int, tp_axis: str = "tp",
                      sequence_parallel: bool = False):
    """block_maker(e, m) -> block_fn(layer_params, x, pos, seg) -> (x, aux)
    running the LLaMA block manual-over-tp at effective degree e.

    Mirrors models/llama/model.py LlamaBlock exactly (pre-norm, fused qkv
    [h, n_kv, group+2, hd], RoPE, flash attention, row o_proj, SwiGLU MLP)
    — golden-parity tested against it. Dense only (no MoE/dropout here).
    sequence_parallel: between-block activations arrive seq-sharded over
    the FULL tp axis (Megatron-SP in manual form — all-gather into the
    projections, reduce-scatter out of the row-parallel matmuls; weight
    blocks still replicate m-fold at effective degree e)."""
    from hetu_tpu import ops
    from jax.ad_checkpoint import checkpoint_name

    hd = cfg.head_dim
    n_q, n_kv = cfg.num_attention_heads, cfg.num_key_value_heads
    group = n_q // n_kv
    sp = sequence_parallel

    def maker(e: int, m: int) -> Callable:
        if n_kv % e:
            raise ValueError(f"num_key_value_heads={n_kv} must divide by "
                             f"effective tp degree {e}")
        kv_e = n_kv // e

        def block(lp, x, pos, seg, rng=None):
            t = lax.axis_index(tp_axis)
            nw, nw2 = _al(lp["input_norm"]["weight"], lp["post_norm"]["weight"],
                          x)[:2]
            xin = _gather_seq(ops.rms_norm(x, nw, cfg.rms_norm_eps),
                              tp_axis, sp=sp)
            b, s, h = xin.shape
            wqkv = _blk(lp["attn"]["wqkv"], 1, t, e, m, tp_axis)
            xin_t, wqkv = _al(xin, wqkv)
            qkv = jnp.einsum("bsh,hkgd->bskgd", xin_t,
                             wqkv.astype(x.dtype))
            q = qkv[..., :group, :].reshape(b, s, kv_e * group, hd)
            k = qkv[..., group, :]
            v = qkv[..., group + 1, :]
            q, k, cos_a, sin_a, pos_a = _al(q, k, cos, sin,
                                            jnp.zeros((), jnp.int32)
                                            if pos is None else pos)
            pos_a = None if pos is None else pos_a
            q = ops.apply_rotary(q, cos_a, sin_a, pos_a)
            k = ops.apply_rotary(k, cos_a, sin_a, pos_a)
            if seg is not None:
                q, k, v, seg = _al(q, k, v, seg)
            else:
                q, k, v = _al(q, k, v)
            attn = ops.flash_attention(
                q, k, v, causal=True, segment_ids=seg,
                use_pallas=None if cfg.use_flash_attention else False)
            attn = checkpoint_name(attn, "attn_out")
            wo = _blk(lp["attn"]["o_proj"]["weight"], 0, t, e, m, tp_axis)
            attn2, wo = _al(attn.reshape(b, s, kv_e * group * hd), wo)
            if rng is not None and sp:
                # SP: each tp rank holds a DISTINCT seq chunk — fold the
                # rank in so masks are independent per token (non-SP keeps
                # the shared key: replicated activations need identical
                # masks across the m-fold block replicas)
                rng = jax.random.fold_in(rng, t)
            h1 = attn2 @ wo.astype(x.dtype)
            h1, x = _al(_reduce_out(h1, tp_axis, sp=sp) / m, x)
            if rng is not None and cfg.hidden_dropout > 0.0:
                # same (micro, layer)-keyed folds as LlamaBlock.forward
                h1 = ops.dropout(h1, cfg.hidden_dropout,
                                 jax.random.fold_in(rng, 2), False)
            x = x + h1
            xin2 = _gather_seq(
                ops.rms_norm(x, _al(nw2, x)[0], cfg.rms_norm_eps),
                tp_axis, sp=sp)
            wgu = _blk(lp["mlp"]["w_gate_up"], 2, t, e, m, tp_axis)
            xin2_t, wgu = _al(xin2, wgu)
            gu = jnp.einsum("bsh,hci->bsci", xin2_t, wgu.astype(x.dtype))
            hidden = ops.swiglu(gu[:, :, 0, :], gu[:, :, 1, :])
            wd = _blk(lp["mlp"]["down_proj"]["weight"], 0, t, e, m, tp_axis)
            hidden, wd = _al(hidden, wd)
            h2 = hidden @ wd.astype(x.dtype)
            h2, x = _al(_reduce_out(h2, tp_axis, sp=sp) / m, x)
            if rng is not None and cfg.hidden_dropout > 0.0:
                h2 = ops.dropout(h2, cfg.hidden_dropout,
                                 jax.random.fold_in(rng, 3), False)
            return x + h2, jnp.zeros((), jnp.float32)

        return block

    return maker


def gpt_block_maker(cfg, *, tp: int, tp_axis: str = "tp",
                    sequence_parallel: bool = False):
    """block_maker(e, m) -> block_fn(layer_params, x, pos, seg) -> (x, 0)
    running the GPT block manual-over-tp at effective degree e.

    Mirrors models/gpt/model.py GPTBlock exactly (pre-LN, fused qkv
    [h, n, 3, hd] + bias, flash attention, row o_proj + bias, GELU MLP
    with biases) — golden-parity tested against it.  Dense, no dropout
    (the hetero envelope ParallelStrategy.validate enforces).
    sequence_parallel: see llama_block_maker."""
    from hetu_tpu import ops
    from jax.ad_checkpoint import checkpoint_name

    hd = cfg.head_dim
    n_heads = cfg.num_attention_heads
    sp = sequence_parallel

    def maker(e: int, m: int) -> Callable:
        if n_heads % e:
            raise ValueError(f"num_attention_heads={n_heads} must divide "
                             f"by effective tp degree {e}")
        n_e = n_heads // e

        def block(lp, x, pos, seg, rng=None):
            t = lax.axis_index(tp_axis)
            ln1w, ln1b, ln2w, ln2b = _al(
                lp["ln1"]["weight"], lp["ln1"]["bias"],
                lp["ln2"]["weight"], lp["ln2"]["bias"], x)[:4]
            xin = _gather_seq(
                ops.layer_norm(x, ln1w, ln1b, cfg.layer_norm_eps),
                tp_axis, sp=sp)
            b, s, h = xin.shape
            wqkv = _blk(lp["attn"]["wqkv"], 1, t, e, m, tp_axis)
            bqkv = _blk(lp["attn"]["bqkv"], 0, t, e, m, tp_axis)
            xin_t, wqkv, bqkv = _al(xin, wqkv, bqkv)
            qkv = jnp.einsum("bsh,hngd->bsngd", xin_t,
                             wqkv.astype(x.dtype)) + bqkv.astype(x.dtype)
            q = qkv[..., 0, :]
            k = qkv[..., 1, :]
            v = qkv[..., 2, :]
            if seg is not None:
                q, k, v, seg = _al(q, k, v, seg)
            else:
                q, k, v = _al(q, k, v)
            attn = ops.flash_attention(
                q, k, v, causal=True, segment_ids=seg,
                use_pallas=None if cfg.use_flash_attention else False)
            attn = checkpoint_name(attn, "attn_out")
            wo = _blk(lp["attn"]["o_proj"]["weight"], 0, t, e, m, tp_axis)
            attn2, wo = _al(attn.reshape(b, s, n_e * hd), wo)
            h1 = attn2 @ wo.astype(x.dtype)
            # row-parallel bias adds ONCE, after the reduction
            if rng is not None and sp:
                # per-rank fold under SP (see llama counterpart)
                rng = jax.random.fold_in(rng, t)
            h1, ob, x = _al(_reduce_out(h1, tp_axis, sp=sp) / m,
                            lp["attn"]["o_proj"]["bias"], x)
            h1 = h1 + ob.astype(x.dtype)
            if rng is not None and cfg.hidden_dropout > 0.0:
                # same folds as GPTBlock.forward (bias included, like the
                # homogeneous RowParallelLinear output)
                h1 = ops.dropout(h1, cfg.hidden_dropout,
                                 jax.random.fold_in(rng, 2), False)
            x = x + h1
            xin2 = _gather_seq(
                ops.layer_norm(x, ln2w, ln2b, cfg.layer_norm_eps),
                tp_axis, sp=sp)
            w_up = _blk(lp["mlp"]["w_up"], 1, t, e, m, tp_axis)
            b_up = _blk(lp["mlp"]["b_up"], 0, t, e, m, tp_axis)
            xin2_t, w_up, b_up = _al(xin2, w_up, b_up)
            y = xin2_t @ w_up.astype(x.dtype) + b_up.astype(x.dtype)
            y = ops.gelu(y)
            wd = _blk(lp["mlp"]["down"]["weight"], 0, t, e, m, tp_axis)
            y, wd = _al(y, wd)
            h2 = y @ wd.astype(x.dtype)
            h2, db, x = _al(_reduce_out(h2, tp_axis, sp=sp) / m,
                            lp["mlp"]["down"]["bias"], x)
            h2 = h2 + db.astype(x.dtype)
            if rng is not None and cfg.hidden_dropout > 0.0:
                h2 = ops.dropout(h2, cfg.hidden_dropout,
                                 jax.random.fold_in(rng, 3), False)
            x = x + h2
            return x, jnp.zeros((), jnp.float32)

        return block

    return maker


def _manual_specs(param_spec_tree, keep=("pp", "tp"), lead=("pp", None)):
    """Model ParamSpec tree (one layer) -> PartitionSpecs naming ONLY the
    manual axes (auto axes like dp must stay unmentioned), with the stacked
    (pp, layer) lead dims prepended."""
    from hetu_tpu.nn.module import ParamSpec

    def one(psp):
        ds = getattr(psp, "ds", None)
        if ds is None:
            return P(*lead)
        ent = []
        for axes in ds.spec:
            ax = [a for a in (axes or ()) if a in keep]
            ent.append(ax[0] if len(ax) == 1 else (tuple(ax) or None))
        return P(*(lead + tuple(ent)))
    return jax.tree.map(one, param_spec_tree,
                        is_leaf=lambda x: isinstance(x, ParamSpec))


def _hetero_switch_stack(block_maker: Callable, param_ds_tree, mesh, *,
                         pp: int, tp: int, tp_eff: Sequence[int],
                         stage_layers: Sequence[int], remat: bool,
                         remat_policy: str, token_keys=(),
                         pp_axis: str = "pp", tp_axis: str = "tp",
                         sequence_parallel: bool = False):
    """shard_map'ed (stage_params, x_buf [pp, mb, s, h], tok_buf) ->
    (y_buf, aux_row [pp]): manual over (pp, tp) with a `lax.switch` on the
    stage index choosing that stage's static (tp_eff, layer-count) branch.
    ONE builder shared by the GPipe hetero pipeline and the 1F1B hetero
    round bodies.  Under SP the x buffer enters/leaves seq-sharded over
    the tp axis (the block maker must be built sequence_parallel too).

    Dropout: when a "dropout_rng" rider is present (the build_dropout_ride
    scheme — per-micro uint32 bits on the token stream), each layer's key
    is fold_in(key(bits), global_layer_id) with the stage's STATIC layer
    offset, and the block is called with rng=key.  The rider is replicated
    over tp, so tp replicas draw identical masks (consistency under
    block-major replication); the 1F1B backward visit replays exactly
    because the saved rider re-derives the same keys inside the vjp."""
    import numpy as np

    offs = np.concatenate([[0], np.cumsum(list(stage_layers))[:-1]])
    has_rng = "dropout_rng" in token_keys

    def stage_branch(stage_i: int):
        e = tp_eff[stage_i]
        m = tp // e
        k_s = stage_layers[stage_i]
        block = block_maker(e, m)
        off = int(offs[stage_i])

        def run(sp1, x_mb, tok1):
            micro_key = (jax.random.key(tok1["dropout_rng"][0, 0])
                         if has_rng else None)

            def body(carry, xs):
                lp, gid = xs
                x_c, aux_c = carry
                kw = {}
                if has_rng:
                    kw["rng"] = jax.random.fold_in(micro_key, gid)
                out, aux = block(lp, x_c, tok1.get("position_ids"),
                                 tok1.get("segment_ids"), **kw)
                return (out, aux_c + aux), None

            fn = body
            if remat:
                from hetu_tpu.nn.remat import remat_policy as _policy
                fn = jax.checkpoint(body, policy=_policy(remat_policy))
            sliced = jax.tree.map(lambda a: a[:k_s], sp1)
            gids = jnp.arange(off, off + k_s, dtype=jnp.uint32)
            (y, aux), _ = lax.scan(
                fn, (x_mb, jnp.zeros((), jnp.float32)), (sliced, gids))
            return y, aux

        return run

    pspecs = _manual_specs(param_ds_tree, keep=(pp_axis, tp_axis),
                           lead=(pp_axis, None))

    def manual(sp, x_b, tok_b):
        # local views: stage dim extent 1, weights local tp shards
        sp1 = jax.tree.map(lambda a: a[0], sp)
        tok1 = {k: v[0] for k, v in tok_b.items()}
        p = lax.axis_index(pp_axis)
        branches = [stage_branch(i) for i in range(pp)]
        y, aux = lax.switch(p, branches, sp1, x_b[0], tok1)
        return y[None], jnp.reshape(aux, (1,)).astype(jnp.float32)

    Ppp = P(pp_axis)
    # [pp, mb, s, h] buffers: seq dim manual-sharded over tp under SP
    Px = P(pp_axis, None, tp_axis) if sequence_parallel else Ppp
    return jax.shard_map(
        manual, mesh=mesh,
        in_specs=(pspecs, Px, {k: Ppp for k in token_keys}),
        out_specs=(Px, Ppp),
        axis_names=frozenset({pp_axis, tp_axis}), check_vma=True)


def hetero_tp_1f1b_rounds(block_maker: Callable, param_ds_tree, embed_fn,
                          head_fn, *, mesh, pp: int, tp: int,
                          tp_eff: Sequence[int], stage_layers: Sequence[int],
                          remat: bool, remat_policy: str, compute_dtype,
                          token_keys=(), pp_axis: str = "pp",
                          tp_axis: str = "tp",
                          sequence_parallel: bool = False):
    """(vfwd, vbwd) round bodies for `pipeline_train_1f1b(custom_rounds=...)`
    running each stage at effective TP degree tp_eff[s].

    Design: the decoder stack runs under the manual-(pp, tp) switch body
    (_hetero_switch_stack), while the EDGES — the tp-sharded vocab embedding
    and the loss head — run in auto (GSPMD) mode outside the manual region,
    composed per round:

        y = switch_stack(where(stage==0, embed(ids), x_in))
        ce = head(y[last], labels)

    That keeps the known partitioner crash (a sharded gather partitioned
    inside a partial-manual region, see pipeline_1f1b.py skip_dead_halves)
    out of the program: the embedding gather is a plain auto-mode op, and
    the manual region contains only the block math the GPipe hetero path
    already differentiates (topology-8 dryrun).  The backward round is a
    `jax.vjp` of the composed round function, seeded with the engine's
    per-stage cotangent rows — exact 1F1B semantics because the round
    function is row-wise independent across stages.

    embed_fn(edge_params, feed_b, feed_s) -> [mb, s, h] hidden (auto mode;
      feed_b carries "ids"/"labels", feed_s the token riders — GPT's wpe
      needs the positions);
    head_fn(edge_params, y [mb, s, h], labels) -> summed CE scalar.
    """
    import numpy as np

    vstack = _hetero_switch_stack(
        block_maker, param_ds_tree, mesh, pp=pp, tp=tp, tp_eff=tp_eff,
        stage_layers=stage_layers, remat=remat, remat_policy=remat_policy,
        token_keys=token_keys, pp_axis=pp_axis, tp_axis=tp_axis,
        sequence_parallel=sequence_parallel)

    first = jnp.asarray(np.arange(pp) == 0)
    last_idx = pp - 1

    def round_fn(sp, ep, x_in, feed_b, feed_s):
        emb = embed_fn(ep, feed_b, feed_s).astype(compute_dtype)
        x0 = jnp.where(first[:, None, None, None], emb[None], x_in)
        y, aux_row = vstack(sp, x0, feed_s)
        ce = head_fn(ep, y[last_idx], feed_b["labels"])
        ce_row = jnp.zeros((pp,), jnp.float32).at[last_idx].set(
            jnp.asarray(ce, jnp.float32))
        return y, ce_row, aux_row

    def vfwd(sp, ep, x, fb, fs, fl, fv):
        return round_fn(sp, ep, x, fb, fs)

    def vbwd(sp, ep, x, fb, fs, fl, dy, dce, daux, bv):
        fn = lambda sp_, ep_, x_: round_fn(sp_, ep_, x_, fb, fs)
        _, vjp = jax.vjp(fn, sp, ep, x)
        dsp, dep, dx = vjp((dy, dce, daux))
        # the engine accumulates edge grads with a leading pp dim (one row
        # per stage); the composed round used the edges once — record the
        # whole contribution on row 0
        dep = jax.tree.map(
            lambda g: jnp.zeros((pp,) + g.shape, jnp.float32)
            .at[0].set(g.astype(jnp.float32)), dep)
        return dsp, dep, dx

    return vfwd, vbwd


def staged_stack_forward_hetero_tp(
        block_maker: Callable, param_ds_tree, stack_params, x, *,
        num_layers: int, pp: int, tp: int, tp_eff: Sequence[int], mesh,
        position_ids=None, segment_ids=None, stage_layers=None,
        n_micro: Optional[int] = None, remat: bool = True,
        remat_policy: str = "nothing", state_spec=None,
        pp_axis: str = "pp", tp_axis: str = "tp",
        sequence_parallel: bool = False, rng=None):
    """GPipe pipeline where stage s runs at effective TP degree tp_eff[s].

    block_maker(e, m) -> block_fn(local_layer_params, x_mb, pos, seg[, rng]);
    param_ds_tree: the model's per-layer DS tree (for the manual in_specs).
    rng enables hidden dropout inside the hetero pipeline (the
    build_dropout_ride per-micro-bits scheme; see _hetero_switch_stack).
    Everything else mirrors pipeline.staged_stack_forward."""
    tp_eff = tuple(int(e) for e in tp_eff)
    if len(tp_eff) != pp:
        raise ValueError(f"tp_eff has {len(tp_eff)} entries for pp={pp}")
    for e in tp_eff:
        if e < 1 or tp % e:
            raise ValueError(f"tp_eff {e} must divide mesh tp={tp}")

    B, s, h = x.shape
    if n_micro is None:
        n_micro = pp
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    T = n_micro + pp - 1
    pad = pp - 1
    spec = state_spec if state_spec is not None else P(pp_axis)
    tok_spec = P(*((spec[0],) + tuple(spec[1:3])))

    stage_params, _, stage_layers = build_stage_stack(
        stack_params, num_layers, pp, stage_layers)

    token_data = {}
    if position_ids is not None:
        token_data["position_ids"] = position_ids
    if segment_ids is not None:
        token_data["segment_ids"] = segment_ids
    if rng is not None:
        from hetu_tpu.parallel.pipeline_1f1b import build_dropout_ride
        token_data["dropout_rng"], _ = build_dropout_ride(
            rng, n_micro, (B, s), stage_layers)

    xm = x.reshape(n_micro, mb, s, h)
    tok = {k: v.reshape(n_micro, mb, s) for k, v in token_data.items()}

    vbody = _hetero_switch_stack(
        block_maker, param_ds_tree, mesh, pp=pp, tp=tp, tp_eff=tp_eff,
        stage_layers=stage_layers, remat=remat, remat_policy=remat_policy,
        token_keys=tuple(token_data), pp_axis=pp_axis, tp_axis=tp_axis,
        sequence_parallel=sequence_parallel)

    def shift_in(new, state, sp=None):
        out = jnp.concatenate([new[None], state[:-1]], axis=0)
        return lax.with_sharding_constraint(
            out, sp if sp is not None else spec)

    if pad:
        xs_x = jnp.concatenate(
            [xm, jnp.zeros((pad,) + xm.shape[1:], xm.dtype)])
        xs_tok = {k: jnp.concatenate(
            [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in tok.items()}
    else:
        xs_x, xs_tok = xm, tok

    init_x = lax.with_sharding_constraint(
        jnp.zeros((pp, mb, s, h), x.dtype), spec)
    init_tok = {k: jnp.zeros((pp, mb, s), v.dtype) for k, v in tok.items()}

    ticks = jnp.arange(T)
    stages = jnp.arange(pp)
    micro_idx = ticks[:, None] - stages[None, :]
    aux_mask = ((micro_idx >= 0) & (micro_idx < n_micro)).astype(jnp.float32)

    def step(carry, xs_t):
        state_x, state_tok = carry
        in_x, in_tok, mask_t = xs_t
        cur_x = shift_in(in_x, state_x)
        cur_tok = {k: shift_in(in_tok[k], state_tok[k], tok_spec)
                   for k in state_tok}
        out_x, aux = vbody(stage_params, cur_x, cur_tok)
        aux = jnp.sum(aux * mask_t)
        out_x = lax.with_sharding_constraint(out_x, spec)
        return (out_x, cur_tok), (out_x[-1], aux)

    _, (ys, auxs) = lax.scan(step, (init_x, init_tok),
                             (xs_x, xs_tok, aux_mask))
    outs = ys[pad:] if pad else ys
    return outs.reshape(B, s, h), jnp.sum(auxs)