"""Parallel strategy: the per-run binding of model dims to mesh axes.

The reference expresses a strategy as a ds-parallel JSON (per-layer-block
device groups + split/dup/zero maps, reference: python/hetu/utils/parallel/
generate_ds.py:253) consumed by parallel nn modules.  Here a strategy is a
small object that (a) names the mesh shape, (b) hands out DistributedStates
for every parameter/activation role, and (c) knows the SP/ZeRO switches.
Models ask the strategy for layouts instead of hard-coding them, so the same
model code runs dense single-chip, TP, TP+SP, DP×TP×PP×CP, etc.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from hetu_tpu.core.mesh import MeshConfig, create_mesh
from hetu_tpu.dstates import DistributedStates as DS


@dataclasses.dataclass(frozen=True)
class ParallelStrategy:
    """Strategy = mesh shape + behavior flags.

    sequence_parallel: Megatron-SP — between-block activations sharded on the
      seq dim over tp (reference: parallel_multi_ds.py:90 sequence_parallel).
    zero: shard optimizer state (and master params) over dp — ZeRO-1
      (reference: distributed_states.h:15 zero flag + bridge subgraphs).
    """

    mesh: MeshConfig = MeshConfig()
    sequence_parallel: bool = False
    zero: bool = True

    # -- mesh ---------------------------------------------------------------
    def build_mesh(self, devices=None):
        return create_mesh(self.mesh, devices=devices)

    @property
    def tp(self) -> int:
        return self.mesh.tp

    @property
    def dp(self) -> int:
        return self.mesh.dp

    @property
    def cp(self) -> int:
        return self.mesh.cp

    @property
    def pp(self) -> int:
        return self.mesh.pp

    @property
    def ep(self) -> int:
        return self.mesh.ep

    # -- parameter layouts (Megatron-style TP over the tp axis) -------------
    def col_weight(self, ndim: int = 2) -> Optional[DS]:
        """Column-parallel weight [in, out]: out dim sharded.
        (reference: HtMultiColumnParallelLinear, parallel_multi_ds.py:328)"""
        return DS.make(ndim, {ndim - 1: "tp"}) if self.tp > 1 else None

    def row_weight(self, ndim: int = 2) -> Optional[DS]:
        """Row-parallel weight [in, out]: in dim sharded."""
        return DS.make(ndim, {ndim - 2: "tp"}) if self.tp > 1 else None

    def col_bias(self) -> Optional[DS]:
        return DS.make(1, {0: "tp"}) if self.tp > 1 else None

    def vocab_weight(self) -> Optional[DS]:
        """Vocab-parallel embedding [vocab, hidden]
        (reference: HtMultiVocabParallelEmbedding, parallel_multi_ds.py:268)."""
        return DS.make(2, {0: "tp"}) if self.tp > 1 else None

    def replicated(self, ndim: int) -> Optional[DS]:
        return None

    # -- activation layouts --------------------------------------------------
    # Activations are [batch, seq, hidden]; batch shards over dp, seq over cp
    # (the reference's fused "dcp" input dim, trainer.py:208-260), and over tp
    # too in SP regions.
    def act_hidden(self) -> DS:
        """Between-block activations."""
        seq_axes: Tuple[str, ...] = ("cp",) if self.cp > 1 else ()
        if self.sequence_parallel and self.tp > 1:
            seq_axes = seq_axes + ("tp",)
        splits = {}
        if self.dp > 1:
            splits[0] = "dp"
        if seq_axes:
            splits[1] = seq_axes
        return DS.make(3, splits)

    def act_inner(self) -> DS:
        """Activations inside attention/MLP: last dim tp-sharded."""
        splits = {}
        if self.dp > 1:
            splits[0] = "dp"
        if self.cp > 1:
            splits[1] = "cp"
        if self.tp > 1:
            splits[2] = "tp"
        return DS.make(3, splits)

    def act_tokens(self) -> DS:
        """Token-id tensors [batch, seq]."""
        splits = {}
        if self.dp > 1:
            splits[0] = "dp"
        if self.cp > 1:
            splits[1] = "cp"
        return DS.make(2, splits)

    def constrain(self, x, ds: Optional[DS]):
        if ds is None:
            return x
        return ds.constrain(x)

    def describe(self) -> str:
        bits = [str(self.mesh)]
        if self.sequence_parallel:
            bits.append("sp")
        if self.zero:
            bits.append("zero1")
        return "+".join(bits)


SINGLE = ParallelStrategy()
