"""Parallel strategy: the per-run binding of model dims to mesh axes.

The reference expresses a strategy as a ds-parallel JSON (per-layer-block
device groups + split/dup/zero maps, reference: python/hetu/utils/parallel/
generate_ds.py:253) consumed by parallel nn modules.  Here a strategy is a
small object that (a) names the mesh shape, (b) hands out DistributedStates
for every parameter/activation role, and (c) knows the SP/ZeRO switches.
Models ask the strategy for layouts instead of hard-coding them, so the same
model code runs dense single-chip, TP, TP+SP, DP×TP×PP×CP, etc.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from hetu_tpu.core.mesh import MeshConfig, create_mesh
from hetu_tpu.dstates import DistributedStates as DS


class StrategyValidationError(ValueError):
    """A parallel plan outside the engines' envelope, rejected at PLAN time
    (before any tracing) — the DeduceStates-rejects-at-graph-build analog
    (reference: hetu/graph/operator.h:425-594)."""


@dataclasses.dataclass(frozen=True)
class ParallelStrategy:
    """Strategy = mesh shape + behavior flags.

    sequence_parallel: Megatron-SP — between-block activations sharded on the
      seq dim over tp (reference: parallel_multi_ds.py:90 sequence_parallel).
    zero: shard optimizer state (and master params) over dp — ZeRO-1
      (reference: distributed_states.h:15 zero flag + bridge subgraphs).
    """

    mesh: MeshConfig = MeshConfig()
    sequence_parallel: bool = False
    # hetero CP: effective tp degree per cp ring member (each a divisor of
    # mesh.tp; None = homogeneous). Routes ring attention through the
    # head-resplit hetero ring (reference: ParallelAttention.cc:949-1050).
    # PRICE (plan accordingly; search/cost_model.py charges it): the
    # rotating KV buffer is padded to the widest member, so every ring hop
    # moves m_max = tp/min(e) times the homogeneous bytes and each rank
    # pre-gathers KV over the full tp axis once per layer — a cp_tp_eff
    # plan must beat homogeneous CP by MORE than its straggler savings.
    cp_tp_eff: Optional[Tuple[int, ...]] = None
    # CP split pattern of the data actually fed to the model
    # (data/bucket.py cp_split_batch: "normal" | "stripe" | "sym").  Drives
    # static ring-step tile skipping (the AttnInfo analog) — it must DESCRIBE
    # the layout, not request one, so None (no skipping, positions still
    # mask exactly) is the safe default; the Trainer resolves it from
    # HETU_TPU_CP_SPLIT and reorders batches to match.
    cp_split: Optional[str] = None
    # hetero PP: effective tp degree per pipeline stage (each a divisor of
    # mesh.tp; None = homogeneous). Routes the decoder stack through the
    # one-program hetero-TP pipeline (parallel/hetero_pp.py — the
    # distributed_states.h:158 unequal-stage-group capability on a
    # rectangular mesh)
    pp_tp_eff: Optional[Tuple[int, ...]] = None
    zero: bool = True          # ZeRO-1 (optimizer-state sharding over dp)
    zero_stage: int = 1        # 1 = opt state; 2 = +grads; 3 = +params (FSDP)
                               # (reference: distributed_states.h zero flag +
                               # bridge subgraphs; stage 3 = fully sharded
                               # weights gathered per-layer by the scan)

    # -- mesh ---------------------------------------------------------------
    def build_mesh(self, devices=None):
        return create_mesh(self.mesh, devices=devices)

    @property
    def tp(self) -> int:
        return self.mesh.tp

    @property
    def dp(self) -> int:
        return self.mesh.dp

    @property
    def cp(self) -> int:
        return self.mesh.cp

    @property
    def pp(self) -> int:
        return self.mesh.pp

    @property
    def ep(self) -> int:
        return self.mesh.ep

    # -- parameter layouts (Megatron-style TP over the tp axis) -------------
    def fsdp(self, ds: Optional[DS], ndim: int, dim: int) -> Optional[DS]:
        """ZeRO-3/FSDP: additionally shard a weight dim over dp; the
        scan-over-layers gathers one layer's weights at a time (streaming
        all-gather), giving the reference's ZeRO-3 memory shape."""
        if self.zero_stage < 3 or self.dp <= 1:
            return ds
        if ds is None:
            ds = DS.dup(ndim)
        if "dp" in ds.used_axes() or ds.spec[dim]:
            return ds
        return ds.with_split(dim, "dp")

    def col_weight(self, ndim: int = 2) -> Optional[DS]:
        """Column-parallel weight [in, out]: out dim sharded.
        (reference: HtMultiColumnParallelLinear, parallel_multi_ds.py:328)"""
        ds = DS.make(ndim, {ndim - 1: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, ndim, ndim - 2)

    def row_weight(self, ndim: int = 2) -> Optional[DS]:
        """Row-parallel weight [in, out]: in dim sharded."""
        ds = DS.make(ndim, {ndim - 2: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, ndim, ndim - 1)

    def col_bias(self) -> Optional[DS]:
        return DS.make(1, {0: "tp"}) if self.tp > 1 else None

    def vocab_weight(self) -> Optional[DS]:
        """Vocab-parallel embedding [vocab, hidden]
        (reference: HtMultiVocabParallelEmbedding, parallel_multi_ds.py:268)."""
        ds = DS.make(2, {0: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, 2, 1)

    def replicated(self, ndim: int) -> Optional[DS]:
        return None

    # -- activation layouts --------------------------------------------------
    # Activations are [batch, seq, ...]; batch shards over dp, seq over cp
    # (the reference's fused "dcp" input dim, trainer.py:208-260), and over tp
    # too in SP regions.  All layouts flow through _act so the axis policy
    # lives in exactly one place.
    def _act(self, ndim: int, tp_dim: Optional[int],
             seq_tp: bool = False) -> DS:
        """[batch, seq, ...rest] layout: dp on dim 0, cp on dim 1, tp on
        `tp_dim` (or on the seq dim when seq_tp — SP regions)."""
        splits: dict = {}
        if self.dp > 1:
            splits[0] = ("dp",)
        seq_axes: Tuple[str, ...] = ("cp",) if self.cp > 1 else ()
        if seq_tp and self.sequence_parallel and self.tp > 1:
            seq_axes = seq_axes + ("tp",)
        if seq_axes:
            splits[1] = seq_axes
        if not seq_tp and tp_dim is not None and self.tp > 1:
            splits[tp_dim] = ("tp",)
        return DS.make(ndim, splits)

    def act_hidden(self) -> DS:
        """Between-block activations [b, s, h] (seq tp-sharded in SP)."""
        return self._act(3, None, seq_tp=True)

    def act_inner(self) -> DS:
        """Activations inside attention/MLP [b, s, f]: last dim tp-sharded."""
        return self._act(3, 2)

    def act_attn(self) -> DS:
        """Per-head activations [b, s, heads, hd]: heads shard over tp
        (inside attention the seq dim is only cp-sharded — SP ends at the
        qkv projection)."""
        return self._act(4, 2)

    def act_qkv(self) -> DS:
        """Fused qkv activations [b, s, n_kv, group+2, hd]: kv-head dim tp."""
        return self._act(5, 2)

    def act_gate_up(self) -> DS:
        """Fused gate/up activations [b, s, 2, intermediate]: last dim tp."""
        return self._act(4, 3)

    def act_logits(self) -> DS:
        """LM logits [b, s, vocab]: vocab dim tp-sharded."""
        return self._act(3, 2)

    def act_tokens(self) -> DS:
        """Token-id tensors [batch, seq]."""
        return self._act(2, None)

    def pipeline_state_spec(self):
        """PartitionSpec for stage-major pipeline buffers [pp, mb, s, h]:
        the stage dim over pp plus act_hidden's dp/cp/sp layout, so stage
        hand-offs move ONLY the stage-dim permute (one definition shared by
        the GPipe and 1F1B engines)."""
        from jax.sharding import PartitionSpec as P
        return P("pp", *tuple(self.act_hidden().partition_spec()))

    def constrain(self, x, ds: Optional[DS]):
        if ds is None:
            return x
        return ds.constrain(x)

    # -- plan-time validation -------------------------------------------
    def validate(self, model_cfg=None, *, pp_schedule: str = "gpipe",
                 n_micro: Optional[int] = None,
                 global_batch: Optional[int] = None,
                 seq_len: Optional[int] = None,
                 stage_layers: Optional[Tuple[int, ...]] = None,
                 deterministic: bool = False,
                 moe_dispatch: Optional[str] = None) -> "ParallelStrategy":
        """The ONE chokepoint encoding the real engine envelope.

        Every planner (Trainer, searcher, Malleus/Ampelos,
        BatchStrategyDispatcher) calls this so no plan the engines would
        reject — or silently degrade — survives past plan time.  Raises
        StrategyValidationError with the rule that failed.

        model_cfg: a model config (LlamaConfig/GPTConfig-shaped, duck-typed
          via getattr) or None for mesh-only checks.
        deterministic: True = an inference/eval plan (dropout never runs,
          so dropout-composition rules are skipped).
        moe_dispatch: the MoE dispatch mode this PLAN runs under; None
          (trainer path) reads the live HETU_TPU_MOE_DISPATCH flag —
          callers judging hypothetical plans (the searcher's
          per-candidate modes) pass the candidate's own mode so a flag
          exported in the planning process cannot veto them.
        """
        def fail(msg):
            raise StrategyValidationError(f"[{self.describe()}] {msg}")

        m = self.mesh
        for name, v in (("dp", m.dp), ("tp", m.tp), ("pp", m.pp),
                        ("cp", m.cp), ("ep", m.ep)):
            if v < 1:
                fail(f"mesh axis {name}={v} must be >= 1")
        if pp_schedule not in ("gpipe", "1f1b"):
            fail(f"pp_schedule must be 'gpipe' or '1f1b', got {pp_schedule!r}")
        if self.zero_stage not in (1, 2, 3):
            fail(f"zero_stage must be 1, 2 or 3, got {self.zero_stage}")
        if self.zero_stage >= 2 and not self.zero:
            fail(f"zero_stage={self.zero_stage} requires zero=True")
        if self.cp_split not in (None, "normal", "stripe", "sym"):
            fail(f"cp_split must be normal|stripe|sym|None, got "
                 f"{self.cp_split!r}")

        # hetero CP ring: per-member effective TP (head-resplit ring)
        if self.cp_tp_eff is not None:
            if self.cp <= 1:
                fail("cp_tp_eff requires cp > 1")
            if len(self.cp_tp_eff) != self.cp:
                fail(f"cp_tp_eff has {len(self.cp_tp_eff)} entries for "
                     f"cp={self.cp}")
            for e in self.cp_tp_eff:
                if e < 1 or self.tp % e:
                    fail(f"cp_tp_eff entry {e} must divide mesh tp={self.tp}")

        # hetero-TP pipeline: per-STAGE effective TP in one program, on
        # both schedules (GPipe switch bodies + 1f1b hetero round bodies),
        # with or without SP.  Engine envelope (models pp_tp_eff paths +
        # parallel/hetero_pp.py): dense blocks, cp=1, hidden dropout only.
        if self.pp_tp_eff is not None:
            if self.pp <= 1:
                fail("pp_tp_eff requires pp > 1")
            if len(self.pp_tp_eff) != self.pp:
                fail(f"pp_tp_eff has {len(self.pp_tp_eff)} entries for "
                     f"pp={self.pp}")
            for e in self.pp_tp_eff:
                if e < 1 or self.tp % e:
                    fail(f"pp_tp_eff entry {e} must divide mesh tp={self.tp}")
            if self.cp > 1:
                fail(f"pp_tp_eff composes with dense blocks, cp=1 "
                     f"(cp={self.cp} set)")
            if self.sequence_parallel and seq_len is not None \
                    and seq_len % self.tp:
                fail(f"pp_tp_eff+SP reduce-scatters the seq dim: "
                     f"seq_len={seq_len} must divide by tp={self.tp}")

        # batch/micro divisibility (pipeline schedules and plain gradient
        # accumulation both split the batch into n_micro equal microbatches)
        if n_micro is not None and n_micro > 1:
            if global_batch is not None and \
                    global_batch % (self.dp * n_micro):
                fail(f"global_batch={global_batch} must divide by "
                     f"dp*n_micro={self.dp * n_micro}")
        if global_batch is not None and global_batch % self.dp:
            fail(f"global_batch={global_batch} must divide by dp={self.dp}")

        # CP data-layout divisibility (data/bucket.py cp_split_batch —
        # the ONE rule set shared with the ring's static step masks)
        if seq_len is not None and self.cp > 1:
            from hetu_tpu.utils import flags as _flags
            split = self.cp_split or _flags.str_flag("HETU_TPU_CP_SPLIT")
            if split == "sym" and seq_len % (2 * self.cp):
                fail(f"seq_len={seq_len} must divide by 2*cp={2 * self.cp} "
                     "for the 'sym' CP split")
            if split == "normal" and seq_len % self.cp:
                fail(f"seq_len={seq_len} must divide by cp={self.cp} for "
                     "the 'normal' CP split")
            if split == "stripe":
                from hetu_tpu.data.bucket import stripe_granularity
                if seq_len % self.cp or \
                        stripe_granularity(seq_len, self.cp) is None:
                    fail(f"seq_len={seq_len} needs a cp*m divisor (m >= 2) "
                         f"for the 'stripe' CP split (cp={self.cp})")

        # explicit MoE dispatch envelope (HETU_TPU_MOE_DISPATCH,
        # nn/moe_dispatch.py): the dispatch shard_map composes with
        # tp=1, pp=1 — reject the plan here instead of at trace time
        if self.ep > 1 and (self.tp > 1 or self.pp > 1):
            if moe_dispatch is None:
                from hetu_tpu.utils import flags as _flags
                moe_dispatch = _flags.str_flag("HETU_TPU_MOE_DISPATCH")
            if moe_dispatch != "gspmd":
                fail("HETU_TPU_MOE_DISPATCH explicit modes require "
                     f"tp=1, pp=1 (got tp={self.tp}, pp={self.pp}); "
                     "unset the flag for this mesh")

        if model_cfg is None:
            return self

        # ---- model-dependent rules (duck-typed config attributes) ----
        heads = getattr(model_cfg, "num_attention_heads", None)
        n_kv = getattr(model_cfg, "num_key_value_heads", heads)
        n_layers = getattr(model_cfg, "num_hidden_layers", None)
        n_experts = getattr(model_cfg, "num_experts", 0) or 0
        use_scan = getattr(model_cfg, "use_scan", True)
        stage_layers = (stage_layers if stage_layers is not None
                        else getattr(model_cfg, "pipeline_stage_layers", None))
        # hidden dropout composes everywhere the engines run; only
        # attention_dropout has composition limits
        attn_drop = getattr(model_cfg, "attention_dropout", 0.0) or 0.0

        if heads is not None and self.tp > 1 and heads % self.tp:
            fail(f"num_attention_heads={heads} must divide by tp={self.tp}")
        if n_kv is not None and self.tp > 1 and n_kv % self.tp:
            fail(f"num_key_value_heads={n_kv} must divide by tp={self.tp}")
        if n_kv is not None:
            for label, effs in (("cp_tp_eff", self.cp_tp_eff),
                                ("pp_tp_eff", self.pp_tp_eff)):
                for e in (effs or ()):
                    if e > 1 and n_kv % e:
                        fail(f"num_key_value_heads={n_kv} must divide by "
                             f"every {label} entry (got {e})")

        if self.ep > 1:
            if n_experts <= 0:
                fail(f"ep={self.ep} requires a MoE model (num_experts > 0)")
            if n_experts % self.ep:
                fail(f"num_experts={n_experts} must divide by ep={self.ep}")

        if self.pp > 1:
            if not use_scan:
                fail("pipeline parallelism requires use_scan=True")
            if stage_layers is not None:
                if len(stage_layers) != self.pp:
                    fail(f"stage_layers={list(stage_layers)} must have "
                         f"len pp={self.pp}")
                if any(k < 1 for k in stage_layers):
                    fail(f"stage_layers={list(stage_layers)} entries must "
                         "be >= 1")
                if n_layers is not None and sum(stage_layers) != n_layers:
                    fail(f"stage_layers={list(stage_layers)} must sum to "
                         f"num_hidden_layers={n_layers}")
            elif n_layers is not None and n_layers % self.pp:
                fail(f"num_hidden_layers={n_layers} must divide by "
                     f"pp={self.pp} (or pass stage_layers)")

        if self.pp_tp_eff is not None:
            if not getattr(model_cfg, "supports_hetero_tp", False):
                fail("pp_tp_eff needs a model family with a hetero-TP "
                     "block maker (LLaMA and GPT have one — see "
                     "parallel/hetero_pp.py); this one would silently "
                     "run all stages at homogeneous TP")
            if n_experts > 0:
                fail("pp_tp_eff composes with dense blocks only "
                     f"(num_experts={n_experts})")
            if (not deterministic) and attn_drop > 0:
                fail("attention_dropout inside the hetero-TP pipeline is "
                     "not implemented (hidden_dropout is supported)")

        if self.cp > 1 and not deterministic and attn_drop > 0:
            fail(f"attention_dropout={attn_drop} inside ring attention "
                 "(cp > 1) is not implemented")

        if pp_schedule == "1f1b" and self.pp > 1 and not use_scan:
            fail("1f1b requires use_scan=True")
        return self

    def describe(self) -> str:
        bits = [str(self.mesh)]
        if self.cp_tp_eff is not None:
            bits.append(f"cptp{list(self.cp_tp_eff)}")
        if self.pp_tp_eff is not None:
            bits.append(f"pptp{list(self.pp_tp_eff)}")
        if self.sequence_parallel:
            bits.append("sp")
        if self.zero:
            bits.append(f"zero{max(self.zero_stage, 1)}")
        return "+".join(bits)


def validate_stage_plan(num_layers: int, dp: int, tp: int,
                        stage_layers) -> None:
    """Envelope check for a planner-produced stage plan (Malleus/Ampelos):
    one shared call instead of each planner synthesizing its own
    strategy+config dance."""
    from types import SimpleNamespace
    ParallelStrategy(mesh=MeshConfig(dp=dp, tp=tp, pp=len(stage_layers))) \
        .validate(SimpleNamespace(num_hidden_layers=num_layers),
                  stage_layers=tuple(stage_layers))


SINGLE = ParallelStrategy()
