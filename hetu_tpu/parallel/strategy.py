"""Parallel strategy: the per-run binding of model dims to mesh axes.

The reference expresses a strategy as a ds-parallel JSON (per-layer-block
device groups + split/dup/zero maps, reference: python/hetu/utils/parallel/
generate_ds.py:253) consumed by parallel nn modules.  Here a strategy is a
small object that (a) names the mesh shape, (b) hands out DistributedStates
for every parameter/activation role, and (c) knows the SP/ZeRO switches.
Models ask the strategy for layouts instead of hard-coding them, so the same
model code runs dense single-chip, TP, TP+SP, DP×TP×PP×CP, etc.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from hetu_tpu.core.mesh import MeshConfig, create_mesh
from hetu_tpu.dstates import DistributedStates as DS


@dataclasses.dataclass(frozen=True)
class ParallelStrategy:
    """Strategy = mesh shape + behavior flags.

    sequence_parallel: Megatron-SP — between-block activations sharded on the
      seq dim over tp (reference: parallel_multi_ds.py:90 sequence_parallel).
    zero: shard optimizer state (and master params) over dp — ZeRO-1
      (reference: distributed_states.h:15 zero flag + bridge subgraphs).
    """

    mesh: MeshConfig = MeshConfig()
    sequence_parallel: bool = False
    # hetero CP: effective tp degree per cp ring member (each a divisor of
    # mesh.tp; None = homogeneous). Routes ring attention through the
    # head-resplit hetero ring (reference: ParallelAttention.cc:949-1050).
    # PRICE (plan accordingly; search/cost_model.py charges it): the
    # rotating KV buffer is padded to the widest member, so every ring hop
    # moves m_max = tp/min(e) times the homogeneous bytes and each rank
    # pre-gathers KV over the full tp axis once per layer — a cp_tp_eff
    # plan must beat homogeneous CP by MORE than its straggler savings.
    cp_tp_eff: Optional[Tuple[int, ...]] = None
    # CP split pattern of the data actually fed to the model
    # (data/bucket.py cp_split_batch: "normal" | "stripe" | "sym").  Drives
    # static ring-step tile skipping (the AttnInfo analog) — it must DESCRIBE
    # the layout, not request one, so None (no skipping, positions still
    # mask exactly) is the safe default; the Trainer resolves it from
    # HETU_TPU_CP_SPLIT and reorders batches to match.
    cp_split: Optional[str] = None
    # hetero PP: effective tp degree per pipeline stage (each a divisor of
    # mesh.tp; None = homogeneous). Routes the decoder stack through the
    # one-program hetero-TP pipeline (parallel/hetero_pp.py — the
    # distributed_states.h:158 unequal-stage-group capability on a
    # rectangular mesh)
    pp_tp_eff: Optional[Tuple[int, ...]] = None
    zero: bool = True          # ZeRO-1 (optimizer-state sharding over dp)
    zero_stage: int = 1        # 1 = opt state; 2 = +grads; 3 = +params (FSDP)
                               # (reference: distributed_states.h zero flag +
                               # bridge subgraphs; stage 3 = fully sharded
                               # weights gathered per-layer by the scan)

    # -- mesh ---------------------------------------------------------------
    def build_mesh(self, devices=None):
        return create_mesh(self.mesh, devices=devices)

    @property
    def tp(self) -> int:
        return self.mesh.tp

    @property
    def dp(self) -> int:
        return self.mesh.dp

    @property
    def cp(self) -> int:
        return self.mesh.cp

    @property
    def pp(self) -> int:
        return self.mesh.pp

    @property
    def ep(self) -> int:
        return self.mesh.ep

    # -- parameter layouts (Megatron-style TP over the tp axis) -------------
    def fsdp(self, ds: Optional[DS], ndim: int, dim: int) -> Optional[DS]:
        """ZeRO-3/FSDP: additionally shard a weight dim over dp; the
        scan-over-layers gathers one layer's weights at a time (streaming
        all-gather), giving the reference's ZeRO-3 memory shape."""
        if self.zero_stage < 3 or self.dp <= 1:
            return ds
        if ds is None:
            ds = DS.dup(ndim)
        if "dp" in ds.used_axes() or ds.spec[dim]:
            return ds
        return ds.with_split(dim, "dp")

    def col_weight(self, ndim: int = 2) -> Optional[DS]:
        """Column-parallel weight [in, out]: out dim sharded.
        (reference: HtMultiColumnParallelLinear, parallel_multi_ds.py:328)"""
        ds = DS.make(ndim, {ndim - 1: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, ndim, ndim - 2)

    def row_weight(self, ndim: int = 2) -> Optional[DS]:
        """Row-parallel weight [in, out]: in dim sharded."""
        ds = DS.make(ndim, {ndim - 2: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, ndim, ndim - 1)

    def col_bias(self) -> Optional[DS]:
        return DS.make(1, {0: "tp"}) if self.tp > 1 else None

    def vocab_weight(self) -> Optional[DS]:
        """Vocab-parallel embedding [vocab, hidden]
        (reference: HtMultiVocabParallelEmbedding, parallel_multi_ds.py:268)."""
        ds = DS.make(2, {0: "tp"}) if self.tp > 1 else None
        return self.fsdp(ds, 2, 1)

    def replicated(self, ndim: int) -> Optional[DS]:
        return None

    # -- activation layouts --------------------------------------------------
    # Activations are [batch, seq, ...]; batch shards over dp, seq over cp
    # (the reference's fused "dcp" input dim, trainer.py:208-260), and over tp
    # too in SP regions.  All layouts flow through _act so the axis policy
    # lives in exactly one place.
    def _act(self, ndim: int, tp_dim: Optional[int],
             seq_tp: bool = False) -> DS:
        """[batch, seq, ...rest] layout: dp on dim 0, cp on dim 1, tp on
        `tp_dim` (or on the seq dim when seq_tp — SP regions)."""
        splits: dict = {}
        if self.dp > 1:
            splits[0] = ("dp",)
        seq_axes: Tuple[str, ...] = ("cp",) if self.cp > 1 else ()
        if seq_tp and self.sequence_parallel and self.tp > 1:
            seq_axes = seq_axes + ("tp",)
        if seq_axes:
            splits[1] = seq_axes
        if not seq_tp and tp_dim is not None and self.tp > 1:
            splits[tp_dim] = ("tp",)
        return DS.make(ndim, splits)

    def act_hidden(self) -> DS:
        """Between-block activations [b, s, h] (seq tp-sharded in SP)."""
        return self._act(3, None, seq_tp=True)

    def act_inner(self) -> DS:
        """Activations inside attention/MLP [b, s, f]: last dim tp-sharded."""
        return self._act(3, 2)

    def act_attn(self) -> DS:
        """Per-head activations [b, s, heads, hd]: heads shard over tp
        (inside attention the seq dim is only cp-sharded — SP ends at the
        qkv projection)."""
        return self._act(4, 2)

    def act_qkv(self) -> DS:
        """Fused qkv activations [b, s, n_kv, group+2, hd]: kv-head dim tp."""
        return self._act(5, 2)

    def act_gate_up(self) -> DS:
        """Fused gate/up activations [b, s, 2, intermediate]: last dim tp."""
        return self._act(4, 3)

    def act_logits(self) -> DS:
        """LM logits [b, s, vocab]: vocab dim tp-sharded."""
        return self._act(3, 2)

    def act_tokens(self) -> DS:
        """Token-id tensors [batch, seq]."""
        return self._act(2, None)

    def pipeline_state_spec(self):
        """PartitionSpec for stage-major pipeline buffers [pp, mb, s, h]:
        the stage dim over pp plus act_hidden's dp/cp/sp layout, so stage
        hand-offs move ONLY the stage-dim permute (one definition shared by
        the GPipe and 1F1B engines)."""
        from jax.sharding import PartitionSpec as P
        return P("pp", *tuple(self.act_hidden().partition_spec()))

    def constrain(self, x, ds: Optional[DS]):
        if ds is None:
            return x
        return ds.constrain(x)

    def describe(self) -> str:
        bits = [str(self.mesh)]
        if self.cp_tp_eff is not None:
            bits.append(f"cptp{list(self.cp_tp_eff)}")
        if self.sequence_parallel:
            bits.append("sp")
        if self.zero:
            bits.append(f"zero{max(self.zero_stage, 1)}")
        return "+".join(bits)


SINGLE = ParallelStrategy()
