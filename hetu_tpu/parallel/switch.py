"""Parallelism hot-switching.

Rebuild of the reference's SwitchExecGraph (reference: hetu/graph/
switch_exec_graph.{h,cc} — the SOSP'24 "HotSPa" engine: partition every param
into ParamSlices over the src∪dst layout lattice :566, build a
BatchedISendIRecv comm graph :919, pack contiguous buffers :1307, switch
modes param/param+optimizer/grads :42-48).

TPU-native design: the slice lattice + batched P2P program IS what the XLA
runtime executes for a sharding-changing `jax.device_put` — resharding a
pytree onto new NamedShardings computes exactly the minimal slice transfers
(ICI collective-permutes / copies).  So the engine here is thin and the
heavy machinery lives where it should (the runtime):

    switch_tree(tree, new_shardings, donate=True)

`StrategySwitcher` adds the reference's mode semantics (SWITCH_MODE) and the
bookkeeping the trainer needs: per-strategy model instances, sharding pytrees,
and cached compiled steps (the reference's plan pool keyed by strategy id,
define_and_run_graph.cc:1174).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Optional

import jax

from hetu_tpu.parallel.strategy import ParallelStrategy


class SwitchMode(enum.Enum):
    """What travels to the new layout (reference: switch_exec_graph.h:42-48)."""
    PARAM = "param"                    # params only (opt state re-init)
    PARAM_AND_OPTIMIZER = "param_opt"  # params + m/v (exact resume)


def switch_tree(tree, new_shardings, donate: bool = True):
    """Reshard a pytree onto new shardings (the ParamSlice comm graph,
    executed by the runtime)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s, donate=donate), tree, new_shardings)


@dataclasses.dataclass
class StrategyHandle:
    """Per-strategy artifacts (one entry of the reference's plan pool)."""
    strategy: ParallelStrategy
    model: Any
    mesh: Any
    param_shardings: Any
    state_shardings: Any


class StrategySwitcher:
    """Owns the strategy pool and performs hot switches on (params, opt_state).

    Usage (mirrors examples/hotspa/llama_hot_switch_trainer.py):
        sw = StrategySwitcher({0: handle0, 1: handle1})
        params, opt = sw.switch(params, opt_state, to_id=1)
    """

    def __init__(self, handles: Dict[int, StrategyHandle]):
        self.handles = handles

    def switch(self, params, opt_state, to_id: int,
               mode: SwitchMode = SwitchMode.PARAM_AND_OPTIMIZER,
               donate: bool = True):
        dst = self.handles[to_id]
        new_params = switch_tree(params, dst.param_shardings, donate=donate)
        if mode is SwitchMode.PARAM_AND_OPTIMIZER and opt_state is not None:
            new_state = switch_tree(opt_state, dst.state_shardings,
                                    donate=donate)
        else:
            new_state = None
        return new_params, new_state
