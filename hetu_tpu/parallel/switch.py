"""Parallelism hot-switching.

Rebuild of the reference's SwitchExecGraph (reference: hetu/graph/
switch_exec_graph.{h,cc} — the SOSP'24 "HotSPa" engine: partition every param
into ParamSlices over the src∪dst layout lattice :566, build a
BatchedISendIRecv comm graph :919, pack contiguous buffers :1307, switch
modes param/param+optimizer/grads :42-48).

TPU-native design: the slice lattice + batched P2P program IS what the XLA
runtime executes for a sharding-changing `jax.device_put` — resharding a
pytree onto new NamedShardings computes exactly the minimal slice transfers
(ICI collective-permutes / copies).  So the engine here is thin and the
heavy machinery lives where it should (the runtime):

    switch_tree(tree, new_shardings, donate=True)

`StrategySwitcher` adds the reference's mode semantics (SWITCH_MODE) and the
bookkeeping the trainer needs: per-strategy model instances, sharding pytrees,
and cached compiled steps (the reference's plan pool keyed by strategy id,
define_and_run_graph.cc:1174).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Optional

import jax
import numpy as np

from hetu_tpu.parallel.strategy import ParallelStrategy


class SwitchMode(enum.Enum):
    """What travels to the new layout (reference: switch_exec_graph.h:42-48)."""
    PARAM = "param"                    # params only (opt state re-init)
    PARAM_AND_OPTIMIZER = "param_opt"  # params + m/v (exact resume)


def switch_tree(tree, new_shardings, donate: bool = True):
    """Reshard a pytree onto new shardings (the ParamSlice comm graph,
    executed by the runtime)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s, donate=donate), tree, new_shardings)


# ----------------------------------------------------------------------
# Switch profiling — the analog of SwitchExecGraph::ProfileRunningDetails
# (reference: switch_exec_graph.cc:1904 — per-device send/recv bytes for
# the ParamSlice program).  The comm program is compiler-planned here, so
# instead of instrumenting it we compute the same numbers analytically
# from the (src, dst) sharding index maps: each device must fetch exactly
# the part of its destination slice it does not already hold.
# ----------------------------------------------------------------------

@dataclasses.dataclass
class SwitchProfile:
    """Byte accounting for one hot switch.  All tallies are recv-side and
    aggregate over devices, so replication counts once per replica (the
    reference's per-device recv tallies do the same):
    total_bytes == moved_bytes + local_bytes == the destination layout's
    aggregate memory footprint; logical_bytes is the tree payload counted
    once."""
    total_bytes: int = 0          # aggregate dst footprint over devices
    logical_bytes: int = 0        # tree payload, each element counted once
    moved_bytes: int = 0          # bytes crossing devices (recv side)
    local_bytes: int = 0          # bytes already resident at the dst slice
    per_device_recv: Dict[str, int] = dataclasses.field(default_factory=dict)
    wall_s: float = 0.0

    def describe(self) -> str:
        frac = self.moved_bytes / self.total_bytes if self.total_bytes else 0.0
        return (f"moved {self.moved_bytes / 1e6:.1f} MB of "
                f"{self.total_bytes / 1e6:.1f} MB dst footprint ({frac:.0%}; "
                f"payload {self.logical_bytes / 1e6:.1f} MB) "
                f"in {self.wall_s:.3f}s")


def _slice_volume(idx, shape) -> int:
    vol = 1
    for sl, n in zip(idx, shape):
        start = 0 if sl.start is None else sl.start
        stop = n if sl.stop is None else sl.stop
        vol *= max(0, stop - start)
    return vol


def _overlap_volume(a, b, shape) -> int:
    vol = 1
    for sa, sb, n in zip(a, b, shape):
        a0 = 0 if sa.start is None else sa.start
        a1 = n if sa.stop is None else sa.stop
        b0 = 0 if sb.start is None else sb.start
        b1 = n if sb.stop is None else sb.stop
        vol *= max(0, min(a1, b1) - max(a0, b0))
        if vol == 0:
            return 0
    return vol


def profile_switch(tree, old_shardings, new_shardings) -> SwitchProfile:
    """Analytic bytes-moved accounting for resharding `tree` from
    `old_shardings` to `new_shardings` (reference: ProfileRunningDetails'
    send/recv byte tallies, switch_exec_graph.cc:1904).

    For every leaf and every device d: recv bytes = |dst slice on d| minus
    the overlap with the src slice d already holds.  The overlap rule is
    exact for the slice lattice both engines use (rectangular sub-blocks).
    """
    prof = SwitchProfile()
    leaves = jax.tree.leaves(tree)
    olds = jax.tree.leaves(old_shardings)
    news = jax.tree.leaves(new_shardings)
    for x, os_, ns in zip(leaves, olds, news):
        shape = tuple(x.shape)
        nbytes = int(np.dtype(x.dtype).itemsize)
        if not shape:                       # scalar: replication only
            prof.logical_bytes += nbytes
            continue
        src_map = os_.devices_indices_map(shape)
        dst_map = ns.devices_indices_map(shape)
        prof.logical_bytes += int(np.prod(shape)) * nbytes
        for dev, didx in dst_map.items():
            want = _slice_volume(didx, shape)
            sidx = src_map.get(dev)
            have = _overlap_volume(didx, sidx, shape) if sidx is not None else 0
            moved = (want - have) * nbytes
            if moved:
                key = str(dev.id)
                prof.per_device_recv[key] = \
                    prof.per_device_recv.get(key, 0) + moved
            prof.total_bytes += want * nbytes
            prof.moved_bytes += moved
            prof.local_bytes += have * nbytes
    return prof


@dataclasses.dataclass
class StrategyHandle:
    """Per-strategy artifacts (one entry of the reference's plan pool)."""
    strategy: ParallelStrategy
    model: Any
    mesh: Any
    param_shardings: Any
    state_shardings: Any


class StrategySwitcher:
    """Owns the strategy pool and performs hot switches on (params, opt_state).

    Usage (mirrors examples/hotspa/llama_hot_switch_trainer.py):
        sw = StrategySwitcher({0: handle0, 1: handle1})
        params, opt = sw.switch(params, opt_state, to_id=1)
    """

    def __init__(self, handles: Dict[int, StrategyHandle]):
        self.handles = handles

    def switch(self, params, opt_state, to_id: int,
               mode: SwitchMode = SwitchMode.PARAM_AND_OPTIMIZER,
               donate: bool = True):
        # the two switch phases (param move, opt-state move) are timed
        # separately into the metrics registry — the reference profiles
        # its ParamSlice program per phase the same way
        from hetu_tpu.obs.metrics import get_registry
        reg = get_registry()
        dst = self.handles[to_id]
        with reg.timer("switch.params_s", to_id=to_id):
            new_params = switch_tree(params, dst.param_shardings,
                                     donate=donate)
        if mode is SwitchMode.PARAM_AND_OPTIMIZER and opt_state is not None:
            with reg.timer("switch.opt_state_s", to_id=to_id):
                new_state = switch_tree(opt_state, dst.state_shardings,
                                        donate=donate)
        else:
            new_state = None
        return new_params, new_state
