from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.parallel.hetero_dp import HeteroDPEngine, HeteroDPGroup
