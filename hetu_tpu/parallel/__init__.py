from hetu_tpu.parallel.strategy import ParallelStrategy
