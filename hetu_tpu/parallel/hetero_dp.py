"""Heterogeneous data parallelism: uneven dp groups with different inner
layouts (different tp degrees), executing as one logical training run.

Rebuild of the reference's hetero-DS-union execution for dp
(reference: hetu/graph/distributed_states.h:158-321 DistributedStatesUnion;
hetu/graph/define_and_run_graph.cc:159 DeducePipeline's hetero groups;
python/hetu/engine/strategy.py:99 Malleus assigning uneven batch shares to
unequal device groups).  There, hetero dp groups run different (tp, batch)
configurations and bridge their gradients with cross-group NCCL.

TPU-native design: one rectangular jit program cannot hold two different tp
degrees, so a hetero-dp run is SEVERAL compiled programs over disjoint
sub-meshes of the same slice — exactly how the reference executes unions
(per-group exec graphs + bridge comm).  The union layer
(dstates.DistributedStatesUnion) owns the cross-group batch partition
(hetero_dim=0, shares = per-group rows); this engine owns execution:

    per group   g: grads_g = d/dp [ sum-CE(batch slice g) ]     (jit on mesh_g)
    bridge      : G = sum_g transfer(grads_g)  / sum_g tokens_g
    update      : params0 <- AdamW(params0, G)                  (jit on mesh_0)
    broadcast   : params_g <- transfer(params0)

The bridge transfers ride `jax.device_put` across meshes (ICI/DCN chosen by
the runtime — the reference's bridge NCCL groups).  Group 0 holds the
optimizer state; with shares proportional to measured group throughput
(MalleusPlanner.plan_hetero_dp) every group finishes its slice in the same
wall time, which is the whole point of hetero dp under stragglers.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from hetu_tpu.core.mesh import use_mesh
from hetu_tpu.dstates import DistributedStates as DS, DistributedStatesUnion
from hetu_tpu.parallel.strategy import ParallelStrategy
from hetu_tpu.utils.logging import get_logger

logger = get_logger("hetero_dp")


@dataclasses.dataclass
class HeteroDPGroup:
    """One hetero group: an inner strategy over an explicit device subset
    plus its batch share (reference: one member of a DS union)."""
    strategy: ParallelStrategy
    devices: Sequence[jax.Device]
    share: int = 1

    def __post_init__(self):
        need = self.strategy.mesh.num_devices
        if need != len(self.devices):
            raise ValueError(
                f"group strategy {self.strategy.describe()} needs {need} "
                f"devices, got {len(self.devices)}")


class HeteroDPEngine:
    """Training engine over hetero dp groups.

    model_factory(strategy) -> model (same architecture per group; only the
    layout differs).  The optimizer lives on group 0 (the reference keeps
    ZeRO/optimizer state on one union member and bridges the rest).
    """

    def __init__(self, model_factory: Callable, optimizer,
                 groups: List[HeteroDPGroup],
                 grad_compress: Optional[str] = None):
        if not groups:
            raise ValueError("need at least one group")
        for gi, g in enumerate(groups):
            if int(g.share) < 1:
                raise ValueError(
                    f"hetero-dp group {gi} ({g.strategy.describe()}): share "
                    f"must be a positive integer, got {g.share!r}")
        # bridge compression (HETU_TPU_GRAD_COMPRESS, overridable per
        # engine): non-resident groups ship int8+scales across meshes
        # instead of f32 sum-grads — quantize-before-device_put, with
        # per-GROUP error-feedback residuals living on the source mesh
        # (docs/comm_compression.md)
        from hetu_tpu.utils import flags as _flags
        self.grad_compress = (grad_compress if grad_compress is not None
                              else _flags.str_flag("HETU_TPU_GRAD_COMPRESS"))
        from hetu_tpu.comm.grad_sync import MODES
        if self.grad_compress not in MODES:
            raise ValueError(f"grad_compress must be one of {MODES}, got "
                             f"{self.grad_compress!r}")
        self.optimizer = optimizer
        self.groups = groups
        self.models = [model_factory(g.strategy) for g in groups]
        self.meshes = [g.strategy.build_mesh(devices=g.devices)
                       for g in groups]
        self.batch_union = DistributedStatesUnion(
            tuple(DS.make(2, {0: "dp"} if g.strategy.dp > 1 else {})
                  for g in groups),
            hetero_dim=0, shares=tuple(g.share for g in groups)).validate()
        self.params: Optional[List] = None      # per-group replicas
        self.opt_state = None                   # group-0 resident
        self._grad_fns = []
        self._update_fn = None
        self._pshards = []
        # bridge-compression state: per source group a jitted quantize fn
        # and (int8-ef) its error-feedback residual tree, mesh-resident
        self._compress_fns: List = []
        self._accum_fn = None
        self._bridge_residuals: List = []

    # ------------------------------------------------------------------
    def build(self, rng=None):
        rng = jax.random.key(0) if rng is None else rng
        self._pshards = [m.shardings(mesh)
                         for m, mesh in zip(self.models, self.meshes)]
        with use_mesh(self.meshes[0]):
            p0 = jax.jit(self.models[0].init,
                         out_shardings=self._pshards[0])(rng)
        self.params = [p0] + [
            jax.device_put(p0, sh) for sh in self._pshards[1:]]
        with use_mesh(self.meshes[0]):
            self.opt_state = jax.jit(self.optimizer.init)(p0)

        for gi, (model, mesh) in enumerate(zip(self.models, self.meshes)):
            def _grads(params, ids, _model=model):
                def loss_sum(p):
                    s, c = _model(p, ids, labels=ids, loss_reduction="sum")
                    return s, c
                (s, c), g = jax.value_and_grad(loss_sum, has_aux=True)(params)
                return s, c, g
            with use_mesh(mesh):
                self._grad_fns.append(jax.jit(_grads))

        def _update(params, opt_state, gsum, tokens):
            g = jax.tree.map(lambda x: x / tokens, gsum)
            params, opt_state = self.optimizer.update(g, opt_state, params)
            return params, opt_state
        with use_mesh(self.meshes[0]):
            self._update_fn = jax.jit(
                _update, out_shardings=(self._pshards[0], None),
                donate_argnums=(0, 1))

        if self.grad_compress != "none" and len(self.groups) > 1:
            from hetu_tpu.comm.grad_sync import (bridge_accumulate,
                                                 bridge_compress,
                                                 bridge_residual_init,
                                                 uses_error_feedback)
            from hetu_tpu.comm.wire import mode_bits
            ef = uses_error_feedback(self.grad_compress)
            bits = mode_bits(self.grad_compress)
            self._compress_fns = [None]
            self._bridge_residuals = [None]
            for gi in range(1, len(self.groups)):
                with use_mesh(self.meshes[gi]):
                    if ef:
                        self._bridge_residuals.append(
                            jax.jit(bridge_residual_init)(self.params[gi]))
                        self._compress_fns.append(
                            jax.jit(lambda g, r: bridge_compress(
                                g, r, bits=bits)))
                    else:
                        self._bridge_residuals.append(None)
                        self._compress_fns.append(
                            jax.jit(lambda g: bridge_compress(
                                g, bits=bits)))
            with use_mesh(self.meshes[0]):
                self._accum_fn = jax.jit(
                    lambda acc, qs, ss: bridge_accumulate(
                        acc, qs, ss, bits=bits),
                    out_shardings=self._pshards[0])
        return self

    # ------------------------------------------------------------------
    def bridged_grads(self, host_batch: Dict[str, np.ndarray]):
        """The bridge's output WITHOUT stepping: (token-weighted mean grad
        on group 0's layout, token count, loss).  This is the quantity the
        parity regression test pins down — G must be sum_g grads_g divided
        by the global token count (never share- or group-weighted).
        Inspection must not perturb training: EF residuals are NOT
        committed (the quantization error of a discarded transfer must
        not be 'corrected' on the next real step)."""
        gsum, tokens, loss = self._grads_and_bridge(
            host_batch, commit_residuals=False)
        G = jax.tree.map(lambda x: x / np.float32(tokens), gsum)
        return G, tokens, loss

    def train_step(self, host_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One global step: per-group grads -> bridge -> update -> broadcast.
        The batch is split along dim 0 by the union's shares."""
        gsum, tokens, loss = self._grads_and_bridge(host_batch)
        with use_mesh(self.meshes[0]):
            self.params[0], self.opt_state = self._update_fn(
                self.params[0], self.opt_state, gsum, tokens)
        # broadcast updated params to the other groups' layouts
        for gi in range(1, len(self.groups)):
            self.params[gi] = jax.device_put(self.params[0],
                                             self._pshards[gi])
        return {"loss": loss, "tokens": tokens}

    def _grads_and_bridge(self, host_batch: Dict[str, np.ndarray],
                          commit_residuals: bool = True):
        """Per-group sum-grads + the cross-mesh bridge reduce; returns
        (gsum on group 0, global token count, token-weighted loss).
        commit_residuals=False evaluates the bridge without advancing the
        EF state (bridged_grads inspection)."""
        ids = np.asarray(host_batch["input_ids"])
        parts = self.batch_union.split_host(ids)
        for gi, (part, grp) in enumerate(zip(parts, self.groups)):
            dp = max(grp.strategy.dp, 1)
            if part.shape[0] == 0 or part.shape[0] % dp:
                raise ValueError(
                    f"hetero-dp group {gi} ({grp.strategy.describe()}, "
                    f"share={grp.share}): batch slice of {part.shape[0]} "
                    f"rows is not a positive multiple of its dp degree "
                    f"{dp} — resize the global batch ({ids.shape[0]}) or "
                    f"the union shares {list(self.batch_union.shares)}")
        sums, counts, grads = [], [], []
        for gi, part in enumerate(parts):
            with use_mesh(self.meshes[gi]):
                s, c, g = self._grad_fns[gi](self.params[gi], part)
            sums.append(s)
            counts.append(c)
            grads.append(g)
        # bridge: bring every group's sum-grads onto group 0's layout and
        # accumulate (the union's cross-group reduce).  Compressed modes
        # ship int8+scales (~3.9x fewer bridge bytes, comm/wire.py) and
        # keep the quantization error as a per-group EF residual on the
        # source mesh; group 0's own grads never quantize (resident).
        gsum = grads[0]
        for gi in range(1, len(grads)):
            if self.grad_compress == "none":
                g0 = jax.device_put(grads[gi], self._pshards[0])
                gsum = jax.tree.map(lambda a, b: a + b, gsum, g0)
                continue
            with use_mesh(self.meshes[gi]):
                if self._bridge_residuals[gi] is not None:
                    qs, ss, new_res = self._compress_fns[gi](
                        grads[gi], self._bridge_residuals[gi])
                    if commit_residuals:
                        self._bridge_residuals[gi] = new_res
                else:
                    qs, ss, _ = self._compress_fns[gi](grads[gi])
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep0 = NamedSharding(self.meshes[0], P())
            qs0 = jax.device_put(qs, rep0)
            ss0 = jax.device_put(ss, rep0)
            with use_mesh(self.meshes[0]):
                gsum = self._accum_fn(gsum, qs0, ss0)
        tokens = sum(float(c) for c in counts)
        loss = sum(float(s) for s in sums) / max(tokens, 1.0)
        return gsum, tokens, loss
