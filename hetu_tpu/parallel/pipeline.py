"""Pipeline parallelism.

Rebuild of the reference's pipeline engine (reference: hetu/graph/
executable_graph.cc — GPipe schedule :803, PipeDream-flush/1F1B :836,
micro-batch interpreter ComputeFunc :883, P2P stage transfer via
is_pipeline_stage_send_op + kP2PStream).

TPU-first design: the whole pipeline is ONE compiled GSPMD program — no
host-interpreted per-stage programs, no NCCL P2P, and no manual shard_map:

- layer params are stacked [pp, layers_per_stage, ...] and sharded over the
  `pp` mesh axis, so each device group holds exactly one stage's weights
  (the reference's op->stage placement from the ds JSON).
- the pipeline state is a stage-major activation buffer [pp, mb, s, h], also
  sharded over pp.  Each schedule tick applies ALL stages in parallel with
  `jax.vmap(stage_body, spmd_axis_name="pp")` — GSPMD partitions the vmapped
  dim across the pp axis, and the body's own TP/SP sharding constraints
  compose (they gain a leading pp dim automatically).
- the stage hand-off is a shift along the stage dim
  (concat(new_micro, state[:-1])); under the pp sharding XLA lowers it to a
  collective-permute between neighbor stages — the kP2PStream send/recv of
  the reference, inserted by the compiler.
- schedule: classic GPipe filling/draining over T = n_micro + pp - 1 ticks
  (lax.scan).  Stage s processes micro t-s at tick t; token metadata
  (positions/segments) rides the same buffer.  Backward is jax autodiff
  through the scan (GPipe backward); per-tick remat keeps activation memory
  at one stage-slice per in-flight micro — the memory class the reference
  reaches via 1F1B + recompute.  Bubble fraction (pp-1)/(n_micro+pp-1),
  same as the reference's GPipe schedule.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


def gpipe_schedule_validity(pp: int, n_micro: int):
    """The GPipe fill/steady/drain structure as a [T, pp] numpy mask:
    stage s is live at tick t iff 0 <= t-s < n_micro (its micro index).
    This is the mask pipeline_apply scans over (aux masking) — factored
    out so the Chrome-trace exporter (hetu_tpu.obs.trace) renders the
    schedule the engine actually executes."""
    T = n_micro + pp - 1
    micro_idx = np.arange(T)[:, None] - np.arange(pp)[None, :]   # [T, pp]
    return (micro_idx >= 0) & (micro_idx < n_micro)


def pipeline_apply(stage_body: Callable, stage_params, x, token_data: Dict,
                   *, n_micro: int, mesh, pp_axis: str = "pp",
                   remat: bool = True, remat_policy: str = "nothing",
                   stage_mask=None, state_spec=None, hetero_exec: bool = False,
                   stage_const=None):
    """Run the circular pipeline.

    stage_body(stage_params_slice, x_mb, token_data_mb) -> x_mb — applies one
    stage's layer slice to one micro-batch activation [mb, s, h].
    stage_params: pytree with leading [pp, ...] dims (sharded over pp).
    x: [B, s, h] global activations (B divides by n_micro).
    token_data: dict of [B, s] arrays riding along (positions/segments).

    hetero_exec: run the per-tick stage computation under `jax.shard_map`
    manual over ONLY the pp axis (dp/tp/cp stay automatic/GSPMD) instead of
    `jax.vmap(spmd_axis_name=pp)`.  Under vmap every stage traces one shared
    program, so a hetero (Malleus) layout's padded layers become `select`s
    that still PAY max(stage_layers) compute per tick; under shard_map each
    stage's `lax.cond` stays a real XLA conditional, so a stage executes
    exactly its own layer count — the point of uneven stage assignment
    (reference: define_and_run_graph.cc:159 DeducePipeline hetero stages).
    """
    B, s, h = x.shape
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    pp = mesh.shape[pp_axis]
    T = n_micro + pp - 1
    pad = pp - 1
    # full buffer layout: keep dp/cp/sp shards of the mb/seq dims across the
    # stage hand-off so only the stage-dim permute moves data (a bare
    # P(pp) would replicate-then-reslice every tick)
    spec = state_spec if state_spec is not None else P(pp_axis)
    tok_spec = P(*((spec[0],) + tuple(spec[1:3])))

    xm = x.reshape(n_micro, mb, s, h)
    tok = {k: v.reshape(n_micro, mb, s) for k, v in token_data.items()}

    body = stage_body
    if remat:
        from hetu_tpu.nn.remat import remat_policy as _policy
        body = jax.checkpoint(stage_body, policy=_policy(remat_policy))
    extra_axes = (0,) if stage_mask is not None else ()
    # stage_const: optional per-stage constants with a leading [pp] dim
    # (e.g. the global-layer offset feeding pipeline dropout rng derivation)
    if stage_const is not None:
        extra_axes = extra_axes + (0,)
    if hetero_exec:
        if stage_const is not None:
            raise NotImplementedError(
                "stage_const (pipeline dropout) uses the padded vmap path; "
                "pass hetero_exec=False")
        # note: only the stage-dim (pp) layout is named in the shard_map
        # specs — the dp/cp/tp parts of state_spec stay AUTO axes and are
        # honored by the body's own sharding constraints
        vbody = _shard_map_stage_body(body, mesh, pp_axis, token_data,
                                      has_mask=stage_mask is not None)
    else:
        vbody = jax.vmap(body, in_axes=(0, 0, 0) + extra_axes,
                         spmd_axis_name=pp_axis)

    def shift_in(new, state, sp=None):
        """Stage hand-off: stage 0 gets the fresh micro, stage i gets stage
        i-1's output (a collective-permute under the pp sharding)."""
        out = jnp.concatenate([new[None], state[:-1]], axis=0)
        return lax.with_sharding_constraint(out, sp if sp is not None else spec)

    if pad:
        xs_x = jnp.concatenate(
            [xm, jnp.zeros((pad,) + xm.shape[1:], xm.dtype)])
        xs_tok = {k: jnp.concatenate(
            [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in tok.items()}
    else:
        xs_x, xs_tok = xm, tok

    init_x = jnp.zeros((pp, mb, s, h), x.dtype)
    init_x = lax.with_sharding_constraint(init_x, spec)
    init_tok = {k: jnp.zeros((pp, mb, s), v.dtype) for k, v in tok.items()}

    # stage s processes micro t-s at tick t; anything else is fill/drain
    # garbage whose aux (e.g. MoE router loss on zero activations) must NOT
    # reach the training loss
    aux_mask = jnp.asarray(gpipe_schedule_validity(pp, n_micro), jnp.float32)

    def step(carry, xs_t):
        state_x, state_tok = carry
        in_x, in_tok, mask_t = xs_t
        cur_x = shift_in(in_x, state_x)
        cur_tok = {k: shift_in(in_tok[k], state_tok[k], tok_spec)
                   for k in state_tok}
        args = (stage_params, cur_x, cur_tok)
        if stage_mask is not None:
            args = args + (stage_mask,)
        if stage_const is not None:
            args = args + (stage_const,)
        out = vbody(*args)
        if isinstance(out, tuple):
            out_x, aux = out                 # [pp, mb, s, h], [pp]
            aux = jnp.sum(aux * mask_t)
        else:
            out_x, aux = out, jnp.zeros((), jnp.float32)
        out_x = lax.with_sharding_constraint(out_x, spec)
        # collect the LAST stage's output (micro t-(pp-1) finishes at tick t)
        return (out_x, cur_tok), (out_x[-1], aux)

    _, (ys, auxs) = lax.scan(step, (init_x, init_tok),
                             (xs_x, xs_tok, aux_mask))
    outs = ys[pad:] if pad else ys          # [n_micro, mb, s, h]
    return outs.reshape(B, s, h), jnp.sum(auxs)


def _shard_map_stage_body(body, mesh, pp_axis: str, token_data: Dict,
                          has_mask: bool):
    """Wrap a per-stage body in `jax.shard_map` manual over ONLY the pp axis.

    Every other mesh axis (dp/cp/tp/...) stays automatic, so the body's own
    with_sharding_constraint calls keep composing via GSPMD.  Inside, the
    stage dim has local extent 1 (this device group's stage); `lax.cond`
    on per-stage values stays a real branch instead of vmap's `select`.
    """
    from jax.sharding import PartitionSpec
    Ppp = PartitionSpec(pp_axis)

    def manual(sp, x, tok, *mask_args):
        sp1 = jax.tree.map(lambda a: a[0], sp)
        tok1 = {k: v[0] for k, v in tok.items()}
        args = (sp1, x[0], tok1)
        if mask_args:
            args = args + (mask_args[0][0],)
        out = body(*args)
        if isinstance(out, tuple):
            ox, aux = out
        else:
            ox, aux = out, jnp.zeros((), jnp.float32)
        return ox[None], jnp.reshape(aux, (1,)).astype(jnp.float32)

    in_specs = (Ppp, Ppp, {k: Ppp for k in token_data})
    if has_mask:
        in_specs = in_specs + (Ppp,)
    return jax.shard_map(manual, mesh=mesh, in_specs=in_specs,
                         out_specs=(Ppp, Ppp),
                         axis_names=frozenset({pp_axis}))


def build_stage_stack(stack_params, num_layers: int, pp: int, stage_layers):
    """[L, ...] stacked layer params -> ([pp, max_k, ...] stage stacks,
    layer_mask [pp, max_k] or None, normalized stage_layers).

    Hetero (Malleus) layouts pad each stage to max_k with layer-0 copies and
    return the validity mask (padded slots are masked to identity by the
    stage body and receive exactly zero gradient through the mask's where)."""
    import numpy as np

    L = num_layers
    if stage_layers is None:
        if L % pp:
            raise ValueError(f"num_layers={L} must divide by pp={pp} "
                             "(or pass stage_layers)")
        stage_layers = [L // pp] * pp
    stage_layers = list(stage_layers)
    if len(stage_layers) != pp or sum(stage_layers) != L:
        raise ValueError(f"stage_layers={stage_layers} must have len pp={pp} "
                         f"and sum num_layers={L}")
    max_k = max(stage_layers)

    if all(k == max_k for k in stage_layers):
        stage_params = jax.tree.map(
            lambda a: a.reshape((pp, max_k) + a.shape[1:]), stack_params)
        return stage_params, None, stage_layers

    starts = np.cumsum([0] + stage_layers[:-1])
    idx = np.zeros((pp, max_k), np.int32)
    mask = np.zeros((pp, max_k), np.float32)
    for s_i, (st0, k) in enumerate(zip(starts, stage_layers)):
        idx[s_i, :k] = np.arange(st0, st0 + k)
        mask[s_i, :k] = 1.0
    idx_j = jnp.asarray(idx).reshape(-1)
    stage_params = jax.tree.map(
        lambda a: jnp.take(a, idx_j, axis=0).reshape(
            (pp, max_k) + a.shape[1:]), stack_params)
    return stage_params, jnp.asarray(mask), stage_layers


def unstack_stage_grads(d_stage, num_layers: int, pp: int, stage_layers):
    """Inverse of build_stage_stack for GRADIENTS: [pp, max_k, ...] -> [L, ...]
    (padded slots carry exactly-zero grads and are dropped)."""
    import numpy as np

    stage_layers = list(stage_layers)
    max_k = max(stage_layers)
    if all(k == max_k for k in stage_layers):
        return jax.tree.map(
            lambda a: a.reshape((num_layers,) + a.shape[2:]), d_stage)
    starts = np.cumsum([0] + stage_layers[:-1])
    flat_idx = np.concatenate(
        [s_i * max_k + np.arange(k)
         for s_i, (st0, k) in enumerate(zip(starts, stage_layers))])
    flat_idx = jnp.asarray(flat_idx, jnp.int32)
    return jax.tree.map(
        lambda a: jnp.take(a.reshape((pp * max_k,) + a.shape[2:]),
                           flat_idx, axis=0), d_stage)


def staged_stack_forward(block_fn, stack_params, x, *, num_layers: int,
                         pp: int, mesh, position_ids=None, segment_ids=None,
                         stage_layers=None, n_micro=None,
                         remat: bool = True, remat_policy: str = "nothing",
                         state_spec=None, hetero_exec="auto", rng=None):
    """Model-family-agnostic pipelined decoder stack.

    block_fn(layer_params, x_mb, position_ids_mb, segment_ids_mb) ->
    (x_mb, aux_scalar) applies ONE layer; the per-micro token riders are
    threaded by the pipeline (None stays None).
    stack_params: pytree with leading [num_layers, ...] dims.
    Handles equal and heterogeneous (Malleus) stage layer counts.  With
    hetero_exec (default "auto": on whenever stages are uneven) each stage
    runs under shard_map-over-pp and executes exactly its own layer count —
    padded slots are untaken `lax.cond` branches, so a Malleus layout
    actually saves the straggler's compute.  hetero_exec=False keeps the
    padded+masked vmap path (every stage pays max(stage_layers) per tick).

    rng: enables dropout INSIDE the pipeline.  Per-micro random bits ride
    the token stream (so each micro keeps its bits as it moves through the
    stages) and each stage folds in its GLOBAL layer index, giving every
    (micro, layer) pair an independent mask — the fold_in(stage, round)
    scheme the reference gets implicitly from per-op RNG states.  With rng,
    block_fn is called as block_fn(lp, x, pos, seg, rng=key).  Forces the
    padded vmap execution path (hetero_exec off).
    Returns (x, aux_total).
    """
    token_data = {}
    if position_ids is not None:
        token_data["position_ids"] = position_ids
    if segment_ids is not None:
        token_data["segment_ids"] = segment_ids

    if n_micro is None:
        n_micro = pp
    stage_params, layer_mask, stage_layers = build_stage_stack(
        stack_params, num_layers, pp, stage_layers)
    if hetero_exec == "auto":
        hetero_exec = layer_mask is not None
    hetero_exec = bool(hetero_exec) and layer_mask is not None

    stage_const = None
    if rng is not None:
        hetero_exec = False
        # ONE rider scheme shared with the 1F1B and hetero-TP paths
        # (build_dropout_ride), so the same rng draws the same masks in
        # every pipeline engine
        from hetu_tpu.parallel.pipeline_1f1b import build_dropout_ride
        rider, stage_const = build_dropout_ride(
            rng, n_micro, (x.shape[0], x.shape[1]), stage_layers)
        token_data = dict(token_data, dropout_rng=rider)

    has_mask = layer_mask is not None
    has_rng = rng is not None

    def stage_body(local_params, x_mb, tok, *extra):
        m = extra[0] if has_mask else None
        offset = extra[1 if has_mask else 0] if has_rng else None
        micro_key = (jax.random.key(tok["dropout_rng"][0, 0])
                     if has_rng else None)

        def _vary(v):
            # both cond branches must agree on varying-manual-axes typing
            # inside the shard_map-over-pp region; constants come out
            # unvarying, so promote them
            if not hetero_exec:
                return v
            from hetu_tpu.core.vma import cast_varying
            return cast_varying(v, ("pp",))

        def run_layer(layer_params, x_c, gid=None):
            kw = {}
            if has_rng:
                # (micro bits, global layer id) -> independent mask per
                # (micro, layer) — stage offset makes the id global
                kw["rng"] = jax.random.fold_in(micro_key, gid)
            out, aux = block_fn(layer_params, x_c,
                                tok.get("position_ids"),
                                tok.get("segment_ids"), **kw)
            return _vary(out), _vary(jnp.asarray(aux, jnp.float32))

        def body(carry, xs):
            if m is None:
                layer_params = xs
            else:
                layer_params, mj = xs
            if has_rng:
                x_c, aux_c, gid = carry
            else:
                x_c, aux_c = carry
                gid = None
            if m is not None and hetero_exec:
                # real branch (shard_map keeps it a conditional): a padded
                # slot costs nothing and its params get exactly-zero grads
                out, aux = lax.cond(
                    mj > 0, run_layer,
                    lambda _lp, x_: (_vary(x_),
                                     _vary(jnp.zeros((), jnp.float32))),
                    layer_params, x_c)
            else:
                out, aux = run_layer(layer_params, x_c, gid)
                if m is not None:
                    out = jnp.where(mj > 0, out, x_c)  # padded = identity
                    aux = aux * mj
            new_c = ((out, aux_c + aux, gid + 1) if has_rng
                     else (out, aux_c + aux))
            return new_c, None

        xs = local_params if m is None else (local_params, m)
        carry0 = (x_mb, _vary(jnp.zeros((), jnp.float32)))
        if has_rng:
            carry0 = carry0 + (offset,)
        out_carry, _ = lax.scan(body, carry0, xs)
        return out_carry[0], out_carry[1]

    return pipeline_apply(stage_body, stage_params, x, token_data,
                          n_micro=n_micro, mesh=mesh, remat=remat,
                          remat_policy=remat_policy, stage_mask=layer_mask,
                          state_spec=state_spec, hetero_exec=hetero_exec,
                          stage_const=stage_const)
