"""Fault-application helpers for the non-wire fault kinds:

* `ckpt_corrupt` — deterministic byte-level damage to an on-disk
  checkpoint step;
* `slow_worker` / `decode_stall` — per-step delay inflation at a
  training- or engine-step injection point (`maybe_slow_step`), the
  hardware-skew-free way to fake a straggling host / a decode-clock
  stall window;
* `engine_kill` / `reshard_storm` — the serving faults
  (`maybe_chaos_serving`): fail the engine over at a scheduled step
  (in-flight requests requeue under HETU_TPU_SERVE_RETRY) or pin the
  LoadAdaptiveMesh onto a flip-flopping tier for a window (exercising
  KV re-paging, HETU_TPU_SERVE_KV_REPAGE);
* `prefill_kill` — the disaggregated-tier fault
  (`maybe_chaos_disagg`): kill the prefill tier at a scheduled
  coordinator step (in-flight prefills are lost; decode replicas fall
  back to colocated chunked prefill for the down-window).  The
  shipment_* wire kinds are consulted by the shipment channel itself
  (`FaultPlan.shipment_fault`), not here.

Checkpoint-corruption details (the `ckpt_corrupt` fault kind):

Deterministic byte-level damage to an on-disk checkpoint step, used by the
chaos harness and tests to prove `restore_latest_valid()` walks back to
the newest checkpoint whose manifest verifies instead of crashing the
surviving cluster.

Target selection is seeded and size-biased: the largest file under the
step directory is the tensor data (where a torn write actually lands);
ties break lexicographically so the choice is stable across runs.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple


def maybe_slow_step(plan, rank: Optional[int], step: int) -> float:
    """Apply any scheduled `slow_worker` / `decode_stall` delay for
    (rank, step): sleeps the plan's per-step inflation and returns the
    seconds slept (0.0 when no plan / no matching spec — the identity
    hot path is one None check).  Call it at the top of a training step
    (slow_worker) or from the serving `on_step` hook (decode_stall)."""
    if plan is None:
        return 0.0
    delay = plan.step_delay(rank, step)
    if delay > 0:
        time.sleep(delay)
    return delay


def maybe_chaos_serving(plan, engine, step: int,
                        rank: Optional[int] = None) -> dict:
    """Apply the serving fault kinds for engine step `step` (the
    `on_step` hook of `ServingEngine.run`; no plan / nothing scheduled
    = one None check, zero side effects).  Returns what fired:
    ``{"killed": bool, "forced_tier": Optional[int]}``.

    * `engine_kill` — one-shot: `engine.fail_over()` requeues every
      in-flight request (retry budget HETU_TPU_SERVE_RETRY, stall
      reason `replica_lost`); seeded sampling then replays each
      survivor token-identically.
    * `reshard_storm` — each covered step pins the engine's
      LoadAdaptiveMesh onto tier ``offset % num_tiers``, so the next
      step's reshard hook fires a hot switch (and, with
      HETU_TPU_SERVE_KV_REPAGE, a KV re-page) regardless of load.
    """
    out = {"killed": False, "forced_tier": None}
    if plan is None:
        return out
    if plan.should_kill_engine(step, rank):
        engine.fail_over()
        out["killed"] = True
    off = plan.reshard_storm_offset(step, rank)
    if off is not None and getattr(engine, "reshard", None) is not None:
        tier = off % len(engine.reshard.tiers)
        engine.reshard.force_tier(tier)
        out["forced_tier"] = tier
    return out


def maybe_chaos_disagg(plan, coordinator, step: int,
                       rank: Optional[int] = None) -> dict:
    """Apply the disaggregated-tier fault kinds for coordinator step
    `step` (called from the coordinator's step loop; no plan = one None
    check).  Returns what fired:
    ``{"prefill_killed": bool, "prefill_down": bool}``.

    * `prefill_kill` — one-shot: `coordinator.kill_prefill_tier()`
      drops every in-flight prefill (their shipments never arrive, so
      the at-least-once timeout re-prefills each under the shipment
      retry budget).
    * the `prefill_down` window — while True, the coordinator routes
      new admissions through colocated chunked prefill on the decode
      tier (stall reason `prefill_tier_down`, metered as degraded-mode
      seconds) and auto-recovers when the window passes.
    """
    out = {"prefill_killed": False, "prefill_down": False}
    if plan is None:
        return out
    if plan.should_kill_prefill(step, rank):
        coordinator.kill_prefill_tier()
        out["prefill_killed"] = True
    out["prefill_down"] = plan.prefill_down(step, rank)
    return out


def _step_files(step_dir: str) -> List[Tuple[str, int]]:
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for fn in sorted(files):
            p = os.path.join(root, fn)
            out.append((p, os.path.getsize(p)))
    return sorted(out, key=lambda t: (-t[1], t[0]))


def corrupt_step(directory: str, step: int, mode: str = "flip",
                 seed: int = 0) -> str:
    """Damage checkpoint `step` under `directory`; returns the path hit.

    flip      XOR eight seeded byte positions (silent bit rot)
    truncate  cut the file to half length (a torn write / full disk)
    delete    remove the file entirely (a lost object / partial upload)
    """
    import random
    step_dir = os.path.join(directory, str(step))
    files = _step_files(step_dir)
    if not files:
        raise FileNotFoundError(f"no files under checkpoint step {step_dir}")
    path, size = files[0]
    rng = random.Random(seed)
    if mode == "flip":
        with open(path, "r+b") as f:
            for _ in range(8):
                pos = rng.randrange(max(size, 1))
                f.seek(pos)
                b = f.read(1)
                if not b:
                    continue
                f.seek(pos)
                f.write(bytes([b[0] ^ 0xFF]))
    elif mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode == "delete":
        os.remove(path)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return path


def newest_step(directory: str) -> Optional[int]:
    """Newest step number in a checkpoint root by directory name (pure
    filesystem scan — works without an open CheckpointManager)."""
    steps = []
    if not os.path.isdir(directory):
        return None
    for name in os.listdir(directory):
        if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            steps.append(int(name))
    return max(steps) if steps else None


def corrupt_latest(directory: str, mode: str = "flip",
                   seed: int = 0) -> Optional[int]:
    """Corrupt the newest checkpoint step; returns its number (None when
    the root holds no checkpoints yet)."""
    step = newest_step(directory)
    if step is None:
        return None
    corrupt_step(directory, step, mode=mode, seed=seed)
    return step
