"""Replayable chaos demo: a miniature multi-worker elastic run under a
seeded FaultPlan, with full recovery accounting.

Drives the REAL control plane — CoordinationServer/Client (reconnecting
wire layer), ElasticController (re-plan/rebuild/resume), CheckpointManager
(manifests + verified fallback) — around a deliberately model-free
StubTrainer, so a whole kill/partition/corrupt scenario runs in seconds
on CPU with no jax compile.  Used by tests/test_chaos.py (the acceptance
test) and tools_chaos.py (the replay CLI).

The StubTrainer's "model" is a counter pytree checkpointed through orbax:
real bytes on disk, real manifests, real fallback — only the math is fake.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from hetu_tpu import chaos
from hetu_tpu.chaos.inject import (corrupt_step, maybe_chaos_serving,
                                   maybe_slow_step, newest_step)
from hetu_tpu.chaos.plan import FaultPlan, FaultSpec
from hetu_tpu.obs.metrics import get_registry
from hetu_tpu.utils.logging import get_logger

logger = get_logger("chaos.harness")

#: counters the recovery report reconciles (summed across label sets)
_REPORT_COUNTERS = (
    "chaos.injected_rpc_drop", "chaos.injected_rpc_delay",
    "chaos.injected_rpc_dup", "chaos.injected_heartbeat_stall",
    "chaos.injected_worker_kill", "chaos.injected_ckpt_corrupt",
    "chaos.injected_slow_worker", "chaos.injected_engine_kill",
    "chaos.injected_reshard_storm", "chaos.injected_decode_stall",
    "chaos.injected_shipment_drop", "chaos.injected_shipment_dup",
    "chaos.injected_shipment_delay", "chaos.injected_prefill_kill",
    "rpc.disconnects", "rpc.reconnects", "rpc.reattaches",
    "rpc.heartbeat_lost", "rpc.workers_lost",
    "rpc.telemetry_pushes", "rpc.telemetry_push_failures",
    "cluster.telemetry_pushes", "cluster.telemetry_dup_pushes",
    "cluster.stragglers_flagged",
    "health.anomalies",
    "ckpt.fallbacks", "ckpt.quarantined", "ckpt.manifests_written",
    "elastic.replans", "elastic.step_failures", "elastic.emergency_saves",
    "elastic.recovery_attempts", "elastic.recovery_success",
    "elastic.restore_failures", "elastic.save_failures",
    "elastic.stragglers_persistent", "elastic.straggler_replans",
)


def _counter_totals(reg) -> Dict[str, float]:
    snap = reg.snapshot()
    out = {name: 0.0 for name in _REPORT_COUNTERS}
    for rec in snap["counters"]:
        if rec["name"] in out:
            out[rec["name"]] += rec["value"]
    return out


class StubTrainer:
    """Checkpoint-real, model-free trainer the ElasticController drives.

    Mirrors the real Trainer's telemetry surface when the observability
    flags ask for it: an optional per-slot RunLog (with the telemetry
    tail), the HETU_TPU_HEALTH HealthMonitor observing every step, and
    the chaos `slow_worker` per-step delay inflation (the fake
    straggling host the cluster straggler detector must catch)."""

    def __init__(self, ckpt_dir: Optional[str], plan: Dict,
                 chaos_plan: Optional[FaultPlan] = None,
                 rank: Optional[int] = None,
                 run_log=None):
        import numpy as np
        self.global_step = 0
        self._v = np.zeros(4, np.float64)
        self.plan = plan
        self._chaos = chaos_plan
        self._rank = rank
        self.run_log = run_log
        from hetu_tpu.obs.health import maybe_health_monitor
        self.health = maybe_health_monitor(runlog=run_log)
        self._ckpt = None
        if ckpt_dir:
            from hetu_tpu.utils.checkpoint import CheckpointManager
            self._ckpt = CheckpointManager(ckpt_dir, max_to_keep=8,
                                           async_save=False)

    def train_step(self, batch) -> Dict[str, float]:
        t0 = time.perf_counter()
        # the slow_worker injection point: a straggling host, faked as a
        # deterministic per-step sleep (identity when no plan/spec)
        maybe_slow_step(self._chaos, self._rank, self.global_step)
        self._v = self._v + 1.0
        self.global_step += 1
        metrics = {"loss": 1.0 / (1.0 + self.global_step)}
        step_s = time.perf_counter() - t0
        if self.run_log is not None:
            self.run_log.step(self.global_step, step_s,
                              loss=metrics["loss"])
        if self.health is not None:
            self.health.observe_step(self.global_step, step_s,
                                     loss=metrics["loss"])
        return metrics

    def save(self, wait: bool = False):
        assert self._ckpt is not None
        self._ckpt.save(self.global_step,
                        {"v": self._v, "step": self.global_step}, wait=True)

    def _target(self):
        # a fresh CheckpointManager (each generation builds one) can only
        # restore against an explicit target template
        import numpy as np
        return {"v": np.zeros_like(self._v), "step": 0}

    def restore(self, step: Optional[int] = None):
        import numpy as np
        assert self._ckpt is not None
        restored = self._ckpt.restore(step, target=self._target())
        self._v = np.asarray(restored["v"])
        self.global_step = int(restored["step"])
        return self

    def restore_latest_valid(self):
        import numpy as np
        assert self._ckpt is not None
        _step, restored = self._ckpt.restore_latest_valid(
            target=self._target())
        self._v = np.asarray(restored["v"])
        self.global_step = int(restored["step"])
        return self

    def close(self):
        if self._ckpt is not None:
            self._ckpt.close()


class _Killed(Exception):
    """Raised by a worker's batch stream when its worker_kill fires."""


def _run_worker(idx: int, port: int, plan: FaultPlan, ckpt_dir: str,
                num_steps: int, pace: float, expected_world: int,
                results: Dict[int, Dict[str, Any]],
                ckpt_every: int, recovery_budget: int):
    """One elastic worker: client + controller + chaos-aware batch
    stream.  Rank 0 (the leader in these demos) owns the checkpoint dir
    and applies any scheduled checkpoint corruption before its rebuilds —
    i.e. between the boundary save and the restore, exactly where a torn
    write lands in production."""
    from hetu_tpu.engine.elastic import ElasticController
    from hetu_tpu.rpc.client import CoordinationClient

    rec: Dict[str, Any] = {"rank": None, "generations": [],
                           "resumed_steps": [], "final_step": None,
                           "killed": False, "error": None}
    results[idx] = rec
    client = None
    run_log = None
    try:
        # per-slot RunLog (telemetry tail + anomaly events) only when the
        # observability flags ask for it — with both unset the harness
        # runs exactly as before (the flags-unset identity contract)
        from hetu_tpu.obs.aggregate import push_interval
        from hetu_tpu.utils import flags as _flags
        if push_interval() > 0 or _flags.bool_flag("HETU_TPU_HEALTH"):
            from hetu_tpu.obs.runlog import RunLog
            run_log = RunLog(
                os.path.join(os.path.dirname(ckpt_dir) or ".",
                             f"runlog_slot{idx}.jsonl"),
                tail_records=128)

        client = CoordinationClient("127.0.0.1", port,
                                    heartbeat_interval=0.1,
                                    op_timeout=10.0,
                                    max_reconnect_wait=20.0,
                                    info={"slot": idx})
        rec["rank"] = client.rank

        def factory(ds_plan):
            # the initial leader (rank 0) owns the shared checkpoint dir,
            # matching the reference's rank-0 saves; the RunLog is per
            # SLOT and survives trainer rebuilds (append-mode JSONL)
            return StubTrainer(ckpt_dir if client.rank == 0 else None,
                               ds_plan, chaos_plan=plan,
                               rank=client.rank, run_log=run_log)

        def planner(alive: List[int]) -> Dict:
            return {"strategy": {"dp": len(alive), "tp": 1, "pp": 1}}

        ctl = ElasticController(client, factory, planner,
                                expected_world=expected_world,
                                rendezvous_timeout=60.0,
                                recovery_budget=recovery_budget)

        orig_rebuild = ctl._rebuild

        def chaotic_rebuild():
            if client.rank == 0:
                step = newest_step(ckpt_dir)
                spec = plan.take_ckpt_corrupt(step)
                if spec is not None:
                    path = corrupt_step(ckpt_dir, step, mode=spec.mode,
                                        seed=plan.seed)
                    logger.warning(f"chaos: corrupted checkpoint step "
                                   f"{step} ({spec.mode}) at {path}")
            orig_rebuild()
            rec["generations"].append(ctl.generation)
            rec["resumed_steps"].append(ctl.trainer.global_step)

        ctl._rebuild = chaotic_rebuild

        def _ckpts_on_disk() -> int:
            try:
                return sum(1 for n in os.listdir(ckpt_dir) if n.isdigit())
            except OSError:
                return 0

        def batches():
            while True:
                time.sleep(pace)
                step = (ctl.trainer.global_step
                        if ctl.trainer is not None else 0)
                if plan.should_kill(client.rank, step):
                    # event-driven death: once scheduled, wait until the
                    # leader has >= 2 checkpoints on disk before dying, so
                    # a scheduled corruption of the newest step always
                    # leaves a prior VALID step to fall back to — the
                    # scenario's semantics are pinned instead of racing
                    # wall-clock against save latency
                    deadline = time.time() + 60.0
                    while _ckpts_on_disk() < 2 and time.time() < deadline:
                        time.sleep(0.02)
                    raise _Killed()
                yield {"x": 0}

        def cb(trainer, metrics):
            # the first two steps always checkpoint (fallback material for
            # the earliest possible kill), then every ckpt_every
            if trainer._ckpt is not None and \
                    (trainer.global_step <= 2 or
                     trainer.global_step % ckpt_every == 0):
                trainer.save(wait=True)

        trainer = ctl.run(batches(), num_steps, step_callback=cb)
        rec["final_step"] = trainer.global_step
        client.exit()
    except _Killed:
        rec["killed"] = True
        # simulate process death: stop beating AND tear the socket; the
        # server's reattach grace expires with nobody reattaching
        client._shutdown = True
        try:
            client._conn.close()
        except OSError:
            pass
    except Exception as e:   # surfaced in the report, not swallowed
        rec["error"] = repr(e)
        logger.error(f"worker slot {idx} failed: {e!r}")
    finally:
        if run_log is not None:
            run_log.close()


def run_chaos_demo(workdir: str, plan: FaultPlan, num_steps: int = 36,
                   workers: int = 2, pace: float = 0.04,
                   ckpt_every: int = 4, heartbeat_timeout: float = 0.6,
                   recovery_budget: int = 2) -> Dict[str, Any]:
    # defaults are tuned so a mid-run kill is DETECTED mid-run: loss
    # detection costs ~heartbeat_timeout+sweep, which at `pace` must land
    # well before the survivor finishes its num_steps
    """Run the demo elastic cluster under `plan`; returns the recovery
    report (per-worker outcomes, injected-fault summary, counter deltas,
    re-mesh latency percentiles).  Installs the plan process-globally for
    the duration of the run."""
    from hetu_tpu.rpc.server import CoordinationServer

    reg = get_registry()
    before = _counter_totals(reg)
    replan_before = reg.histogram("elastic.replan_s")
    replan_count0 = replan_before.count if replan_before else 0

    ckpt_dir = os.path.join(workdir, "ckpt")
    server = CoordinationServer(world_size=workers,
                                heartbeat_timeout=heartbeat_timeout)
    chaos.install(plan)
    results: Dict[int, Dict[str, Any]] = {}
    threads = []
    t0 = time.perf_counter()
    try:
        for idx in range(workers):
            t = threading.Thread(
                target=_run_worker,
                args=(idx, server.port, plan, ckpt_dir, num_steps, pace,
                      workers, results, ckpt_every, recovery_budget),
                daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120.0)
        wall_s = time.perf_counter() - t0
        # the coordinator's cluster view, captured BEFORE teardown: the
        # ClusterSnapshot over the whole run window plus the straggler
        # report (empty workers when telemetry push was off)
        cluster = server.cluster_snapshot(window_s=max(wall_s * 2, 60.0))
        straggler = server.telemetry.straggler_report(cluster)
    finally:
        chaos.reset()
        server.close()

    after = _counter_totals(reg)
    deltas = {k: after[k] - before[k] for k in _REPORT_COUNTERS
              if after[k] != before[k]}
    replan_h = reg.histogram("elastic.replan_s")
    replan = None
    if replan_h is not None and replan_h.count > replan_count0:
        replan = {"count": replan_h.count - replan_count0,
                  "p50_s": replan_h.percentile(50),
                  "p95_s": replan_h.percentile(95),
                  "max_s": replan_h.vmax}
    return {
        "wall_s": round(wall_s, 3),
        "num_steps": num_steps,
        "workers": {i: results.get(i) for i in range(workers)},
        "injected": plan.summary(),
        "metrics": deltas,
        "replan_s": replan,
        "cluster": cluster,
        "straggler": straggler,
        "completed": all(
            r and (r["final_step"] is not None and
                   r["final_step"] >= num_steps or r["killed"])
            for r in results.values()),
    }


def run_serving_chaos_demo(workdir: str, plan: FaultPlan, *,
                           requests: int = 18, rate: float = 60.0,
                           burst: int = 6, num_slots: int = 2,
                           num_pages: int = 10, preempt: bool = False,
                           retry_budget: int = 0,
                           deadline_s: Optional[float] = None,
                           brownout: bool = False,
                           brownout_page_high: float = 0.95,
                           brownout_streak: int = 4,
                           seed: int = 0) -> Dict[str, Any]:
    """The serving chaos scenario (the PR 7 follow-up): a seeded
    burst-arrival trace through the REAL continuous-batching engine
    (tiny llama on CPU) while the plan's ``slow_worker`` spec inflates
    engine steps — a decode slowdown under bursty load.  Two SLO classes
    ride the trace (``gold`` with tight targets, ``bulk`` uncontracted),
    the flight recorder traces every request, and the serving health
    detectors watch the run.

    The recovery report carries the per-class SLO attainment / goodput /
    stall-attribution sections from `serving/slo_report.py` — the same
    report path `tools_serving_report.py` renders — plus the injected
    summary and fired-detector counts, so "what did the slowdown cost,
    and who paid" is answerable per class.

    ``preempt=True`` (the ``serve-preempt`` schedule) additionally runs
    SLO-class-aware preemptive admission with the gold class at
    priority 2: when the decode slowdown piles bulk decodes onto every
    slot, arriving gold requests evict-and-requeue the bulk occupants —
    the report's `preemptions` section shows who was bumped, and gold's
    attainment holds while bulk pays.

    The serving FAULT kinds ride the same hook (`maybe_chaos_serving`):
    an ``engine_kill`` spec fails the engine over mid-run — with
    ``retry_budget`` > 0 (the ``serve-failover`` schedule) every
    in-flight request requeues under the ``replica_lost`` stall reason
    and replays token-identically — and a ``reshard_storm`` spec forces
    hot tier flips.  ``deadline_s`` arms the bulk class's deadline and
    ``brownout=True`` (the ``serve-brownout`` schedule) arms
    sustained-pressure shedding; the recovery report then carries the
    failover/deadline/brownout sections (retry counts, per-class
    attainment) from `serving/slo_report.py`."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu import serving
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.obs.health import ServingHealthMonitor
    from hetu_tpu.obs.metrics import MetricsRegistry
    from hetu_tpu.obs.runlog import RunLog
    from hetu_tpu.serving import slo_report

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32,
                           use_flash_attention=False)
    model = LlamaLMHeadModel(cfg)
    params = model.init(jax.random.key(seed))

    classes = [serving.SLOClass("gold", ttft_s=0.5, token_gap_s=0.25,
                                priority=2 if preempt or brownout else 0),
               serving.SLOClass("bulk", deadline_s=deadline_s)]
    arrivals = serving.bursty_arrivals(requests, rate, burst=burst,
                                       seed=seed)
    reqs = serving.synthetic_requests(
        requests, vocab_size=cfg.vocab_size, prompt_lens=(3, 16),
        max_new=(3, 8), arrivals=arrivals, slo_classes=classes, seed=seed)

    registry = MetricsRegistry()
    log_path = os.path.join(workdir, "serve_chaos.jsonl")
    run_log = RunLog(log_path)
    tracer = serving.RequestTracer(run_log=run_log, registry=registry)
    health = ServingHealthMonitor(runlog=run_log, registry=registry,
                                  warmup=3, cooldown_steps=4)
    eng = serving.ServingEngine(
        model, params,
        serving.ServeConfig(num_slots=num_slots, page_size=8, max_len=32,
                            prefill_chunk=8, num_pages=num_pages,
                            preempt=preempt, retry_budget=retry_budget,
                            deadline=deadline_s is not None,
                            brownout=brownout,
                            brownout_page_high=brownout_page_high,
                            brownout_streak=brownout_streak),
        registry=registry, run_log=run_log, tracer=tracer, health=health)
    eng.warmup()

    # the engine's own run() loop with the chaos injections hooked at
    # each step boundary (inside the timed window): the slow/stall
    # sleep inflates the virtual clock exactly like a straggling decode
    # step would, and the serving fault kinds (engine_kill,
    # reshard_storm) fire through maybe_chaos_serving
    def _on_step(idx: int):
        maybe_slow_step(plan, 0, idx)
        maybe_chaos_serving(plan, eng, idx, rank=0)

    results = eng.run(reqs, on_step=_on_step)
    run_log.close()

    records = RunLog.read(log_path)
    report = slo_report.serving_report(records)
    snap = registry.snapshot()
    detectors = {r["name"]: r["value"] for r in snap["counters"]
                 if r["name"].startswith("health.")}
    reasons: Dict[str, int] = {}
    for r in results:
        reasons[r.finished_reason] = reasons.get(r.finished_reason, 0) + 1
    fault_names = ("serve.failovers", "serve.replica_requeues",
                   "serve.retry_exhausted", "serve.deadline_exceeded",
                   "serve.brownout_shed", "serve.kv_repages",
                   "serve.reshards")
    faults = {}
    for rec in snap["counters"]:
        if rec["name"] in fault_names:
            faults[rec["name"]] = faults.get(rec["name"], 0) \
                + rec["value"]
    return {
        "completed": len(results) == len(reqs),
        "requests": len(results),
        "engine_steps": eng.steps_done,
        "injected": plan.summary(),
        "detectors": detectors,
        "preemptions": eng.scheduler.preempted,
        "finished_reasons": dict(sorted(reasons.items())),
        "faults": faults,
        "slo": report,
        "runlog": log_path,
    }


def run_disagg_chaos_demo(workdir: str, plan: FaultPlan, *,
                          requests: int = 16, rate: float = 60.0,
                          burst: int = 6, num_slots: int = 2,
                          retry_budget: int = 3,
                          ship_timeout: int = 4, ship_retry: int = 2,
                          ship_quant: str = "none",
                          fallback: bool = True,
                          seed: int = 0) -> Dict[str, Any]:
    """The ``disagg-storm`` scenario: a burst-arrival trace through the
    REAL disaggregated pair — a PrefillWorker tier feeding a decode
    ServingEngine over the acked at-least-once shipment channel
    (serving/disagg.py, tiny llama on CPU) — while the plan's
    ``shipment_drop``/``shipment_dup``/``shipment_delay`` kinds mangle
    the wire and its ``prefill_kill`` specs drop the tier mid-run.

    Every request that survives to ``length``/``eos`` must be
    TOKEN-IDENTICAL to the single-engine colocated run of the same
    trace (the report carries the check): re-sent shipments dedupe on
    seq, lost ones re-prefill under the retry budget, and a dead tier
    degrades to colocated chunked prefill (stall reason
    ``prefill_tier_down``) until the down-window passes.  The recovery
    report carries the shipment/degraded counters plus the per-class
    SLO sections from `serving/slo_report.py`."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu import serving
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.obs.metrics import MetricsRegistry
    from hetu_tpu.obs.runlog import RunLog
    from hetu_tpu.serving import slo_report
    from hetu_tpu.serving.disagg import DisaggCoordinator, PrefillWorker

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32,
                           use_flash_attention=False)
    model = LlamaLMHeadModel(cfg)
    params = model.init(jax.random.key(seed))
    classes = [serving.SLOClass("gold", ttft_s=0.5, priority=2),
               serving.SLOClass("bulk")]

    def _reqs():
        arrivals = serving.bursty_arrivals(requests, rate, burst=burst,
                                           seed=seed)
        return serving.synthetic_requests(
            requests, vocab_size=cfg.vocab_size, prompt_lens=(3, 16),
            max_new=(3, 8), arrivals=arrivals, slo_classes=classes,
            seed=seed)

    def _cfg(**kw):
        return serving.ServeConfig(num_slots=num_slots, page_size=8,
                                   max_len=32, prefill_chunk=8, **kw)

    # the colocated golden: same trace, one engine, no tiers
    base = serving.ServingEngine(model, params, _cfg(),
                                 registry=MetricsRegistry())
    gold = {r.rid: r.tokens for r in base.run(_reqs())}

    registry = MetricsRegistry()
    log_path = os.path.join(workdir, "disagg_chaos.jsonl")
    run_log = RunLog(log_path)
    tracer = serving.RequestTracer(run_log=run_log, registry=registry)
    decode = serving.ServingEngine(
        model, params, _cfg(retry_budget=retry_budget),
        registry=registry, run_log=run_log, tracer=tracer)
    worker = PrefillWorker(model, params, prefill_chunk=8, max_len=32,
                           registry=registry)
    coord = DisaggCoordinator(worker, decode, plan=plan,
                              ship_timeout=ship_timeout,
                              ship_retry=ship_retry,
                              ship_quant=ship_quant, fallback=fallback)
    results = coord.run(_reqs())
    run_log.close()

    reasons: Dict[str, int] = {}
    mismatches = []
    for r in results:
        reasons[r.finished_reason] = reasons.get(r.finished_reason, 0) + 1
        if r.finished_reason in ("length", "eos") \
                and r.tokens != gold.get(r.rid):
            mismatches.append(r.rid)
    snap = registry.snapshot()
    names = ("serve.ship_sent", "serve.ship_acked",
             "serve.ship_dedups", "serve.ship_resends",
             "serve.disagg_reprefills", "serve.colocated_prefills",
             "serve.prefill_tier_kills", "serve.degraded_entries",
             "serve.retry_exhausted", "serve.tier_prefill_chunks")
    faults: Dict[str, float] = {}
    for rec in snap["counters"]:
        if rec["name"] in names or rec["name"].startswith("chaos."):
            faults[rec["name"]] = faults.get(rec["name"], 0) \
                + rec["value"]
    return {
        "completed": len(results) == requests,
        "requests": len(results),
        "token_identical": not mismatches,
        "mismatched_rids": mismatches,
        "injected": plan.summary(),
        "finished_reasons": dict(sorted(reasons.items())),
        "faults": faults,
        "disagg": coord.summary(),
        "slo": slo_report.serving_report(RunLog.read(log_path)),
        "runlog": log_path,
    }


def run_frontend_chaos_demo(workdir: str, plan: FaultPlan, *,
                            requests: int = 16, rate: float = 60.0,
                            burst: int = 6, replicas: int = 2,
                            num_slots: int = 2, retry_budget: int = 2,
                            hedge_after: int = 0,
                            seed: int = 0) -> Dict[str, Any]:
    """The ``frontend-partition`` scenario: the multi-replica frontend
    (serving/frontend.py) routing a burst trace over N real engines
    while the plan's ``engine_kill`` windows partition replicas away
    mid-run.  The frontend detects each death from the health digest,
    fails the replica over, drains its queue and reroutes every pulled
    request to the survivors; rejoin happens when the window passes.
    Survivors must be token-identical to the single-engine run (decode
    math is row-independent, so the replica a request lands on never
    changes its stream).  With ``hedge_after`` > 0 stuck queued
    requests are hedged to a second replica and the duplicate result
    is deduped by rid."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu import serving
    from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
    from hetu_tpu.obs.metrics import MetricsRegistry
    from hetu_tpu.obs.runlog import RunLog
    from hetu_tpu.serving import slo_report
    from hetu_tpu.serving.frontend import Frontend

    cfg = LlamaConfig.tiny(remat=False, compute_dtype=jnp.float32,
                           use_flash_attention=False)
    model = LlamaLMHeadModel(cfg)
    params = model.init(jax.random.key(seed))
    classes = [serving.SLOClass("gold", ttft_s=0.5, priority=2),
               serving.SLOClass("bulk")]

    def _reqs():
        arrivals = serving.bursty_arrivals(requests, rate, burst=burst,
                                           seed=seed)
        return serving.synthetic_requests(
            requests, vocab_size=cfg.vocab_size, prompt_lens=(3, 16),
            max_new=(3, 8), arrivals=arrivals, slo_classes=classes,
            seed=seed)

    def _cfg(**kw):
        return serving.ServeConfig(num_slots=num_slots, page_size=8,
                                   max_len=32, prefill_chunk=8, **kw)

    base = serving.ServingEngine(model, params, _cfg(),
                                 registry=MetricsRegistry())
    gold = {r.rid: r.tokens for r in base.run(_reqs())}

    registry = MetricsRegistry()
    log_path = os.path.join(workdir, "frontend_chaos.jsonl")
    run_log = RunLog(log_path)
    engines = [serving.ServingEngine(
        model, params, _cfg(retry_budget=retry_budget),
        registry=registry, run_log=run_log if i == 0 else None)
        for i in range(replicas)]
    fe = Frontend(engines, plan=plan, hedge_after=hedge_after,
                  registry=registry)
    results = fe.run(_reqs())
    run_log.close()

    reasons: Dict[str, int] = {}
    mismatches = []
    for r in results:
        reasons[r.finished_reason] = reasons.get(r.finished_reason, 0) + 1
        if r.finished_reason in ("length", "eos") \
                and r.tokens != gold.get(r.rid):
            mismatches.append(r.rid)
    snap = registry.snapshot()
    faults: Dict[str, float] = {}
    for rec in snap["counters"]:
        if rec["name"].startswith(("chaos.", "serve.frontend",
                                   "serve.hedge", "serve.failovers",
                                   "serve.replica_requeues",
                                   "serve.retry_exhausted")):
            faults[rec["name"]] = faults.get(rec["name"], 0) \
                + rec["value"]
    return {
        "completed": len(results) == requests,
        "requests": len(results),
        "token_identical": not mismatches,
        "mismatched_rids": mismatches,
        "injected": plan.summary(),
        "finished_reasons": dict(sorted(reasons.items())),
        "faults": faults,
        "frontend": fe.summary(),
        "replicas": fe.digests(),
        "slo": slo_report.serving_report(RunLog.read(log_path)),
        "runlog": log_path,
    }


def run_fleet_chaos_demo(workdir: str, plan: FaultPlan, *,
                         requests: int = 5000, rate: float = 2000.0,
                         burst: int = 16, num_slots: int = 16,
                         seed: int = 0) -> Dict[str, Any]:
    """The ``fleet-storm`` scenario: a bursty MULTI-TENANT arrival storm
    through the fleet simulator (serving/fleet.py — the real scheduler/
    page-pool/quota machinery under an analytic clock, no model, no
    device) while the plan's ``slow_worker`` windows inflate the modeled
    step time, exactly like the live engine's on_step sleep inflates its
    wall clock.  Three tenants ride the storm — ``acme`` (gold-classed,
    preemption-armed), ``bigco`` (bulk) and ``free`` (bulk, quota-capped
    at a few slots/pages) — so the recovery report answers what the
    slowdown cost PER TENANT: attainment/goodput from the simulator's
    exact ledger plus the sampled-RunLog view through
    `serving/slo_report.py` (they must agree; the fleet tests pin it).

    Hardware-free and fast: tens of thousands of requests cost seconds,
    so this is the chaos schedule that can afford fleet-scale load."""
    from hetu_tpu.obs.runlog import RunLog
    from hetu_tpu.serving import slo_report
    from hetu_tpu.serving.fleet import (FleetConfig, FleetSimulator,
                                        analytic_models, fleet_workload)
    from hetu_tpu.serving.request import SLOClass, parse_quotas

    classes = [SLOClass("gold", ttft_s=0.05, token_gap_s=0.02,
                        priority=2),
               SLOClass("bulk"), SLOClass("bulk")]
    reqs = fleet_workload(requests, rate_per_s=rate, burst=burst,
                          tenants=("acme", "bigco", "free"),
                          slo_classes=classes, prompt_lens=(8, 48),
                          max_new=(4, 16), seed=seed)
    svc, cost = analytic_models(num_params=1e9, num_layers=16,
                                hidden_size=2048, num_kv_heads=8,
                                head_dim=128, page_size=16)
    cfg = FleetConfig(num_slots=num_slots, page_size=16, max_len=128,
                      prefill_chunk=32, preempt=True,
                      quotas=parse_quotas("free:2:16"))
    log_path = os.path.join(workdir, "fleet_chaos.jsonl")
    run_log = RunLog(log_path)
    sim = FleetSimulator(svc, config=cfg, cost_model=cost,
                         run_log=run_log, fault_plan=plan)
    fleet = sim.run(reqs)
    run_log.close()
    slo = slo_report.serving_report(RunLog.read(log_path))
    return {
        "completed": fleet["completed"] == len(reqs),
        "requests": fleet["completed"],
        "sim_steps": fleet["steps"],
        "injected": plan.summary(),
        "fleet": fleet,
        "slo": slo,
        "runlog": log_path,
    }


# ------------------------------------------------------------ schedules
def named_plan(name: str, **kw) -> FaultPlan:
    """Built-in schedules for the replay CLI and the acceptance test."""
    if name == "kill-partition-corrupt":
        # the acceptance scenario: one worker dies mid-run, the leader's
        # control-plane link drops a window of heartbeats, and the newest
        # checkpoint is corrupted before the post-kill restore
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="worker_kill", rank=1, at_step=4),
            FaultSpec(kind="rpc_drop", op="heartbeat", rank=0,
                      after_calls=6, count=2),
            FaultSpec(kind="ckpt_corrupt", at_step=1, mode="flip"),
        ])
    if name == "partition":
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="rpc_drop", op="heartbeat", rank=0,
                      after_calls=5, count=4),
        ])
    if name == "corrupt":
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="worker_kill", rank=1, at_step=5),
            FaultSpec(kind="ckpt_corrupt", at_step=1,
                      mode=kw.get("mode", "truncate")),
        ])
    if name == "slow":
        # a persistent straggler: one rank's steps inflate by delay_s
        # from at_step on — the cluster straggler detector (telemetry
        # push + aggregate.straggler_report) must flag it; pair with
        # HETU_TPU_TELEMETRY_PUSH / HETU_TPU_HEALTH
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="slow_worker", rank=kw.get("rank", 1),
                      at_step=kw.get("at_step", 6),
                      count=kw.get("count", 10_000),
                      delay_s=kw.get("delay_s", 0.15)),
        ])
    if name == "serve-burst":
        # the serving scenario (run_serving_chaos_demo): a burst-arrival
        # trace with a slow-decode window injected mid-run — per-class
        # SLO attainment shows who the slowdown cost
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="slow_worker", rank=0,
                      at_step=kw.get("at_step", 8),
                      count=kw.get("count", 12),
                      delay_s=kw.get("delay_s", 0.25)),
        ])
    if name == "serve-preempt":
        # serve-burst with SLO-class preemption armed
        # (run_serving_chaos_demo(preempt=True)): the slow-decode window
        # pins bulk decodes on every slot, so arriving gold (priority 2)
        # requests must evict-and-requeue them — the report's
        # preemptions section names the victims
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="slow_worker", rank=0,
                      at_step=kw.get("at_step", 4),
                      count=kw.get("count", 16),
                      delay_s=kw.get("delay_s", 0.25)),
        ])
    if name == "serve-failover":
        # the failover scenario (run_serving_chaos_demo with
        # retry_budget > 0): the engine replica dies mid-decode; every
        # in-flight request requeues under its retry budget
        # (stall reason replica_lost), re-prefills against the warm
        # radix cache and replays its exact token stream — the report's
        # failover section carries requeue/retry counts per class
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="engine_kill", rank=0,
                      at_step=kw.get("at_step", 6)),
        ])
    if name == "serve-brownout":
        # the brownout scenario (run_serving_chaos_demo with
        # brownout=True and a tight page pool): a decode-stall window
        # piles queued bulk work onto sustained page pressure until the
        # shed policy fires — the report's brownout section names the
        # shed class and HETU_TPU_HEALTH meters brownout_shed anomalies
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="decode_stall", rank=0,
                      at_step=kw.get("at_step", 3),
                      count=kw.get("count", 12),
                      delay_s=kw.get("delay_s", 0.2)),
        ])
    if name == "fleet-storm":
        # the fleet scenario (run_fleet_chaos_demo): a multi-tenant
        # burst storm through the discrete-event fleet simulator with a
        # slow-service window — step_delay() inflates the MODELED step
        # time (no wall sleep), so the per-tenant attainment/goodput/cost
        # report shows who paid for the slowdown at fleet scale
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="slow_worker", rank=0,
                      at_step=kw.get("at_step", 50),
                      count=kw.get("count", 200),
                      delay_s=kw.get("delay_s", 0.02)),
        ])
    if name == "disagg-storm":
        # the disaggregated scenario (run_disagg_chaos_demo): the
        # prefill->decode shipment wire drops, duplicates and delays
        # KV shipments while two prefill_kill specs drop the tier —
        # once one-shot, once with a down-window long enough that new
        # arrivals degrade to colocated chunked prefill.  Survivors
        # stay token-identical to the colocated run (the report pins
        # it); the dedupe/resend/re-prefill counters account for every
        # mangled shipment.
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="shipment_drop", op="ship",
                      after_calls=kw.get("after_calls", 1), count=2,
                      prob=1.0),
            FaultSpec(kind="shipment_dup", op="ship", after_calls=4,
                      count=2, prob=1.0),
            FaultSpec(kind="shipment_delay", op="ship", after_calls=7,
                      count=2, prob=1.0,
                      delay_s=kw.get("delay_s", 2.0)),
            FaultSpec(kind="shipment_drop", op="ack", after_calls=2,
                      count=2, prob=1.0),
            FaultSpec(kind="prefill_kill",
                      at_step=kw.get("at_step", 6)),
            FaultSpec(kind="prefill_kill", at_step=9,
                      count=kw.get("count", 4)),
        ])
    if name == "frontend-partition":
        # the frontend scenario (run_frontend_chaos_demo): replica 1
        # partitions away for a window mid-run — the frontend's health
        # check fails it over, drains its queue onto the survivors and
        # rejoins it when the window passes; survivors replay
        # token-identically under the retry budget
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="engine_kill", rank=kw.get("rank", 1),
                      at_step=kw.get("at_step", 3),
                      count=kw.get("count", 4)),
        ])
    if name == "stall":
        # a heartbeat stall longer than the server timeout: the classic
        # long-XLA-compile false positive — the stalled worker is declared
        # dead and must NOT resurrect into the old mesh
        return FaultPlan(seed=kw.get("seed", 0), faults=[
            FaultSpec(kind="heartbeat_stall", rank=1, at_beat=8,
                      stall_s=kw.get("stall_s", 2.5)),
        ])
    raise ValueError(f"unknown schedule {name!r}; known: "
                     "kill-partition-corrupt, partition, corrupt, stall, "
                     "slow, serve-burst, serve-preempt, serve-failover, "
                     "serve-brownout, fleet-storm, disagg-storm, "
                     "frontend-partition")
