"""Deterministic fault injection for the elastic control plane.

One process-global `FaultPlan` (or None — the default, meaning chaos is
OFF and every injection point is identity).  The plan resolves lazily
from the `HETU_TPU_CHAOS=<schedule.json>` flag on first query, or is set
programmatically with `install()` in tests and the chaos harness:

    from hetu_tpu import chaos
    plan = chaos.get_plan()          # None unless a schedule is active
    chaos.install(chaos.FaultPlan([...], seed=0))
    chaos.reset()                    # back to flag-resolved / off

With no plan installed and HETU_TPU_CHAOS unset, `get_plan()` is a single
attribute read returning None — the rpc wire layer and heartbeat loop pay
nothing.  See docs/fault_tolerance.md for the schedule format and
hetu_tpu/chaos/harness.py for the replayable demo run.
"""
from __future__ import annotations

import threading
from typing import Optional

from hetu_tpu.chaos.inject import (corrupt_latest,  # noqa: F401
                                   corrupt_step, maybe_chaos_serving,
                                   maybe_slow_step, newest_step)
from hetu_tpu.chaos.plan import (CORRUPT_MODES, KINDS,  # noqa: F401
                                 FaultPlan, FaultSpec)

_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_resolved = False


def get_plan() -> Optional[FaultPlan]:
    """The active FaultPlan, or None (chaos off — the identity path).
    Resolves HETU_TPU_CHAOS once per process; `install()`/`reset()`
    override."""
    global _plan, _resolved
    if _plan is not None or _resolved:
        return _plan
    with _lock:
        if _resolved or _plan is not None:
            return _plan
        from hetu_tpu.utils import flags
        path = flags.str_flag("HETU_TPU_CHAOS")
        if path:
            _plan = FaultPlan.load(path)
        _resolved = True
    return _plan


def install(plan: FaultPlan):
    """Activate a plan for this process (tests / the chaos harness)."""
    global _plan, _resolved
    with _lock:
        _plan = plan
        _resolved = True


def reset():
    """Deactivate chaos; the next get_plan() re-reads HETU_TPU_CHAOS."""
    global _plan, _resolved
    with _lock:
        _plan = None
        _resolved = False


__all__ = ["FaultPlan", "FaultSpec", "KINDS", "CORRUPT_MODES",
           "get_plan", "install", "reset",
           "corrupt_step", "corrupt_latest", "newest_step",
           "maybe_slow_step", "maybe_chaos_serving"]
