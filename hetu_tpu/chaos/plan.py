"""Deterministic fault-injection schedules.

The chaos subsystem makes the elastic recovery paths *provokable*: a
`FaultPlan` is a seeded, replayable schedule of faults the runtime layers
consult at well-defined injection points —

    rpc_drop / rpc_delay / rpc_dup   the client's wire layer (one
                                     request/response exchange) — a lost,
                                     slow, or duplicated message
    heartbeat_stall                  the client heartbeat loop — mimics a
                                     long GIL-pinned XLA compile that
                                     starves the beat thread
    worker_kill                      the chaos harness — a worker dies at
                                     a given training step
    ckpt_corrupt                     the chaos harness — flip/truncate
                                     bytes in the newest checkpoint before
                                     a restore
    slow_worker                      the training step — deterministic
                                     per-step delay inflation on a target
                                     rank (a straggling host, faked), so
                                     the cluster straggler detector is
                                     testable without real hardware skew
    engine_kill                      the serving engine/fleet replica —
                                     dies at a given ENGINE step: every
                                     in-flight request loses its slot and
                                     re-enters the queue under the
                                     HETU_TPU_SERVE_RETRY budget
                                     (docs/fault_tolerance.md); `rank`
                                     selects the fleet replica
    reshard_storm                    the serving reshard hook — forces a
                                     LoadAdaptiveMesh tier flip every step
                                     of a window, exercising KV re-paging
                                     under repeated hot switches
    decode_stall                     the serving engine step — the
                                     slow_worker shape on the decode
                                     clock: a deterministic per-step
                                     delay window (a compile storm, a
                                     straggling reshard, faked)
    shipment_drop / shipment_dup /   the disaggregated prefill→decode KV
    shipment_delay                   shipment wire (one ship or ack
                                     exchange; op "ship" | "ack" | "*")
                                     — a lost, duplicated, or delayed
                                     shipment the at-least-once protocol
                                     must absorb (docs/serving.md,
                                     "Disaggregated serving")
    prefill_kill                     the prefill tier — dies at a given
                                     COORDINATOR step: in-flight
                                     prefills are lost (their shipments
                                     never arrive → timeout →
                                     re-prefill), and decode replicas
                                     fall back to colocated chunked
                                     prefill for the spec's
                                     ``count``-step down-window

Everything is deterministic given the plan: trigger windows are counted in
*matching calls* (not wall time), and probabilistic faults draw from one
`random.Random(seed)` stream, so the same plan against the same run
injects the same faults.  Every injection increments a
`chaos.injected_<kind>` counter in the metrics registry, which is what the
acceptance tests reconcile against the `elastic.recovery_*` /
`ckpt.fallbacks` / `rpc.reconnects` accounting on the observation side.

Plans load from JSON (the `HETU_TPU_CHAOS=<schedule.json>` flag — see
docs/fault_tolerance.md) or are built programmatically in tests.  This
module is stdlib-only: importing it from the rpc hot path costs nothing.
"""
from __future__ import annotations

import dataclasses
import json
import random
import threading
from typing import Any, Dict, List, Optional

KINDS = ("rpc_drop", "rpc_delay", "rpc_dup",
         "heartbeat_stall", "worker_kill", "ckpt_corrupt", "slow_worker",
         "engine_kill", "reshard_storm", "decode_stall",
         "shipment_drop", "shipment_dup", "shipment_delay",
         "prefill_kill")
_WIRE_KINDS = ("rpc_drop", "rpc_delay", "rpc_dup")
_SHIP_KINDS = ("shipment_drop", "shipment_dup", "shipment_delay")
CORRUPT_MODES = ("flip", "truncate", "delete")


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault.  Schedule fields (set by the plan author):

    kind         one of KINDS
    op           rpc op pattern for rpc_* kinds; shipment op pattern
                 ("ship" | "ack") for shipment_* kinds ("*" = any op)
    rank         restrict to one client rank (None = any rank)
    after_calls  skip this many matching calls before firing (rpc_* /
                 heartbeat_stall: matching beats via at_beat instead)
    count        fire on this many consecutive matching calls (a window —
                 count > 1 models a partition that eats several messages)
    prob         per-match firing probability (drawn from the plan's
                 seeded stream — deterministic)
    delay_s      rpc_delay / shipment_delay: added latency per fired
                 call (shipment_delay: virtual seconds the delivery is
                 deferred by)
    at_step      worker_kill / ckpt_corrupt: trigger once the observed
                 training step reaches this value; slow_worker /
                 decode_stall: first slowed step (with `count` following
                 steps slowed and `delay_s` added per step);
                 engine_kill: the engine step the replica dies at;
                 prefill_kill: the coordinator step the prefill tier
                 dies at (`count` steps of down-window before rejoin);
                 reshard_storm: first stormed engine step (`count`
                 steps force a tier flip each)
    at_beat      heartbeat_stall: fire at this beat index
    stall_s      heartbeat_stall: how long the beat thread freezes
    mode         ckpt_corrupt: flip | truncate | delete

    Runtime bookkeeping (never set by the author): seen, injected, done.
    """
    kind: str
    op: str = "*"
    rank: Optional[int] = None
    after_calls: int = 0
    count: int = 1
    prob: float = 1.0
    delay_s: float = 0.0
    at_step: Optional[int] = None
    at_beat: Optional[int] = None
    stall_s: float = 0.0
    mode: str = "flip"
    seen: int = 0
    injected: int = 0
    done: bool = False

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"known: {KINDS}")
        if self.kind == "ckpt_corrupt" and self.mode not in CORRUPT_MODES:
            raise ValueError(f"unknown ckpt_corrupt mode {self.mode!r}; "
                             f"known: {CORRUPT_MODES}")
        if not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")


def _reg():
    from hetu_tpu.obs.metrics import get_registry
    return get_registry()


class FaultPlan:
    """A seeded schedule of FaultSpecs with thread-safe trigger state."""

    _SCHEDULE_FIELDS = ("kind", "op", "rank", "after_calls", "count",
                        "prob", "delay_s", "at_step", "at_beat",
                        "stall_s", "mode")

    def __init__(self, faults: List[FaultSpec], seed: int = 0):
        self.seed = int(seed)
        self.faults = list(faults)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    # ------------------------------------------------------------ loading
    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultPlan":
        faults = []
        for f in d.get("faults", []):
            unknown = set(f) - set(cls._SCHEDULE_FIELDS)
            if unknown:
                raise ValueError(
                    f"unknown fault fields {sorted(unknown)} in {f!r}; "
                    f"known: {cls._SCHEDULE_FIELDS}")
            faults.append(FaultSpec(**f))
        return cls(faults, seed=int(d.get("seed", 0)))

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "faults": [
            {k: getattr(s, k) for k in self._SCHEDULE_FIELDS}
            for s in self.faults]}

    # --------------------------------------------------------- injection
    def _rank_matches(self, spec: FaultSpec, rank: Optional[int]) -> bool:
        if spec.rank is None:
            return True
        return rank is not None and rank == spec.rank

    def wire_fault(self, op: str, rank: Optional[int]) -> Optional[FaultSpec]:
        """Consulted by the rpc client once per request/response exchange.
        Advances the matching-call counter of EVERY matching rpc_* spec
        (order-independent bookkeeping) and returns the first spec whose
        window covers this call; None = deliver the message untouched."""
        fired = None
        with self._lock:
            for spec in self.faults:
                if spec.kind not in _WIRE_KINDS:
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                if spec.op != "*" and spec.op != op:
                    continue
                idx = spec.seen
                spec.seen += 1
                if idx < spec.after_calls or \
                        idx >= spec.after_calls + spec.count:
                    continue
                if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                    continue
                if fired is None:
                    spec.injected += 1
                    fired = spec
        if fired is not None:
            _reg().inc(f"chaos.injected_{fired.kind}", op=op)
        return fired

    def shipment_fault(self, op: str,
                       rank: Optional[int] = None) -> Optional[FaultSpec]:
        """Consulted by the disaggregated shipment channel once per
        ship/ack exchange (op is "ship" or "ack").  Same matching-call
        window semantics as `wire_fault`: every matching shipment_*
        spec's counter advances, the first covering spec fires; None =
        deliver the shipment untouched.  `rank` selects the decode
        replica the shipment is bound for."""
        fired = None
        with self._lock:
            for spec in self.faults:
                if spec.kind not in _SHIP_KINDS:
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                if spec.op != "*" and spec.op != op:
                    continue
                idx = spec.seen
                spec.seen += 1
                if idx < spec.after_calls or \
                        idx >= spec.after_calls + spec.count:
                    continue
                if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                    continue
                if fired is None:
                    spec.injected += 1
                    fired = spec
        if fired is not None:
            _reg().inc(f"chaos.injected_{fired.kind}", op=op)
        return fired

    def should_kill_prefill(self, step: int,
                            rank: Optional[int] = None) -> bool:
        """One-shot: True when a prefill_kill spec has its at_step
        reached on the COORDINATOR-step clock (the disagg layer then
        drops every in-flight prefill; their shipments never arrive and
        the timeout path re-prefills them)."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "prefill_kill" or spec.done:
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                if step >= (spec.at_step or 0):
                    spec.done = True
                    spec.injected += 1
                    break
            else:
                return False
        _reg().inc("chaos.injected_prefill_kill")
        return True

    def prefill_down(self, step: int,
                     rank: Optional[int] = None) -> bool:
        """Is the prefill tier inside a prefill_kill down-window at this
        step?  The window is [at_step, at_step + count): while down,
        decode replicas run colocated chunked prefill (the graceful
        degradation path) and the tier rejoins when the window passes.
        Pure read: no latch, no counter."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "prefill_kill":
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                start = spec.at_step or 0
                if start <= step < start + max(spec.count, 1):
                    return True
        return False

    def heartbeat_stall(self, beat: int, rank: Optional[int]) -> float:
        """Seconds the heartbeat loop should freeze before this beat
        (0.0 = no stall).  Mimics a long XLA compile pinning the GIL."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "heartbeat_stall":
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                start = spec.at_beat if spec.at_beat is not None else 0
                if start <= beat < start + spec.count and spec.stall_s > 0:
                    spec.injected += 1
                    stall = spec.stall_s
                    break
            else:
                return 0.0
        _reg().inc("chaos.injected_heartbeat_stall")
        return stall

    def step_delay(self, rank: Optional[int], step: int) -> float:
        """Seconds of slow_worker / decode_stall delay to inflate this
        step by (0.0 = none).  Deterministic: the window is [at_step,
        at_step + count) in observed steps, the delay a fixed delay_s
        per step — a faked straggling host (training) or a decode-clock
        stall window (serving) the detectors must catch.  Overlapping
        specs stack (their delays sum)."""
        total = 0.0
        fired_kinds = []
        with self._lock:
            for spec in self.faults:
                if spec.kind not in ("slow_worker", "decode_stall"):
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                start = spec.at_step if spec.at_step is not None else 0
                if start <= step < start + spec.count and spec.delay_s > 0:
                    spec.injected += 1
                    total += spec.delay_s
                    if spec.kind not in fired_kinds:
                        fired_kinds.append(spec.kind)
        for kind in fired_kinds:
            _reg().inc(f"chaos.injected_{kind}")
        return total

    def should_kill(self, rank: Optional[int], step: int) -> bool:
        """One-shot: True when a worker_kill spec for this rank has its
        at_step reached (the harness then kills/zombifies the worker)."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "worker_kill" or spec.done:
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                if step >= (spec.at_step or 0):
                    spec.done = True
                    spec.injected += 1
                    break
            else:
                return False
        _reg().inc("chaos.injected_worker_kill")
        return True

    def should_kill_engine(self, step: int,
                           rank: Optional[int] = None) -> bool:
        """One-shot: True when an engine_kill spec has its at_step
        reached on the ENGINE-step clock (the serving harness then
        fails the engine over; `rank` selects a fleet replica)."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "engine_kill" or spec.done:
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                if step >= (spec.at_step or 0):
                    spec.done = True
                    spec.injected += 1
                    break
            else:
                return False
        _reg().inc("chaos.injected_engine_kill")
        return True

    def engine_down(self, step: int,
                    rank: Optional[int] = None) -> bool:
        """Is the (replica's) engine inside an engine_kill down-window
        at this step?  The window is [at_step, at_step + count): count=1
        (the default) means the recovery replica takes over by the next
        step.  The fleet simulator suspends admissions while down (the
        live single-engine harness recovers instantly — its fail_over
        IS the recovery replica).  Pure read: no latch, no counter."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "engine_kill":
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                start = spec.at_step or 0
                if start <= step < start + max(spec.count, 1):
                    return True
        return False

    def reshard_storm_offset(self, step: int,
                             rank: Optional[int] = None) -> Optional[int]:
        """The storm-window offset of this engine step (0-based), or
        None when no reshard_storm spec covers it.  The serving harness
        forces the LoadAdaptiveMesh onto tier ``offset % num_tiers``
        each covered step — a deterministic flip-flop that exercises KV
        re-paging under repeated hot switches."""
        with self._lock:
            for spec in self.faults:
                if spec.kind != "reshard_storm":
                    continue
                if not self._rank_matches(spec, rank):
                    continue
                start = spec.at_step if spec.at_step is not None else 0
                if start <= step < start + spec.count:
                    spec.injected += 1
                    off = step - start
                    break
            else:
                return None
        _reg().inc("chaos.injected_reshard_storm")
        return off

    def take_ckpt_corrupt(self,
                          newest_step: Optional[int]) -> Optional[FaultSpec]:
        """One-shot: the spec to apply when the newest on-disk checkpoint
        step has reached at_step (the harness then corrupts that step)."""
        if newest_step is None:
            return None
        with self._lock:
            for spec in self.faults:
                if spec.kind != "ckpt_corrupt" or spec.done:
                    continue
                if newest_step >= (spec.at_step or 0):
                    spec.done = True
                    spec.injected += 1
                    break
            else:
                return None
        _reg().inc("chaos.injected_ckpt_corrupt")
        return spec

    # ------------------------------------------------------------ report
    def summary(self) -> Dict[str, int]:
        """Injected-fault counts by kind (kinds present in the plan appear
        even at zero — a schedule that never fired is a signal too)."""
        with self._lock:
            out: Dict[str, int] = {}
            for spec in self.faults:
                out[spec.kind] = out.get(spec.kind, 0) + spec.injected
            return out
