"""The runtime env-flag surface — one typed registry for every
`HETU_TPU_*` variable, with defaults and docs.

Rebuild of the reference's env-driven runtime controls (reference:
hetu/graph/executable_graph.cc:1163-1313 GetExecEnvs — HETU_STRAGGLER,
HETU_MEMORY_PROFILE, HETU_PARALLEL_ATTN_SPLIT_PATTERN, event timing...;
SURVEY §5.6 layer 3).  XLA owns op scheduling, so the TPU flag set controls
the layers above it: profiling, kernel routing, CP split mode, switch
accounting, and the multi-process bootstrap.

Usage:
    from hetu_tpu.utils import flags
    if flags.bool_flag("HETU_TPU_EVENT_TIMING"): ...
    mode = flags.str_flag("HETU_TPU_CP_SPLIT")      # validated default
    flags.describe()                                # the full surface
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str
    kind: str            # "bool" | "str" | "int"
    default: object
    doc: str
    choices: Optional[Tuple[str, ...]] = None
    #: the BYTE-IDENTITY contract, declared where the flag lives: setting
    #: the flag to this value must lower the canonical train-step AND
    #: serving-decode programs to exactly the text an unset environment
    #: lowers (for routing flags that is the neutral value — "none",
    #: "flat", "0"; for post-compile analysis flags it is "1": turning the
    #: analysis ON must not perturb the traced program).  None = no such
    #: contract (the flag legitimately changes shapes/routing).  Enforced
    #: systematically by the graph-contract linter's flag-identity sweep
    #: (hetu_tpu/analysis/flag_identity.py, tools_lint.py --flags), which
    #: replaced the per-flag hand-written byte-identity tests.
    identity: Optional[str] = None
    #: which canonical programs (analysis/programs.py PROGRAMS keys) the
    #: identity contract sweeps against; None = all of them.  Flags read
    #: ONLY inside hetu_tpu/serving (structurally enforced: serving is
    #: never imported from the package root and the env-bypass AST lint
    #: pins every read to this module) cannot perturb a training trace,
    #: so their contracts sweep the decode program alone — the training
    #: lowers would be pure sweep cost with no information.
    identity_programs: Optional[Tuple[str, ...]] = None


REGISTRY: Dict[str, Flag] = {f.name: f for f in [
    # -- profiling / observability (reference: HETU_EVENT_TIMING,
    #    HETU_MEMORY_PROFILE, profiler.h) --------------------------------
    Flag("HETU_TPU_EVENT_TIMING", "bool", False,
         "log per-step wall time from the trainer loop", identity="1"),
    Flag("HETU_TPU_TRACE_DIR", "str", "",
         "capture a jax.profiler trace of a step window into this dir"),
    Flag("HETU_TPU_MEMORY_PROFILE", "bool", False,
         "log per-step device memory stats + compiled-plan memory analysis",
         identity="1"),
    Flag("HETU_TPU_SWITCH_PROFILE", "bool", False,
         "per-hot-switch byte accounting (ProfileRunningDetails analog); "
         "off by default — the tree walk costs host time per switch"),
    Flag("HETU_TPU_LOG_LEVEL", "str", "INFO",
         "root log level for hetu_tpu loggers"),
    Flag("HETU_TPU_RUNLOG", "str", "",
         "write the structured run-event JSONL (obs.RunLog) to this path; "
         "default: <ckpt_dir>/runlog.jsonl when checkpointing, else off"),
    Flag("HETU_TPU_METRICS_EXPORT", "str", "",
         "export the metrics-registry snapshot as JSONL to this path when "
         "the trainer loop ends"),
    Flag("HETU_TPU_TRACE_SCHEDULE", "str", "",
         "write a Chrome-trace render of the pipeline micro-batch schedule "
         "(obs.pipeline_schedule_trace) to this path at build time when "
         "pp > 1; open in Perfetto / chrome://tracing"),
    Flag("HETU_TPU_RUNLOG_MAX_MB", "int", 0,
         "size-cap one RunLog segment to this many MiB; on overflow the "
         "writer appends a 'rotated' marker record, renames the file to "
         "<path>.<n> and starts a fresh segment (iter_records follows the "
         "whole chain in order).  0 (default) = no rotation"),
    Flag("HETU_TPU_TELEMETRY_PUSH", "str", "",
         "cluster telemetry push interval in seconds (e.g. '2.0'): each "
         "worker's control-plane client ships a delta-encoded metrics "
         "snapshot + recent RunLog tail to the coordination server, which "
         "folds them into the time-windowed ClusterSnapshot "
         "(hetu_tpu/obs/aggregate.py, docs/observability.md).  Unset/empty "
         "= off: no telemetry_push op ever hits the wire"),
    Flag("HETU_TPU_HEALTH", "bool", False,
         "run the training health monitor (obs.health.HealthMonitor) in "
         "the trainer loop: EWMA+MAD detectors for loss spikes, NaN/Inf "
         "grads, grad-norm blowups, step-time regressions and data-pipeline "
         "stalls -> health.* counters + 'anomaly' RunLog events.  Costs a "
         "per-step device sync for loss/grad_norm; off (default) = zero "
         "per-step work", identity="1"),
    Flag("HETU_TPU_HW_PROFILE", "str", "",
         "hardware profile JSON for the MFU/roofline reporter (obs.mfu); "
         "default: repo-root hardware_profile_v5e.json, else built-in v5e "
         "constants"),
    Flag("HETU_TPU_PROFILE", "bool", False,
         "per-compile analytic step profile (obs.hlo_profile): per-layer "
         "HLO attribution (FLOPs/HBM bytes/wire bytes per named "
         "layer/op-group) + liveness-based peak-HBM estimate -> a "
         "schema-versioned 'profile' RunLog record per fresh compile.  "
         "Pure post-compile HLO-text analysis: the traced program is "
         "byte-identical with the flag on or off", identity="1"),
    Flag("HETU_TPU_PROFILE_TOPK", "int", 8,
         "how many top layers/op-groups (by predicted roofline time) the "
         "'profile' RunLog record and BENCH detail.profile carry"),
    Flag("HETU_TPU_PROFILE_TRACE", "str", "",
         "write the analytic flame graph (obs.hlo_profile.flame_trace — "
         "a Chrome-trace lane of predicted per-layer roofline times) to "
         "this path on each fresh compile; open in Perfetto"),
    Flag("HETU_TPU_BUDGETS", "str", "",
         "declared perf-budget JSON (obs/budget.py PerfBudget: absolute "
         "ceilings for step time / comm bytes / peak HBM / MFU plus "
         "relative regression thresholds).  The trainer checks each "
         "fresh compile's profile against it (budget RunLog events, "
         "budget.breaches counter; 'enforce': true raises), and "
         "tools_bench_diff.py diffs BENCH rounds with its thresholds"),
    Flag("HETU_TPU_COMM_ANALYZE", "bool", True,
         "per-compile bytes-on-wire analysis (obs.comm) in RunLog compile "
         "events; costs one as_text() of the optimized HLO per fresh "
         "compile — set 0 on very large programs where stringifying the "
         "module is noticeable next to the compile itself", identity="0"),
    Flag("HETU_TPU_LINT", "bool", False,
         "per-compile graph-contract lints (hetu_tpu/analysis/hlo_lints): "
         "run the donation / replication / dtype-drift / scope-coverage "
         "lints over each fresh compile's optimized HLO -> a 'lint' "
         "RunLog record + lint.* counters (error findings log loudly but "
         "never fail the step — tools_lint.py is the enforcing surface).  "
         "Pure post-compile HLO-text analysis: the traced program is "
         "byte-identical with the flag on or off; see "
         "docs/static_analysis.md", identity="1"),
    Flag("HETU_TPU_NUMERICS", "bool", False,
         "the numerics observatory (obs/numerics.py, "
         "docs/observability.md): compute per-tensor absmax/rms/norm, "
         "nonfinite counts and bf16 underflow/overflow fractions at "
         "named scopes INSIDE the jitted step, exact quantization-error "
         "SNR at every compressed path (DP grad sync, SP collectives, "
         "ZeRO delta-gather, int8 KV pages), EF-residual norms, "
         "loss-scale dynamics and MoE router stats (per-expert load, "
         "entropy, capacity drops) -> an auxiliary stats pytree per "
         "step, recorded as schema-versioned 'numerics' RunLog records "
         "+ numerics.* registry gauges, feeding the numerics health "
         "detectors (HETU_TPU_HEALTH).  Unset (default) = the step "
         "wrapper never runs: the traced program is byte-identical to "
         "the flag not existing (registered identity contract)",
         identity="0"),
    Flag("HETU_TPU_NUMERICS_EVERY", "int", 1,
         "numerics host-fetch sampling interval in steps: record the "
         "stats pytree every N-th step (the in-graph stats are traced "
         "either way — only the device fetch + RunLog/registry write is "
         "sampled).  Raise on hot loops where a per-step scalar fetch "
         "is noticeable"),
    Flag("HETU_TPU_MAX_PLANS", "int", 8,
         "max compiled train-step plans per strategy (one per batch-shape "
         "bucket); a new shape past the cap is a loud error instead of a "
         "silent recompile (HETU_SHAPE_MISMATCH analog); 0 = unbounded"),
    # -- kernel / execution routing (reference: HETU_PARALLEL_ATTN*) -----
    Flag("HETU_TPU_GRAD_COMPRESS", "str", "none",
         "compressed DP grad sync (hetu_tpu/comm/): none = f32 collectives "
         "(byte-identical default), int8 = blockwise-int8 quantized "
         "reduce-scatter/all-gather (+ quantized hetero-DP bridge), "
         "int4 = packed two-per-byte (~7.8x fewer bytes), -ef variants "
         "carry error-feedback residuals in the optimizer state; see "
         "docs/comm_compression.md",
         choices=("none", "int8", "int8-ef", "int4", "int4-ef"),
         identity="none"),
    Flag("HETU_TPU_SP_COMPRESS", "str", "none",
         "quantized SP/TP activation collectives (comm/collectives.py): "
         "the explicit shard_map paths' all-gathers/reduce-scatters/"
         "all-to-alls (dstates.convert, hetero-TP pipeline SP edges) move "
         "blockwise int8/int4 + f32 scales instead of full-width floats; "
         "backward transports quantize too (custom_vjp transpose).  none "
         "(default) is HLO-byte-identical to unset",
         choices=("none", "int8", "int4"), identity="none"),
    Flag("HETU_TPU_ZERO_COMPRESS", "str", "none",
         "quantized ZeRO-1/2 param refresh (optim/zero_refresh.py): the "
         "optimizer update runs on dp-sharded state inside a shard_map "
         "and the param DELTA all-gathers as int8/int4 + scales instead "
         "of GSPMD's f32 param all-gather (~3.9x/7.8x fewer refresh "
         "bytes).  Same homogeneous-DP envelope as GRAD_COMPRESS; none "
         "(default) is HLO-byte-identical to unset",
         choices=("none", "int8", "int4"), identity="none"),
    Flag("HETU_TPU_MOE_DISPATCH", "str", "gspmd",
         "MoE expert-parallel token dispatch (nn/moe_dispatch.py, "
         "docs/moe.md): gspmd (default) keeps the compiler-chosen "
         "collectives — byte-identical to unset; fp32/int8/int4 route the "
         "sort dispatch through an explicit shard_map over the ep axis "
         "(HetuMoE HAllToAll): each ep rank scatters its token share, an "
         "all-to-all (comm/collectives.all_to_all_q — quantized custom-vjp "
         "both directions for int8/int4) delivers expert buffers, and the "
         "combine all-gathers expert outputs.  With "
         "HETU_TPU_COMM_TOPOLOGY=two_level and an applicable topology the "
         "dispatch runs hierarchically (intra-slice a2a at intra rates, "
         "strided inter-slice transversal at inter rates).  No-op at "
         "ep=1; explicit modes require tp=1, pp=1 (loud error otherwise)",
         choices=("gspmd", "fp32", "int8", "int4"), identity="gspmd"),
    Flag("HETU_TPU_COMM_TOPOLOGY", "str", "flat",
         "collective routing over the hardware profile's `topology` "
         "section (comm/topology.py): two_level runs the DP grad sync "
         "hierarchically (intra-slice reduce-scatter -> inter-slice "
         "exchange of the 1/slice shard -> intra-slice all-gather, "
         "HetCCL-style) so inter-slice links move slice_devices-fold "
         "fewer bytes.  flat (default) is HLO-byte-identical to unset",
         choices=("flat", "two_level"), identity="flat"),
    # -- serving (hetu_tpu/serving, docs/serving.md) ---------------------
    Flag("HETU_TPU_KV_QUANT", "str", "none",
         "paged-KV-cache page mode (serving/kv_pool.py): int8 stores "
         "pages as blockwise int8 + one f32 absmax scale per head-vector "
         "(comm/compress primitives; ~3.9x smaller than the fp32 exact "
         "cache at hd=128, ~1.9x vs bf16); int4 packs two values per "
         "byte under the same per-head-vector scale (~7.5x vs fp32 at "
         "hd=128 — decode parity within the documented tolerance, "
         "docs/serving.md).  none (default) stores exact pages in the "
         "model compute dtype — byte-identical semantics to "
         "models/generation.init_cache",
         choices=("none", "int8", "int4"), identity="none"),
    Flag("HETU_TPU_SERVE_SLOTS", "int", 8,
         "serving engine decode-slot count (the static batch dimension "
         "of the continuous-batching decode program)"),
    Flag("HETU_TPU_SERVE_PAGE", "int", 16,
         "KV-cache page size in tokens (serving/kv_pool.py block size)"),
    Flag("HETU_TPU_SERVE_MAX_LEN", "int", 256,
         "per-sequence serving cap (prompt + decode budget); must be a "
         "multiple of HETU_TPU_SERVE_PAGE and <= the model's "
         "max_position_embeddings"),
    Flag("HETU_TPU_SERVE_PREFILL_CHUNK", "int", 32,
         "chunked-prefill token budget per engine step (one chunk per "
         "step, interleaved with decode, so long prompts never stall "
         "the decode batch); SERVE_MAX_LEN must be a multiple of it"),
    Flag("HETU_TPU_SERVE_PAGES", "int", 0,
         "usable KV pages in the pool; 0 (default) = full reservation "
         "(slots * max_len / page), i.e. admission never waits on pages"),
    Flag("HETU_TPU_SERVE_SAMPLE", "bool", False,
         "in-graph serving sampler (serving/sampling.py): the decode "
         "program takes per-slot temperature/top-k/top-p vectors and "
         "seeded PRNG keys derived as fold_in(key(seed), position) — "
         "same seed => same tokens across engine restarts and batch "
         "compositions; greedy rows (temperature 0) stay argmax.  "
         "Unset (default) builds the greedy-only decode program "
         "byte-identical to the flag not existing (registered identity "
         "contract); SamplingParams on a Request then raise loudly",
         identity="0", identity_programs=("decode",)),
    Flag("HETU_TPU_SPEC_DECODE", "str", "none",
         "speculative decoding (serving/spec_decode.py): ngram drafts "
         "HETU_TPU_SPEC_K tokens per slot per step (prompt-lookup, "
         "host-side, model-free) and ONE batched verify forward "
         "(models/generation.verify_step_slots) scores all k+1 "
         "positions; acceptance is sample-then-match — the exact "
         "rejection rule for a deterministic drafter, so greedy output "
         "is token-identical to sequential generate() and sampled "
         "output matches the non-speculative distribution (and seed).  "
         "model runs a resident-quantized draft model (the engine's "
         "draft_model/draft_params kwargs) with the full stochastic p/q "
         "rejection rule: accept with prob min(1, p/q), residual "
         "resample on rejection — the output distribution is exactly "
         "the target's for ANY drafter.  none (default) builds the "
         "single-token decode program byte-identical to unset",
         choices=("none", "ngram", "model"), identity="none",
         identity_programs=("decode",)),
    Flag("HETU_TPU_SPEC_K", "int", 4,
         "draft tokens per speculative decode step (the verify "
         "program's static width is k+1); also widens every page "
         "reservation by k positions (reserve-on-admit must cover the "
         "draft writes).  Read only when HETU_TPU_SPEC_DECODE is set — "
         "the registered identity contract pins that setting it alone "
         "leaves the decode program byte-identical",
         identity="4", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_PREFIX_CACHE", "bool", False,
         "radix prefix cache (serving/prefix_cache.py): finished "
         "prompts' page-aligned KV pages stay resident in a radix tree "
         "keyed by token blocks, with copy-on-write refcounts in the "
         "page pool — a request sharing the prefix admits with those "
         "pages already in its page table and prefill runs only the "
         "unshared suffix (>= 90% of prefill FLOPs eliminated for a "
         "fully-shared system prompt, bench.py detail.serving).  "
         "Host-side bookkeeping only: the decode program is "
         "byte-identical either way (registered identity contract)",
         identity="0", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_PREFIX_PAGES", "int", 0,
         "radix-cache page budget (0 = bounded only by pool pressure: "
         "the scheduler evicts LRU cache entries on demand when an "
         "admission's reservation comes up short, so cached pages are "
         "best-effort slack and can never deadlock admission)",
         identity="0", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_PREEMPT", "bool", False,
         "SLO-class-aware preemptive admission: when the queue head's "
         "class priority strictly outranks the lowest-priority live "
         "slot and admission stalls (no_slot/no_pages), that slot is "
         "evicted-and-requeued (pages released, 'preempted' stall "
         "reason span, serve 'preempt' event) and the head admits.  "
         "Equal priorities never preempt (no thrash).  Host-side "
         "policy only — decode program byte-identical (registered "
         "identity contract)",
         identity="0", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_QUOTAS", "str", "",
         "per-tenant admission quotas (serving/request.py parse_quotas): "
         "comma list of tenant[:max_slots[:max_pages]] specs, e.g. "
         "'acme:2:16,free:1:4' — the scheduler caps how many decode "
         "slots / KV pages each tenant's LIVE requests may hold, "
         "stalling the queue head with the 'quota_exceeded' reason when "
         "its tenant is over (docs/serving.md).  Unset/empty (default) "
         "= quota-free: the admission path is byte-identical to the "
         "flag not existing (registered identity contract; host-side "
         "policy only — the decode program never sees tenants)",
         identity="", identity_programs=("decode",)),
    Flag("HETU_TPU_RUNLOG_SERVE_SAMPLE", "int", 1,
         "serve-event/span RunLog sampling: only a deterministic hashed "
         "1-in-N of request ids (serving/request.py rid_sampled — "
         "decorrelated from round-robin tenant/class assignment) emit "
         "their 'serve'/'span' records, stamped with "
         "sample_weight=N so serving/slo_report.py re-weights rates and "
         "goodput unbiasedly (exact registry counters are never "
         "sampled).  1 (default) logs every request — the RunLog is "
         "byte-identical to the flag not existing (registered identity "
         "contract); raise to ~1000 for 10^6-request fleet runs",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_TRACE", "bool", False,
         "serving flight recorder (serving/tracing.py): record every "
         "request's lifecycle as schema-versioned 'span' RunLog records "
         "— queued (with the scheduler's no_slot/no_pages stall "
         "attribution), one span per prefill chunk, decode segments "
         "split at evictions/reshard pauses, terminal "
         "done/evicted/hedge_withdrawn — each span stamped with its "
         "clock basis (driver|wall) and, on fleet tiers, tier/replica "
         "trace context, so obs/spans.py FleetTrace.stitch can assemble "
         "the per-engine hops plus frontend dispatch/hedge/ship events "
         "into one causal per-request DAG and obs/critpath.py can "
         "decompose TTFT/e2e with zero residual.  Pure host-side "
         "bookkeeping: the compiled prefill/decode programs are "
         "byte-identical with the flag on or off (registered identity "
         "contract, decode program — reads are serving-confined)",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_RETRY", "int", 0,
         "per-request retry budget after a serving replica death (chaos "
         "engine_kill): in-flight requests re-enter the queue with the "
         "'replica_lost' stall reason and a bumped attempt index, up to "
         "this many times; past the budget they terminate as "
         "'retry_exhausted'.  Seeded sampling replays each survivor to "
         "the exact token stream of the undisturbed run "
         "(docs/fault_tolerance.md).  0 (default) = no retries: a "
         "killed replica's in-flight requests terminate.  Host-side "
         "failover policy only — the decode program is byte-identical "
         "at any value (registered identity contract)",
         identity="3", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_DEADLINE", "bool", False,
         "enforce SLOClass deadlines (serving/request.py deadline_s, "
         "the 5th --slo-class field): each engine step sweeps queued "
         "AND live requests, terminating any older than its class "
         "deadline as 'deadline_exceeded' — a real terminal span, "
         "costed in the ledger and reported by slo_report.  Unset "
         "(default) = deadlines never inspected.  Host-side policy "
         "only — decode program byte-identical (registered identity "
         "contract)",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_BROWNOUT", "bool", False,
         "sustained-pressure brownout shedding: when KV page "
         "utilization sits at the high watermark with a backed-up "
         "queue for a streak of steps (the page_exhaustion_imminent "
         "detector's signals), the engine sheds the lowest-priority "
         "queued requests ('brownout_shed' stall reason, 'evicted' "
         "terminal span), lowest-priority tenants first, and meters "
         "the shed through the HETU_TPU_HEALTH serving detectors.  "
         "Unset (default) = never shed.  Host-side policy only — "
         "decode program byte-identical (registered identity "
         "contract)",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_KV_REPAGE", "bool", False,
         "migrate the paged KV pool through a LoadAdaptiveMesh tier "
         "change (serving/reshard.py reshard_pool): the pool arrays "
         "(fp or int8 payload+scales) are device_put onto the "
         "destination tier's mesh alongside the params, so in-flight "
         "requests survive a scale-up/down token-identically; page "
         "tables are host-resident and re-uploaded each step, so they "
         "migrate for free.  Unset (default) keeps the pre-existing "
         "params-only reshard (the pool stays on its original "
         "placement).  Pure data movement between steps — the decode "
         "program is byte-identical (registered identity contract)",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_DISAGG", "bool", False,
         "disaggregated prefill/decode serving (serving/disagg.py): "
         "prompts prefill on a separate tier running the SAME chunk "
         "program, and the finished scratch KV ships to the decode "
         "tier over an acked at-least-once channel (seq-numbered "
         "shipments, receiver-side dedupe before any page allocation, "
         "timeout -> resend -> re-prefill under HETU_TPU_SERVE_RETRY). "
         "A dead prefill tier degrades to colocated chunked prefill "
         "('prefill_tier_down' stall reason, metered degraded-mode "
         "seconds), auto-recovering.  Host-side orchestration only: "
         "chunk, write, and decode programs are the engine's own, so "
         "the decode program is byte-identical with the flag on or "
         "off (registered identity contract) and exact-wire streams "
         "are token-identical to the colocated run",
         identity="1", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_SHIP_QUANT", "str", "none",
         "wire quantization for prefill->decode KV shipments "
         "(serving/disagg.py pack_shipment): int8/int4 ship blockwise "
         "payloads + f32 scale planes through the same "
         "quantize_heads format the KV pool and re-paging use (~4x / "
         "~7.5x fewer wire bytes vs fp32); none (default) ships the "
         "exact scratch — the mode that preserves token byte-identity "
         "to the colocated run.  A host-side wire transform: the "
         "decode program is byte-identical at any value (registered "
         "identity contract)",
         choices=("none", "int8", "int4"),
         identity="int8", identity_programs=("decode",)),
    Flag("HETU_TPU_SERVE_HEDGE", "int", 0,
         "frontend hedged re-dispatch (serving/frontend.py): a request "
         "queued on its replica for more than this many router steps "
         "is speculatively re-submitted to the next-best healthy "
         "replica; the first replica to finish wins ('hedge_win' "
         "serve event) and the loser's copy is withdrawn, deduped by "
         "rid — duplicate results never reach the client, and loser "
         "tokens are accounted as discarded work.  0 (default) = "
         "never hedge.  Host-side routing policy only — the decode "
         "program is byte-identical at any value (registered "
         "identity contract)",
         identity="2", identity_programs=("decode",)),
    Flag("HETU_TPU_PALLAS", "str", "auto",
         "Pallas fused-kernel layer routing (ops/pallas: flash attention, "
         "residual+RMS/LayerNorm, SwiGLU, rotary, blockwise quantize, "
         "paged-attention decode, multi-query verify, fused sampling "
         "epilogue, fused AdamW — docs/kernels.md): auto (shape-gated, "
         "TPU only), 1 (force the kernels; unsupported shapes raise), "
         "0 (force the XLA compositions — byte-identical to the seed "
         "lowering, tested)",
         choices=("auto", "1", "0"), identity="0"),
    Flag("HETU_TPU_PALLAS_KERNELS", "str", "",
         "restrict WHICH Pallas kernels participate in HETU_TPU_PALLAS "
         "routing: comma list over {flash, norm, swiglu, rotary, quant, "
         "paged_attn, paged_verify, sample, adam}, or 'all' (default: "
         "empty = all) / 'none' — lets one kernel be bisected out "
         "without losing the rest",
         identity="all"),
    Flag("HETU_TPU_CP_SPLIT", "str", "sym",
         "default context-parallel split pattern "
         "(reference: HETU_PARALLEL_ATTN_SPLIT_PATTERN SYM/STRIPE/NORMAL)",
         choices=("sym", "stripe", "normal")),
    # -- robustness / chaos (hetu_tpu/chaos, docs/fault_tolerance.md) ----
    Flag("HETU_TPU_CHAOS", "str", "",
         "path to a deterministic fault-injection schedule JSON "
         "(hetu_tpu.chaos.FaultPlan: seeded rpc drop/delay/dup, heartbeat "
         "stalls, worker kills, checkpoint corruption).  Unset = chaos "
         "off: the rpc wire layer is identity and nothing else changes"),
    # -- multi-process bootstrap (core/distributed.py) -------------------
    Flag("HETU_TPU_COORDINATOR", "str", "",
         "jax.distributed coordinator address host:port"),
    Flag("HETU_TPU_NUM_PROCESSES", "int", 0,
         "world size for multi-process init (0 = single process)"),
    Flag("HETU_TPU_PROCESS_ID", "int", 0,
         "this process's rank for multi-process init"),
    Flag("HETU_TPU_CONTROL", "str", "",
         "coordination-server address host:port (KV/barrier/elastic)"),
    # -- launcher-injected worker env (rpc/launcher.py sets these in each
    #    spawned worker; workers read them back for slot identity) --------
    Flag("HETU_TPU_COORD", "str", "",
         "coordination-server host:port handed to launcher-spawned workers"),
    Flag("HETU_TPU_WORKER_ID", "int", 0,
         "stable launcher slot id (0..n-1); a relaunched worker keeps it"),
    Flag("HETU_TPU_NUM_WORKERS", "int", 0,
         "launcher world size handed to spawned workers"),
]}


def _lookup(name: str) -> Flag:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown flag {name!r}; known: {sorted(REGISTRY)}")


_TRUE = ("1", "true", "True", "TRUE", "yes", "on")
_FALSE = ("0", "false", "False", "FALSE", "no", "off", "")


def bool_flag(name: str) -> bool:
    f = _lookup(name)
    raw = os.environ.get(name)
    if raw is None:
        return bool(f.default)
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    raise ValueError(
        f"{name}={raw!r} is not a boolean; use one of {_TRUE + _FALSE}")


def str_flag(name: str) -> str:
    f = _lookup(name)
    val = os.environ.get(name, f.default)
    if f.choices and val not in f.choices:
        raise ValueError(
            f"{name}={val!r} invalid; choices: {f.choices}")
    return val


def int_flag(name: str) -> int:
    f = _lookup(name)
    raw = os.environ.get(name)
    return int(raw) if raw else int(f.default)


def identity_flags() -> Dict[str, str]:
    """{flag name: identity value} for every registered flag carrying a
    byte-identity contract — THE declarative contract table the
    flag-identity sweep (hetu_tpu/analysis/flag_identity.py) enforces
    against the canonical train-step and serving-decode programs.
    Registering a flag with `identity=` here is all it takes to put it
    under systematic enforcement; there are no per-flag tests to write."""
    return {f.name: f.identity for f in REGISTRY.values()
            if f.identity is not None}


def identity_contract_programs(name: str) -> Optional[Tuple[str, ...]]:
    """The canonical programs `name`'s identity contract sweeps against
    (None = every program) — the sweep's per-flag program axis."""
    return _lookup(name).identity_programs


def describe() -> str:
    """Human-readable flag table (the GetExecEnvs surface, documented)."""
    lines = []
    for f in REGISTRY.values():
        cur = os.environ.get(f.name)
        cur_s = f" [set: {cur}]" if cur is not None else ""
        lines.append(f"{f.name} ({f.kind}, default {f.default!r}){cur_s}\n"
                     f"    {f.doc}")
    return "\n".join(lines)


def active() -> Dict[str, str]:
    """The HETU_TPU_* vars actually set in this environment
    (reference: GetExecEnvs logging)."""
    return {k: v for k, v in os.environ.items() if k.startswith("HETU_TPU_")}
