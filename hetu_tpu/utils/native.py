"""Shared loader for the native C++ components in csrc/ (build-on-demand +
ctypes; the reference builds its native code via CMake up front)."""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_CACHE: dict = {}


def csrc_dir() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..", "csrc"))


def load_native_lib(so_name: str, make_target: Optional[str] = None,
                    required: bool = True) -> Optional[ctypes.CDLL]:
    """Load csrc/<so_name>, building it with make if absent.  Build/compile
    errors surface the compiler's stderr.  required=False returns None on
    failure (callers with a python fallback)."""
    if so_name in _CACHE:
        return _CACHE[so_name] or None
    root = csrc_dir()
    so = os.path.join(root, so_name)
    if not os.path.exists(so):
        try:
            subprocess.run(
                ["make", "-C", root] + ([make_target] if make_target else []),
                check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            _CACHE[so_name] = False
            if required:
                raise RuntimeError(
                    f"building {so_name} failed:\n{e.stderr}") from e
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        _CACHE[so_name] = False
        if required:
            raise
        return None
    _CACHE[so_name] = lib
    return lib
