"""Leveled logging (reference: hetu/common/logging.* HT_LOG_* macros +
python/hetu/logger.py).  Per-process prefix carries the jax process index the
way the reference prefixes device ids."""
from __future__ import annotations

import logging
import sys

_FMT = "[%(asctime)s %(name)s %(levelname).1s] %(message)s"


def get_logger(name: str = "hetu_tpu") -> logging.Logger:
    logger = logging.getLogger(f"hetu_tpu.{name}")
    if not logger.handlers:
        from hetu_tpu.utils import flags
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FMT, datefmt="%H:%M:%S"))
        logger.addHandler(h)
        logger.setLevel(flags.str_flag("HETU_TPU_LOG_LEVEL"))
        logger.propagate = False
    return logger
