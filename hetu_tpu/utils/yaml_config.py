"""YAML config front-end.

Rebuild of the reference's Hydra/OmegaConf config layer (reference:
examples/pretrain/config/*.yaml with rpc / ds_parallel / trainer / model
sections merged into TrainingConfig, SURVEY §5.6 layer 1).  Plain PyYAML
(hydra is not in the image): the same section layout, merged into the typed
configs.

```yaml
parallel:            # == the reference's ds_parallel section
  dp: 2
  tp: 4
  sequence_parallel: true
  zero_stage: 1
model:
  family: llama      # llama | gpt
  preset: llama2_7b  # or explicit fields
  overrides: {remat: true}
trainer:             # == TrainingConfig fields
  global_batch_size: 512
  seq_len: 4096
  lr: 3.0e-4
rpc:                 # coordination service (elastic runs)
  server: "10.0.0.1:7777"
```
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import yaml

from hetu_tpu.core.mesh import MeshConfig
from hetu_tpu.engine.trainer_config import TrainingConfig
from hetu_tpu.parallel.strategy import ParallelStrategy


def load_yaml_config(path_or_dict) -> Dict[str, Any]:
    if isinstance(path_or_dict, dict):
        return path_or_dict
    with open(path_or_dict) as f:
        return yaml.safe_load(f)


def parse_parallel(cfg: Dict[str, Any]) -> ParallelStrategy:
    p = dict(cfg.get("parallel", {}))
    mesh_keys = {k: int(p.pop(k)) for k in ("dp", "cp", "tp", "pp", "ep")
                 if k in p}
    known = {f.name for f in dataclasses.fields(ParallelStrategy)} - {"mesh"}
    unknown = set(p) - known
    if unknown:
        raise ValueError(f"unknown parallel config keys: {sorted(unknown)}")
    return ParallelStrategy(mesh=MeshConfig(**mesh_keys), **p)


def parse_trainer(cfg: Dict[str, Any]) -> TrainingConfig:
    t = dict(cfg.get("trainer", {}))
    known = {f.name for f in dataclasses.fields(TrainingConfig)}
    unknown = set(t) - known
    if unknown:
        raise ValueError(f"unknown trainer config keys: {sorted(unknown)}")
    return TrainingConfig(**t)


def parse_model(cfg: Dict[str, Any], strategy: ParallelStrategy):
    """Build the model from the `model:` section."""
    m = dict(cfg.get("model", {}))
    family = m.get("family", "llama")
    preset = m.get("preset", "tiny")
    overrides = m.get("overrides", {}) or {}
    if family == "llama":
        from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
        mk = getattr(LlamaConfig, preset)
        return LlamaLMHeadModel(mk(**overrides), strategy)
    if family == "gpt":
        from hetu_tpu.models.gpt import GPTConfig, GPTLMHeadModel
        mk = getattr(GPTConfig, preset, None)
        cfg_obj = mk(**overrides) if mk else GPTConfig(**overrides)
        return GPTLMHeadModel(cfg_obj, strategy)
    raise ValueError(f"unknown model family {family!r}")


def load_experiment(path_or_dict) -> Tuple[Any, TrainingConfig, ParallelStrategy, Dict]:
    """(model, training_config, strategy, raw) from one YAML
    (the reference's train_hetu.py:12-14 structured merge)."""
    raw = load_yaml_config(path_or_dict)
    strategy = parse_parallel(raw)
    trainer_cfg = parse_trainer(raw)
    model = parse_model(raw, strategy)
    return model, trainer_cfg, strategy, raw
