"""Profiling / observability surface.

Rebuild of the reference's env-flag-driven profiling (reference: SURVEY §5.1,
§5.6 layer 3 — HETU_EVENT_TIMING records per-op events,
HETU_MEMORY_PROFILE per-micro-batch memory, HETU_PARALLEL_ATTN attn timing,
executable_graph.cc:1163-1313 GetExecEnvs).

TPU mapping: XLA owns op scheduling, so per-op timing comes from
jax.profiler traces; this module keeps the reference's ENV-FLAG CONTRACT and
provides step-level timing + trace capture:

    HETU_TPU_EVENT_TIMING=1        step timing logged per step
    HETU_TPU_TRACE_DIR=/tmp/trace  capture a jax.profiler trace (step window)
    HETU_TPU_MEMORY_PROFILE=1      per-step device memory stats (if exposed)
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax

from hetu_tpu.utils.logging import get_logger

logger = get_logger("profiling")


def env_flags() -> Dict[str, str]:
    """The runtime-behavior env surface (reference: GetExecEnvs); the full
    typed registry with docs lives in hetu_tpu.utils.flags."""
    from hetu_tpu.utils import flags
    return flags.active()


def device_mem_bytes() -> Optional[int]:
    """bytes_in_use on device 0, or None where the backend hides it (CPU).
    ONE definition shared by the trainer's RunLog probe and the
    HETU_TPU_MEMORY_PROFILE step stats."""
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        v = stats.get("bytes_in_use")
        return int(v) if v is not None else None
    except Exception:
        return None


class StepProfiler:
    """Step-level timing/trace hooks for the trainer loop."""

    def __init__(self):
        from hetu_tpu.utils import flags
        self.event_timing = flags.bool_flag("HETU_TPU_EVENT_TIMING")
        self.trace_dir = flags.str_flag("HETU_TPU_TRACE_DIR") or None
        self.mem_profile = flags.bool_flag("HETU_TPU_MEMORY_PROFILE")
        self._trace_active = False
        self._trace_done = False
        self._first_step: Optional[int] = None
        self._times = []
        #: most recent HETU_TPU_MEMORY_PROFILE probe (bytes_in_use), so
        #: the RunLog step record and merged cluster traces see memory
        #: too, not just the log line (None: profiling off / backend
        #: hides memory_stats)
        self.last_mem_bytes: Optional[int] = None

    def _stop_trace(self):
        if self._trace_active:
            try:
                jax.profiler.stop_trace()
                logger.info(f"trace written to {self.trace_dir}")
            finally:
                self._trace_active = False
                self._trace_done = True

    @contextlib.contextmanager
    def step(self, step_idx: int, trace_steps=(2, 4)):
        """trace_steps are RELATIVE to the first profiled step, so traces
        fire on checkpoint-resumed runs too."""
        if self._first_step is None:
            self._first_step = step_idx
        rel = step_idx - self._first_step
        if (self.trace_dir and not self._trace_active and not self._trace_done
                and rel >= trace_steps[0]):
            jax.profiler.start_trace(self.trace_dir)
            self._trace_active = True
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._times.append(dt)
            if self.event_timing:
                logger.info(f"step {step_idx}: {dt * 1000:.1f} ms")
            if self.mem_profile:
                used = device_mem_bytes()
                self.last_mem_bytes = used
                if used is not None:
                    logger.info(
                        f"step {step_idx}: {used / 1e9:.2f} GB in use")
            if self._trace_active and rel >= trace_steps[1]:
                self._stop_trace()

    @property
    def last_step_s(self) -> float:
        """Wall seconds of the most recent profiled step (0.0 before the
        first) — the trainer's RunLog step records read it."""
        return self._times[-1] if self._times else 0.0

    def close(self):
        """Flush an in-flight trace (called by the trainer when the loop
        ends before the trace window closes)."""
        self._stop_trace()

    def summary(self) -> Dict[str, float]:
        if not self._times:
            return {}
        ts = sorted(self._times)
        return {"steps": len(ts), "min_s": ts[0],
                "median_s": ts[len(ts) // 2], "max_s": ts[-1]}


# ---------------------------------------------------------------------------
# per-phase HLO attribution (reference: hetu/impl/profiler/profiler.h:25
# per-op cost records + HETU_EVENT_TIMING executable_graph.cc:1303)
# ---------------------------------------------------------------------------

PHASES = ("embed", "attn", "moe", "mlp", "lm_head", "ring")

# ONE byte-pricing table for every HLO text walker (obs/hlo_text.py is
# its home so a dtype addition lands once); imported here — after PHASES
# — because obs.hlo_profile imports PHASES from this module.
from hetu_tpu.obs.hlo_text import DTYPE_BYTES as _DTYPE_BYTES  # noqa: E402


def phase_breakdown(compiled_or_text, phases=PHASES):
    """Attribute the optimized HLO's instructions to the model's
    jax.named_scope phases (models annotate embed/attn/moe/mlp/lm_head).

    The scopes survive into instruction metadata (op_name="jit(f)/.../attn/
    dot_general"), INCLUDING the autodiff transpose ops, so forward and
    backward both attribute.  Returns {phase: {"instructions", "dots",
    "out_bytes"}} plus an "other" bucket — a hardware-free compute-split
    estimate (dots ~ MXU work, out_bytes ~ HBM traffic) that calibrates the
    cost model's per-phase terms; a jax.profiler trace over the same step
    shows the identical scope names on the timeline for wall-clock truth."""
    import re

    txt = (compiled_or_text if isinstance(compiled_or_text, str)
           else compiled_or_text.as_text())
    op_pat = re.compile(r'op_name="([^"]+)"')
    shape_pat = re.compile(r'\b([a-z][a-z0-9]*)\[([0-9,]*)\]')
    # the OUTPUT-shape section of `%name = <shapes> opcode(...)`: the
    # non-greedy group is everything between the assignment and the first
    # lowercase opcode token followed by '(' (operand shapes live INSIDE
    # the parens and must not count — summing them overcounts traffic by
    # the instruction fan-in).  Tuple outputs `(f32[..]{..}, f32[..]{..})`
    # and tiled layouts `{1,0:T(8,128)}` stay in the group: `T(` starts
    # uppercase, dtype tokens are followed by `[` not `(`.
    out_pat = re.compile(r'=\s*(.*?)\s*[a-z][a-z0-9_.-]*\(')
    # a scope segment may be wrapped by transform names — "attn",
    # "jvp(embed)", "transpose(jvp(mlp))" — so match the phase bounded by
    # path separators or transform parens
    seg_pats = {p: re.compile(r'(?:^|[/(])' + re.escape(p) + r'(?:[)/]|$)')
                for p in phases}
    # NOTE: hetu_tpu.obs.hlo_profile.layer_table is the per-LAYER
    # refinement of this walk (full scope paths, parsed dot FLOPs, wire
    # bytes, while-loop trip counts); with static counting its sums
    # equal these phase totals exactly — a tested contract, so the two
    # walks must not drift apart.
    out = {p: {"instructions": 0, "dots": 0, "out_bytes": 0}
           for p in (*phases, "other")}
    for line in txt.splitlines():
        m = op_pat.search(line)
        if m is None:
            continue
        opname = m.group(1)
        seg = next((p for p in phases if seg_pats[p].search(opname)),
                   "other")
        rec = out[seg]
        rec["instructions"] += 1
        if " dot(" in line or " convolution(" in line:
            rec["dots"] += 1
        # output shape(s): scalar `= f32[8,16]{...}` or tuple-shaped
        # multi-output fusions `= (f32[8,128]{...}, f32[8]{...})`.  HLO
        # text ALSO prints operand shapes inside the call parens, so the
        # scan is anchored to the output section only (out_pat) — every
        # component of a tuple output counts, no operand double-counts.
        om = out_pat.search(line)
        out_section = om.group(1) if om is not None else ""
        for dt, dims in shape_pat.findall(out_section):
            numel = 1
            for d in dims.split(","):
                if d:
                    numel *= int(d)
            rec["out_bytes"] += numel * _DTYPE_BYTES.get(dt, 4)
    return out
