"""Distributed checkpointing with strategy resharding on load.

Rebuild of the reference's safetensors checkpoint stack
(reference: python/hetu/utils/checkpoint/ht_safetensors.py — temp_save_split
:905 / temp_load_split :1147 re-shard per-rank shards when the parallel
strategy changes; save_file_async :505 background saves;
load_by_training/save_by_training :881/:893 resume with ZeRO states).

On TPU this maps onto orbax: tensors are stored sharded (per-host OCDBT
shards) and `load_checkpoint` restores directly into ANY target sharding —
the strategy-resharding load the reference implements by slice bookkeeping
comes from handing orbax the new NamedShardings.  Async save uses orbax's
AsyncCheckpointer (background thread), the analog of save_file_async.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

# remote stores ride orbax's filesystem layer untouched — the TPU-native
# analog of the reference's HDFS branch (model_saver.py:168): on TPU pods
# the durable store is a GCS bucket, and orbax speaks gs:// natively
# (needs the gcsfs/etils deps present in cloud images)
_REMOTE_SCHEMES = ("gs://", "s3://", "hdfs://", "file://")


def resolve_ckpt_path(path: str) -> str:
    """Absolute-ify local paths; pass remote URIs through unmangled."""
    if any(path.startswith(s) for s in _REMOTE_SCHEMES):
        return path
    return os.path.abspath(path)


class CheckpointManager:
    """Step-numbered checkpoints with retention + async save.

    `directory` may be a local path or a remote URI (gs://bucket/ckpts —
    the TPU-pod durable store; reference: model_saver.py:168 remote saves).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = resolve_ckpt_path(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Dict[str, Any], wait: bool = False):
        """state: arbitrary pytree (params/opt_state/step/...)."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                target: Optional[Any] = None) -> Any:
        """Restore into `target`'s shapes+shardings (reshard-on-load when the
        target strategy differs from the saved one).  `target` is a pytree of
        arrays or ShapeDtypeStructs with .sharding set."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if target is None:
            return self._mgr.restore(step)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            target)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def save_checkpoint(path: str, state: Any):
    """One-shot synchronous save (reference temp_save analog)."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(resolve_ckpt_path(path), state, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_checkpoint(path: str, target: Optional[Any] = None) -> Any:
    """One-shot load, resharding into `target`'s shardings if given."""
    ckptr = ocp.StandardCheckpointer()
    try:
        if target is None:
            return ckptr.restore(resolve_ckpt_path(path))
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            target)
        return ckptr.restore(resolve_ckpt_path(path), abstract)
    finally:
        ckptr.close()
