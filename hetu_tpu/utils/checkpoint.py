"""Distributed checkpointing with strategy resharding on load.

Rebuild of the reference's safetensors checkpoint stack
(reference: python/hetu/utils/checkpoint/ht_safetensors.py — temp_save_split
:905 / temp_load_split :1147 re-shard per-rank shards when the parallel
strategy changes; save_file_async :505 background saves;
load_by_training/save_by_training :881/:893 resume with ZeRO states).

On TPU this maps onto orbax: tensors are stored sharded (per-host OCDBT
shards) and `load_checkpoint` restores directly into ANY target sharding —
the strategy-resharding load the reference implements by slice bookkeeping
comes from handing orbax the new NamedShardings.  Async save uses orbax's
AsyncCheckpointer (background thread), the analog of save_file_async.

Verified fallback (docs/fault_tolerance.md): every committed save gets a
per-step MANIFEST next to the step directory — the state's pytree
structure hash plus per-file size+crc32 — written atomically AFTER the
(possibly async) save commits.  `restore_latest_valid()` walks steps
newest-first, skips any step whose manifest fails verification (counting
`ckpt.fallbacks` and quarantining the corrupt step so it cannot shadow a
later re-save of the same step number), and restores the newest step that
checks out — a torn or bit-rotted save degrades to "lose one checkpoint
interval", not "crash the surviving cluster".
"""
from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from hetu_tpu.utils.logging import get_logger

logger = get_logger("checkpoint")

# remote stores ride orbax's filesystem layer untouched — the TPU-native
# analog of the reference's HDFS branch (model_saver.py:168): on TPU pods
# the durable store is a GCS bucket, and orbax speaks gs:// natively
# (needs the gcsfs/etils deps present in cloud images)
_REMOTE_SCHEMES = ("gs://", "s3://", "hdfs://", "file://")


class CheckpointCorruptError(RuntimeError):
    """Checkpoints exist on disk but NONE of them is restorable (every
    step failed manifest verification or raised during restore).  Distinct
    from FileNotFoundError (no checkpoints at all — a legitimate fresh
    start) so recovery paths can be loud about lost state."""


def resolve_ckpt_path(path: str) -> str:
    """Absolute-ify local paths; pass remote URIs through unmangled."""
    if any(path.startswith(s) for s in _REMOTE_SCHEMES):
        return path
    return os.path.abspath(path)


def _is_remote(path: str) -> bool:
    return any(path.startswith(s) for s in _REMOTE_SCHEMES)


# ---------------------------------------------------------------- manifest
def manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"manifest_{int(step)}.json")


def pytree_structure_hash(state: Any) -> str:
    """Stable hash of the state's (keypath, shape, dtype) skeleton —
    recorded in the manifest so a restore target mismatch is explainable
    even before orbax raises."""
    import hashlib
    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        kp = jax.tree_util.keystr(path)
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        leaves.append((kp, list(shape), dtype))
    blob = json.dumps(sorted(leaves), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _file_checksums(step_dir: str) -> Dict[str, Dict[str, int]]:
    """relpath -> {size, crc32} for every file under a step directory."""
    out: Dict[str, Dict[str, int]] = {}
    for root, _dirs, files in os.walk(step_dir):
        for fn in sorted(files):
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, step_dir)
            crc, size = 0, 0
            with open(p, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
            out[rel] = {"size": size, "crc32": crc & 0xFFFFFFFF}
    return out


def write_manifest(directory: str, step: int,
                   structure: Optional[str] = None) -> str:
    """Checksum a committed step directory and write its manifest
    atomically (tmp + rename): a crash mid-write leaves either no
    manifest (step reads as unverified) or a complete one — never a torn
    manifest that poisons verification."""
    step_dir = os.path.join(directory, str(int(step)))
    man = {"schema": 1, "step": int(step), "structure": structure,
           "files": _file_checksums(step_dir), "written_at": time.time()}
    path = manifest_path(directory, step)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f)
        f.flush()
        os.fsync(f.fileno())   # rename durability alone doesn't imply
                               # data durability (delayed allocation)
    os.replace(tmp, path)
    return path


#: verify detail prefix for a torn/unreadable manifest — the DATA may be
#: fine, so restore_latest_valid drops the manifest instead of
#: quarantining the step (the step demotes to 'unverified')
MANIFEST_UNREADABLE = "manifest unreadable"


def verify_manifest(directory: str, step: int) -> Tuple[bool, str]:
    """(ok, detail) for one step.  A MISSING manifest passes as
    'unverified' — pre-manifest checkpoints and in-flight async saves must
    stay restorable — while a present-but-mismatching one fails loudly."""
    path = manifest_path(directory, step)
    if not os.path.exists(path):
        return True, "unverified (no manifest)"
    try:
        with open(path) as f:
            man = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"{MANIFEST_UNREADABLE}: {e!r}"
    step_dir = os.path.join(directory, str(int(step)))
    if not os.path.isdir(step_dir):
        return False, "step directory missing"
    actual = _file_checksums(step_dir)
    expected = man.get("files", {})
    if set(actual) != set(expected):
        missing = sorted(set(expected) - set(actual))
        extra = sorted(set(actual) - set(expected))
        return False, (f"file set mismatch (missing={missing[:3]}, "
                       f"extra={extra[:3]})")
    for rel, meta in expected.items():
        a = actual[rel]
        if a["size"] != meta.get("size") or a["crc32"] != meta.get("crc32"):
            return False, (f"checksum mismatch in {rel} "
                           f"(size {a['size']} vs {meta.get('size')})")
    return True, "verified"


class CheckpointManager:
    """Step-numbered checkpoints with retention + async save + verified
    fallback.

    `directory` may be a local path or a remote URI (gs://bucket/ckpts —
    the TPU-pod durable store; reference: model_saver.py:168 remote saves).
    Manifests are local-filesystem only: remote stores get orbax's own
    atomic-commit semantics and read back as 'unverified'.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = resolve_ckpt_path(directory)
        self._async = async_save
        self._manifests_enabled = not _is_remote(self.directory)
        self._pending: Optional[Tuple[int, Optional[str]]] = None
        self._manifest_thread = None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -------------------------------------------------------------- save
    def save(self, step: int, state: Dict[str, Any], wait: bool = False):
        """state: arbitrary pytree (params/opt_state/step/...)."""
        self._finalize_pending()   # manifest for the PREVIOUS async save
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if saved is False:
            # orbax declines silently when the step already exists (e.g.
            # re-saving the restore point after a fallback walked past a
            # newer step that was NOT quarantined) — silence here would
            # read as "checkpointed" when nothing hit disk
            from hetu_tpu.obs.metrics import get_registry
            get_registry().inc("ckpt.save_skipped")
            logger.warning(f"orbax declined to save step {step} (already "
                           "on disk?); state NOT re-written")
            return
        if self._manifests_enabled:
            self._pending = (int(step),
                             pytree_structure_hash(state))
            if self._async:
                # the wait-for-commit + full checksum read must not stall
                # the training thread — run it alongside the async save
                # and join at the next save/restore/wait/close boundary
                import threading
                self._manifest_thread = threading.Thread(
                    target=self._write_pending_manifest, daemon=True)
                self._manifest_thread.start()
            else:
                self._write_pending_manifest()
        if wait:
            self.wait()

    def _finalize_pending(self):
        """Ensure the last issued save's manifest is on disk (join the
        background writer; write synchronously if none ran)."""
        t = self._manifest_thread
        if t is not None:
            t.join()
            self._manifest_thread = None
        if self._pending is not None:
            self._write_pending_manifest()

    def _write_pending_manifest(self):
        """Write the manifest for the last issued save once it has
        committed (async saves commit in the background; the manifest must
        describe COMMITTED bytes, so it always waits first)."""
        if self._pending is None:
            return
        self._mgr.wait_until_finished()
        step, structure = self._pending
        self._pending = None
        if step not in (self._mgr.all_steps() or []):
            return   # save failed or was retention-pruned already
        try:
            write_manifest(self.directory, step, structure)
            from hetu_tpu.obs.metrics import get_registry
            get_registry().inc("ckpt.manifests_written")
            self._prune_manifests()
        except OSError as e:
            logger.warning(f"manifest for step {step} not written: {e!r}")

    def _prune_manifests(self):
        """Drop manifests for steps orbax's retention already deleted."""
        keep = set(self._mgr.all_steps() or [])
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not (name.startswith("manifest_") and name.endswith(".json")):
                continue
            stem = name[len("manifest_"):-len(".json")]
            if stem.isdigit() and int(stem) not in keep:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    # ------------------------------------------------------------ queries
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps() or [])

    def verify_step(self, step: int) -> Tuple[bool, str]:
        """(ok, detail): does this step's on-disk bytes match its
        manifest?  Remote stores and manifest-less steps pass as
        'unverified' (restore remains the final arbiter for those)."""
        if not self._manifests_enabled:
            return True, "unverified (remote store)"
        return verify_manifest(self.directory, step)

    # ----------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None,
                target: Optional[Any] = None) -> Any:
        """Restore into `target`'s shapes+shardings (reshard-on-load when the
        target strategy differs from the saved one).  `target` is a pytree of
        arrays or ShapeDtypeStructs with .sharding set."""
        self._finalize_pending()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if target is None:
            return self._mgr.restore(step)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            target)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore_latest_valid(self, target: Optional[Any] = None,
                             restore_fn=None, on_fallback=None
                             ) -> Tuple[int, Any]:
        """(step, restored): the newest checkpoint that verifies AND
        restores, walking back past corrupt/torn saves.  Checksum-failed
        steps are quarantined (deleted — they can never restore, and
        leaving them would shadow a later re-save of the same step
        number).  Raises FileNotFoundError when the directory holds no
        checkpoints, CheckpointCorruptError when none is restorable.

        restore_fn(step) overrides the per-step restore (the Trainer
        routes its scaler-retry/EF-reattach restore through here);
        on_fallback(step, why) observes each skipped step (RunLog fault
        events)."""
        from hetu_tpu.obs.metrics import get_registry
        self._finalize_pending()
        reg = get_registry()
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        last_err: Optional[BaseException] = None
        for step in steps:
            ok, why = self.verify_step(step)
            if not ok and why.startswith(MANIFEST_UNREADABLE):
                # a torn manifest (crash between data commit and manifest
                # fsync) must not condemn intact data: drop the manifest
                # only — the step demotes to 'unverified' and restore
                # arbitrates
                reg.inc("ckpt.manifests_torn")
                logger.warning(f"dropping torn manifest for step {step} "
                               f"({why}); step demoted to unverified")
                try:
                    os.remove(manifest_path(self.directory, step))
                except OSError:
                    pass
                ok, why = True, "unverified (torn manifest dropped)"
            if not ok:
                reg.inc("ckpt.fallbacks")
                logger.warning(f"checkpoint step {step} failed "
                               f"verification ({why}); falling back")
                self.quarantine(step, why)
                if on_fallback is not None:
                    on_fallback(step, why)
                continue
            try:
                if restore_fn is not None:
                    return step, restore_fn(step)
                return step, self.restore(step, target=target)
            except Exception as e:
                # verified ('unverified' pass included) but unrestorable —
                # FileNotFoundError included: a vanished data file IS the
                # partial-upload fault.  Count + fall back, do NOT
                # quarantine: the bytes may be fine and merely mismatch
                # the CURRENT target (e.g. a changed model); deleting
                # them would destroy good state
                last_err = e
                reg.inc("ckpt.fallbacks")
                logger.warning(f"restore of step {step} raised {e!r}; "
                               "falling back")
                if on_fallback is not None:
                    on_fallback(step, repr(e))
                continue
        raise CheckpointCorruptError(
            f"no restorable checkpoint among steps {steps} in "
            f"{self.directory}"
            + (f" (last error: {last_err!r})" if last_err else ""))

    def quarantine(self, step: int, why: str = ""):
        """Move a corrupt step aside (+ drop its manifest) so it cannot
        shadow a later save of the same step number (orbax silently
        declines to re-save an existing step).  The bytes are PRESERVED
        in a sibling `<directory>.quarantine/` for forensics/repair — a
        checksum-failed step is never auto-restored (that would load
        silently corrupt weights) but it is not destroyed either.  The
        sibling location matters: a renamed step-like dir INSIDE the root
        breaks orbax's step scan.  Best-effort: a live fallback must not
        die here."""
        from hetu_tpu.obs.metrics import get_registry
        get_registry().inc("ckpt.quarantined")
        logger.warning(f"quarantining corrupt checkpoint step {step}"
                       + (f" ({why})" if why else ""))
        step_dir = os.path.join(self.directory, str(int(step)))
        qdir = self.directory.rstrip("/") + ".quarantine"
        moved = False
        try:
            os.makedirs(qdir, exist_ok=True)
            os.rename(step_dir,
                      os.path.join(qdir, f"{int(step)}_{int(time.time())}"))
            moved = True
        except OSError as e:
            logger.warning(f"quarantine move of step {step} failed "
                           f"({e!r}); deleting instead")
        try:
            # sync orbax's cached step list (deletes the dir too when the
            # move failed — shadowing later re-saves is the worse outcome)
            self._mgr.delete(step)
        except Exception:
            if not moved:
                logger.warning(f"quarantine delete of step {step} failed")
            try:
                self._mgr.reload()
            except Exception:
                pass
        try:
            os.remove(manifest_path(self.directory, step))
        except OSError:
            pass

    # ------------------------------------------------------------- admin
    def wait(self):
        # join the manifest writer FIRST (it owns a wait_until_finished of
        # its own) so two threads never wait on orbax concurrently
        self._finalize_pending()
        self._mgr.wait_until_finished()

    def close(self):
        self._finalize_pending()
        self._mgr.close()


def save_checkpoint(path: str, state: Any):
    """One-shot synchronous save (reference temp_save analog)."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(resolve_ckpt_path(path), state, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_checkpoint(path: str, target: Optional[Any] = None) -> Any:
    """One-shot load, resharding into `target`'s shardings if given."""
    ckptr = ocp.StandardCheckpointer()
    try:
        if target is None:
            return ckptr.restore(resolve_ckpt_path(path))
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            target)
        return ckptr.restore(resolve_ckpt_path(path), abstract)
    finally:
        ckptr.close()
