"""Device-backend probing for entry points.

The axon remote-TPU plugin (a) overrides JAX_PLATFORMS=cpu from the
environment and (b) can hang indefinitely on first contact when its tunnel
is down — even jax.default_backend() blocks.  These helpers give entry
points (bench.py, __graft_entry__) a safe first touch.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Tuple


def force_cpu_if_requested():
    """Honor a caller's CPU request in-process (the plugin ignores the env):
    triggers on JAX_PLATFORMS=cpu or a host-platform device-count flag."""
    import jax
    want_cpu = (os.environ.get("JAX_PLATFORMS") == "cpu"
                or "xla_force_host_platform_device_count"
                in os.environ.get("XLA_FLAGS", ""))
    if want_cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialized


def probe_backend(timeout_s: float = 120.0) -> Tuple[Optional[str], Optional[BaseException]]:
    """First device contact on a watchdog thread.
    Returns (backend_name, None) on success, (None, exception) when the
    probe raised, (None, None) on timeout (tunnel hang)."""
    ok: list = []
    err: list = []

    def probe():
        try:
            import jax
            import jax.numpy as jnp
            backend = jax.default_backend()
            float(jnp.ones((8, 8)).sum())
            ok.append(backend)
        except BaseException as e:  # noqa: BLE001 — reported, not swallowed
            err.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if ok:
        return ok[0], None
    return None, (err[0] if err else None)
