"""Resident quantized expert weights for MoE serving.

The KV pool keeps the serving engine's dominant CACHE allocation small
with blockwise-int8 pages (`kv_pool.py`, HETU_TPU_KV_QUANT); for MoE
models the dominant PARAMETER allocation is the stacked `[E, ...]`
expert FFN tensors, read in full by every decode step.  Under
`HETU_TPU_MOE_DISPATCH=int8|int4` the engine stores those tensors
KV-pool-style: blockwise int payloads + one f32 absmax scale per block
(the same `comm/compress` arithmetic every compressed path shares, int4
packed two values per byte via `ops/quantization.pack_nibbles`), and
the compiled decode/prefill programs dequantize them on the way into
the expert einsums — HBM reads drop ~3.94x (int8) / ~7.76x (int4) on
the expert share of the weights (`expert_bytes` below is the analytic
record bench/detail carries).

Exactness: quantization happens ONCE at engine build (not per step), so
serving output is deterministic; the token-parity test compares the
engine against `generate()` on the dequantized weights — token-exact by
construction — and against the fp weights within the loss-parity-style
tolerance.  "gspmd"/"fp32" (and dense models) leave the params tree
untouched, byte-identical to the flag not existing.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from hetu_tpu.comm.compress import (dequantize_blockwise, pack_int4,
                                    quantize_blockwise, unpack_int4)
from hetu_tpu.comm.wire import DEFAULT_BLOCK

#: the two stacked expert leaves of an MoE FFN subtree (nn/moe.MoELayer)
EXPERT_KEYS = ("w_gate_up", "w_down")


def _is_expert_dict(node, num_experts: int) -> bool:
    """An MoE FFN subtree: router + both stacked expert leaves, with the
    expert count somewhere in the stacked shape (scan/pp stacking may
    prepend a layer dim — [L, E, ...] — so position is not fixed)."""
    return (isinstance(node, dict)
            and "router" in node
            and all(k in node for k in EXPERT_KEYS)
            and all(getattr(node[k], "ndim", 0) >= 3
                    and num_experts in tuple(node[k].shape)
                    for k in EXPERT_KEYS))


def _q_leaf(leaf, block: int, bits: int):
    """Stacked expert leaf -> {"q", "s"}: one flat blockwise quantize
    (scale granularity is one f32 per `block` values regardless of the
    stacking layout; the pad quantizes to zero and is sliced off on
    dequant)."""
    flat = leaf.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, s = quantize_blockwise(flat, block, bits=bits)
    if bits == 4:
        q = pack_int4(q)
    return {"q": q, "s": s}


def quantize_expert_tree(params, num_experts: int, *, bits: int = 8,
                         block: int = DEFAULT_BLOCK
                         ) -> Tuple[Any, Dict[str, Any]]:
    """Replace every stacked expert leaf in a params tree with its
    blockwise-quantized payload.  Returns (params_q, spec) where spec
    maps "path/key" -> {"shape", "dtype", "bits", "block"} — the static
    metadata `dequantize_expert_tree` rebuilds from (shapes cannot ride
    the pytree)."""
    spec: Dict[str, Any] = {}

    def walk(node, path):
        if _is_expert_dict(node, num_experts):
            out = dict(node)
            for k in EXPERT_KEYS:
                leaf = node[k]
                spec["/".join(path + (k,))] = {
                    "shape": tuple(int(d) for d in leaf.shape),
                    "dtype": leaf.dtype, "bits": bits, "block": block}
                out[k] = _q_leaf(leaf, block, bits)
            return out
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    params_q = walk(params, ())
    if not spec:
        raise ValueError(
            f"no stacked [E={num_experts}, ...] expert leaves found — "
            "is this an MoE params tree?")
    return params_q, spec


def dequantize_expert_tree(params_q, spec: Dict[str, Any]):
    """In-program inverse of `quantize_expert_tree`: the jitted decode/
    prefill programs call this first, so the RESIDENT buffers stay int
    and only the working copy is fp."""
    def walk(node, path):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            key = "/".join(path + (k,))
            meta = spec.get(key)
            if meta is not None:
                q = v["q"]
                if meta["bits"] == 4:
                    q = unpack_int4(q)
                flat = dequantize_blockwise(q, v["s"])
                n = 1
                for d in meta["shape"]:
                    n *= d
                out[k] = flat[:n].reshape(meta["shape"]) \
                    .astype(meta["dtype"])
            else:
                out[k] = walk(v, path + (k,))
        return out
    return walk(params_q, ())


def expert_bytes(spec: Dict[str, Any]) -> Dict[str, float]:
    """Analytic resident-bytes record: fp vs quantized expert storage
    (the serve.moe_expert_bytes gauges / bench detail row)."""
    fp = q = 0.0
    for meta in spec.values():
        n = 1
        for d in meta["shape"]:
            n *= d
        elem = jnp.dtype(meta["dtype"]).itemsize
        fp += n * elem
        nb = -(-n // meta["block"])          # scales, one f32 per block
        payload = n if meta["bits"] == 8 else n / 2
        q += payload + 4.0 * nb
    return {"fp_bytes": fp, "quantized_bytes": q,
            "ratio": (fp / q) if q else None}
