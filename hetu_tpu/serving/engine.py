"""Serving engine: continuous batching + paged KV cache over the
training stack.

The engine runs THREE jitted programs, all static-shape (TPU-shaped —
one compile each, no shape-bucket churn):

  * prefill chunk   — `models/generation.extend_cache` over a
                      [1, prefill_chunk] token block into a per-request
                      scratch cache.  Prefill is its OWN program
                      (disaggregated from decode) and advances ONE chunk
                      per engine step, interleaved with the decode
                      batch: a long prompt costs extra engine steps for
                      its own slot, never a multi-chunk stall in the
                      other requests' inter-token gap.
  * prefill write   — scatter the scratch K/V into the slot's pool pages
                      (quantizing in the int8 page mode).
  * decode step     — gather every slot's pages to dense views, run
                      `decode_step_slots` over the full slot batch with
                      per-slot positions, scatter the new token K/V back
                      into the pool, argmax.  Inactive slots ride along
                      pointing at the null page.  Under HETU_TPU_PALLAS
                      (exact fp pages + passing shape gate) the program
                      is the GATHER-FREE form instead: the Pallas
                      paged-attention kernel walks the page tables
                      directly (`models/generation.decode_step_paged`,
                      ops/pallas/paged_attention, docs/kernels.md).

Between device steps the host-side `Scheduler` admits/evicts at token
granularity and the engine stamps SLO metrics into the `obs` registry
(serve.* counters/gauges/histograms) and RunLog ``serve`` events — the
same observability spine training runs use, so `tools_obs_report.py`
reads a serving run like any other.

Decoding is greedy (per-request EOS, length budgets).  Model families:
llama + gpt, via the family dispatch in `models/generation`.

The optional `reshard` hook (`serving/reshard.LoadAdaptiveMesh`) is the
Hetis move: queue-depth tier changes re-shard the serving params through
the hot-switch ParamSlice machinery.

See docs/serving.md for the architecture and known limits.
"""
from __future__ import annotations

import dataclasses
import math
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.models.generation import (_check_context_length,
                                        decode_step_slots, extend_cache)
from hetu_tpu.obs.health import maybe_serving_health_monitor
from hetu_tpu.obs.metrics import MetricsRegistry, get_registry
from hetu_tpu.obs.runlog import RunLog, default_runlog_path
from hetu_tpu.serving.kv_pool import PagePool, PoolArrays
from hetu_tpu.serving.request import Request, RequestResult
from hetu_tpu.serving.scheduler import Scheduler
from hetu_tpu.serving.tracing import maybe_tracer
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.engine")


@dataclasses.dataclass
class ServeConfig:
    """Engine shape knobs (all static: they pick the compiled programs).

    num_pages=0 sizes the pool for FULL reservation —
    num_slots * (max_len / page_size) usable pages, so admission never
    waits on pages, only on slots.  Smaller pools trade queueing delay
    for memory (the scheduler's reserve-on-admit keeps it deadlock-free
    either way)."""
    num_slots: int = 8
    page_size: int = 16
    max_len: int = 256
    prefill_chunk: int = 32
    num_pages: int = 0
    kv_quant: str = "none"           # "none" (exact, default) | "int8"
    # MoE serving (HETU_TPU_MOE_DISPATCH, serving/experts.py): int8/int4
    # store the stacked [E, ...] expert weights resident-quantized
    # (KV-pool-style blockwise payloads + f32 scales, dequantized inside
    # the decode/prefill programs); gspmd (default) and fp32 leave the
    # params untouched.  Ignored for dense models.
    moe_dispatch: str = "gspmd"

    def __post_init__(self):
        if self.max_len % self.page_size:
            raise ValueError(f"max_len {self.max_len} must be a multiple "
                             f"of page_size {self.page_size}")
        if self.max_len % self.prefill_chunk:
            # the chunk program pads prompts to a chunk multiple; an
            # uneven tail would scatter past the [.., max_len, ..]
            # scratch cache (silently dropped by XLA — refuse instead of
            # leaning on out-of-bounds semantics)
            raise ValueError(f"max_len {self.max_len} must be a multiple "
                             f"of prefill_chunk {self.prefill_chunk}")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant {self.kv_quant!r} invalid; "
                             "choices: ('none', 'int8')")
        if self.moe_dispatch not in ("gspmd", "fp32", "int8", "int4"):
            raise ValueError(
                f"moe_dispatch {self.moe_dispatch!r} invalid; choices: "
                "('gspmd', 'fp32', 'int8', 'int4')")
        if self.num_pages == 0:
            self.num_pages = self.num_slots * (self.max_len
                                               // self.page_size)

    @staticmethod
    def from_flags(**overrides) -> "ServeConfig":
        """Defaults from the serving flag surface (utils/flags.py:
        HETU_TPU_KV_QUANT + the serve-shape flags); explicit kwargs
        win."""
        from hetu_tpu.utils import flags
        vals = dict(
            num_slots=flags.int_flag("HETU_TPU_SERVE_SLOTS"),
            page_size=flags.int_flag("HETU_TPU_SERVE_PAGE"),
            max_len=flags.int_flag("HETU_TPU_SERVE_MAX_LEN"),
            prefill_chunk=flags.int_flag("HETU_TPU_SERVE_PREFILL_CHUNK"),
            num_pages=flags.int_flag("HETU_TPU_SERVE_PAGES"),
            kv_quant=flags.str_flag("HETU_TPU_KV_QUANT"),
            moe_dispatch=flags.str_flag("HETU_TPU_MOE_DISPATCH"),
        )
        vals.update(overrides)
        return ServeConfig(**vals)


class ServingEngine:
    """Continuous-batching facade over (model, params)."""

    def __init__(self, model, params, config: Optional[ServeConfig] = None,
                 *, run_log: Optional[RunLog] = None,
                 registry: Optional[MetricsRegistry] = None,
                 reshard=None, tracer=None, health=None,
                 telemetry=None):
        self.model = model
        self.params = params
        self.config = config or ServeConfig.from_flags()
        c = model.config
        _check_context_length(c, self.config.max_len)
        n_kv = getattr(c, "num_key_value_heads", c.num_attention_heads)
        self.pool = PagePool(
            num_layers=c.num_hidden_layers,
            num_pages=self.config.num_pages,
            page_size=self.config.page_size,
            num_kv_heads=n_kv, head_dim=c.head_dim,
            dtype=c.compute_dtype, quant=self.config.kv_quant)
        self.scheduler = Scheduler(num_slots=self.config.num_slots,
                                   pool=self.pool,
                                   max_len=self.config.max_len)
        self.reshard = reshard
        self._registry = registry if registry is not None else get_registry()
        if run_log is None:
            path = default_runlog_path(None)
            run_log = RunLog(path) if path else None
            self._owns_runlog = run_log is not None
        else:
            self._owns_runlog = False
        self.run_log = run_log
        # the flight recorder (HETU_TPU_SERVE_TRACE) and the serving
        # health detectors (HETU_TPU_HEALTH) — both host-side only, both
        # a single None check when their flag is unset; explicit
        # instances win over the flag gates (tests, tools)
        self.tracer = tracer if tracer is not None else \
            maybe_tracer(run_log=self.run_log, registry=self._registry)
        self.health = health if health is not None else \
            maybe_serving_health_monitor(runlog=self.run_log,
                                         registry=self._registry)
        #: optional obs.aggregate.TelemetrySource: serve events ride the
        #: cluster telemetry push so tools_cluster.py sees this worker
        self.telemetry = telemetry
        self.steps_done = 0
        # numerics observatory (obs/numerics.py, HETU_TPU_NUMERICS):
        # read once at build — unset means the decode/write programs
        # below are byte-identical to the flag not existing (registered
        # identity contract).  When on, the int8 KV-page quantize sites
        # tap their exact roundtrip SNR into a stats pytree the wrapped
        # programs return alongside their outputs.
        from hetu_tpu.obs.numerics import numerics_enabled, record_every
        self._numerics = numerics_enabled()
        self._numerics_every = record_every()
        self._numerics_stats = None
        # the numerics detectors (quant_snr_collapse on kv_pages, etc.)
        # ride the same HETU_TPU_HEALTH gate as the serving monitor
        # above — without this the serving side would RECORD SNR but
        # never watch it
        from hetu_tpu.obs.health import maybe_numerics_health_monitor
        self._num_health = (maybe_numerics_health_monitor(
            runlog=self.run_log, registry=self._registry,
            source=self.telemetry) if self._numerics else None)

        # MoE: resident quantized expert weights (serving/experts.py).
        # Quantized ONCE here, dequantized inside the compiled programs
        # — the params tree the engine holds stays int8/int4 on the
        # expert share.  The reshard hook moves fp params; composing it
        # with the quantized tree would reshard int payloads it cannot
        # re-slice — refuse loudly.
        n_exp = getattr(c, "num_experts", 0) or 0
        self._moe_spec = None
        if n_exp > 0 and self.config.moe_dispatch in ("int8", "int4"):
            if self.reshard is not None:
                raise ValueError(
                    "resident-quantized MoE experts (moe_dispatch="
                    f"{self.config.moe_dispatch!r}) do not compose with "
                    "the reshard hook — use gspmd/fp32 dispatch or drop "
                    "the hook")
            from hetu_tpu.serving.experts import (expert_bytes,
                                                  quantize_expert_tree)
            bits = 8 if self.config.moe_dispatch == "int8" else 4
            self.params, self._moe_spec = quantize_expert_tree(
                params, n_exp, bits=bits)
            eb = expert_bytes(self._moe_spec)
            self._registry.set_gauge("serve.moe_expert_bytes",
                                     eb["quantized_bytes"])
            self._registry.set_gauge("serve.moe_expert_bytes_fp",
                                     eb["fp_bytes"])

        # per-request prefill scratch: a dense [L, 1, max_len] cache the
        # chunk program advances; template zeros reused (functionally)
        # for every admission
        shape = (c.num_hidden_layers, 1, self.config.max_len, n_kv,
                 c.head_dim)
        self._scratch = (jnp.zeros(shape, c.compute_dtype),
                         jnp.zeros(shape, c.compute_dtype))
        self._build_programs()

    # ------------------------------------------------------------ build
    def _use_paged_kernel(self) -> bool:
        """Route the decode program through the gather-free Pallas
        paged-attention kernel (ops/pallas/paged_attention) when the
        HETU_TPU_PALLAS surface and the kernel's shape gate allow.
        Exact fp pages only — the int8 page mode keeps the gather path
        (pages dequantize during the gather).  Evaluated once at build:
        the decision is static, like every other program shape."""
        if self.pool.quant != "none":
            return False
        from hetu_tpu.ops.pallas import paged_attention as _pa
        from hetu_tpu.ops.pallas import resolve_route
        c = self.model.config
        S = self.config.num_slots
        q_shape = (S, c.num_attention_heads, c.head_dim)
        pool_shape = (self.config.num_pages + 1, self.config.page_size,
                      self.pool.num_kv_heads, self.pool.head_dim)
        ok = _pa.compatible(q_shape, pool_shape,
                            (S, self.scheduler.max_pages), (S,))
        return resolve_route("paged_attn", ok)

    def _build_programs(self):
        model, pool = self.model, self.pool
        self.decode_paged = self._use_paged_kernel()

        if self.decode_paged:
            from hetu_tpu.models.generation import decode_step_paged

            def decode_fn(params, pool_tree, table, tokens, positions):
                # gather-free: the kernel walks the page table directly;
                # this token's K/V are scattered inside the step (the
                # write_token scatter is folded into the program)
                logits, nk, nv = decode_step_paged(
                    model, params, tokens, pool_tree[0], pool_tree[1],
                    table, positions)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, (nk, nv)
        else:
            def decode_fn(params, pool_tree, table, tokens, positions):
                ck, cv = pool.gather(pool_tree, table)
                logits, _, (kt, vt) = decode_step_slots(
                    model, params, tokens, (ck, cv), positions)
                new_tree = pool.write_token(pool_tree, table, positions,
                                            kt, vt)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, new_tree

        def chunk_fn(params, chunk, cache, start):
            return extend_cache(model, params, chunk, cache, start)

        def write_fn(pool_tree, pages_row, ks, vs):
            return pool.write_pages(pool_tree, pages_row, ks, vs)

        if self._moe_spec is not None:
            # resident int experts: the programs dequantize on entry, so
            # only the transient working copy is fp (the decode step's
            # expert HBM read is the quantized payload)
            from hetu_tpu.serving.experts import dequantize_expert_tree
            spec = self._moe_spec
            base_decode_fp, base_chunk_fp = decode_fn, chunk_fn

            def decode_fn(params, pool_tree, table, tokens, positions):
                return base_decode_fp(dequantize_expert_tree(params, spec),
                                      pool_tree, table, tokens, positions)

            def chunk_fn(params, chunk, cache, start):
                return base_chunk_fp(dequantize_expert_tree(params, spec),
                                     chunk, cache, start)

        if self._numerics:
            # wrap the programs that contain quantize sites in a
            # numerics collector; their stats pytree rides out as one
            # extra output (empty when KV pages are exact).  The
            # unwrapped functions above ARE the unset-flag programs —
            # byte-identity by construction.
            from hetu_tpu.obs import numerics as _numerics
            base_decode, base_write = decode_fn, write_fn

            def decode_fn(params, pool_tree, table, tokens, positions):
                with _numerics.collecting() as col:
                    out = base_decode(params, pool_tree, table, tokens,
                                      positions)
                    stats = col.finalize()
                return out + (stats,)

            def write_fn(pool_tree, pages_row, ks, vs):
                with _numerics.collecting() as col:
                    tree = base_write(pool_tree, pages_row, ks, vs)
                    stats = col.finalize()
                return tree, stats

        # the pool tree is donated: the KV pool is the engine's dominant
        # allocation and it flows through every step — without donation
        # XLA would copy the whole pool to update one token per slot
        # (the engine always reassigns self.pool.arrays from the
        # returned tree, so the donated input is never reused)
        self._decode_jit = jax.jit(decode_fn, donate_argnums=(1,))
        self._chunk_jit = jax.jit(chunk_fn)
        self._write_jit = jax.jit(write_fn, donate_argnums=(0,))

    # ---------------------------------------------------- numerics taps
    def _run_decode(self, *args):
        """Dispatch the decode program, peeling the numerics stats
        output when the observatory wrapped it."""
        out = self._decode_jit(*args)
        if self._numerics:
            nxt, tree, stats = out
            self._note_numerics(stats)
            return nxt, tree
        return out

    def _run_write(self, *args):
        out = self._write_jit(*args)
        if self._numerics:
            tree, stats = out
            self._note_numerics(stats)
            return tree
        return out

    def _note_numerics(self, stats):
        if stats:
            self._numerics_stats = stats   # latest wins until recorded

    def _maybe_record_numerics(self):
        """Every HETU_TPU_NUMERICS_EVERY engine steps, host-fetch the
        latest stats pytree and fan it out through the one numerics
        sink (RunLog record + registry gauges + telemetry)."""
        if (not self._numerics or self._numerics_stats is None
                or self.steps_done % self._numerics_every):
            return
        from hetu_tpu.obs import numerics as _numerics
        try:
            host = jax.device_get(self._numerics_stats)
        except Exception:   # telemetry never kills an engine step
            self._numerics_stats = None
            return
        self._numerics_stats = None
        _numerics.record(host, step=self.steps_done,
                         registry=self._registry, runlog=self.run_log)
        if self._num_health is not None:
            self._num_health.observe(self.steps_done, host)

    def warmup(self):
        """Compile all three programs so the first request's TTFT is not
        a compile.  The dummy decode/write still target the null page
        (zero table/row), so pool CONTENT is untouched — but the pool
        trees are donated through the calls, so the returned trees must
        be committed back (discarding them would leave self.pool.arrays
        pointing at deleted buffers on donating backends)."""
        S, C = self.config.num_slots, self.config.prefill_chunk
        table = jnp.zeros((S, self.scheduler.max_pages), jnp.int32)
        toks = jnp.zeros(S, jnp.int32)
        pos = jnp.zeros(S, jnp.int32)
        nxt, tree = self._run_decode(self.params, self.pool.arrays.tree(),
                                     table, toks, pos)
        self.pool.arrays = PoolArrays.from_tree(tree)
        lg, cache = self._chunk_jit(self.params,
                                    jnp.zeros((1, C), jnp.int32),
                                    self._scratch, jnp.int32(0))
        row = jnp.zeros(self.scheduler.max_pages, jnp.int32)
        tree = self._run_write(self.pool.arrays.tree(), row,
                               cache[0][:, 0], cache[1][:, 0])
        self.pool.arrays = PoolArrays.from_tree(tree)
        jax.block_until_ready(nxt)
        return self

    # ----------------------------------------------------------- intake
    def submit(self, req: Request, now: Optional[float] = None):
        if now is not None:
            req.arrival_t = now
        self.scheduler.submit(req)
        self._registry.inc("serve.requests_submitted")
        self._registry.inc("serve.requests_submitted_class",
                           slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_submit(req)

    def _log_serve(self, **fields):
        """One serve event to every attached sink: the RunLog and (when
        a TelemetrySource rides along) the cluster telemetry push."""
        rec = None
        if self.run_log is not None:
            rec = self.run_log.log("serve", **fields)
        if self.telemetry is not None:
            if rec is None:
                rec = dict(fields, kind="serve", t=time.time())
            self.telemetry.note_event(rec)

    # ------------------------------------------------------------- step
    def step(self, now: float) -> List[RequestResult]:
        """One engine iteration at driver time `now`: admit every
        admissible queued request (reservation only), advance each
        PREFILLING slot by exactly ONE chunk, then one decode step over
        the slots whose prefill is complete.  One-chunk-per-step is the
        disaggregation contract: a long prompt adds engine steps for its
        own slot, never a multi-chunk stall to the decode batch's
        inter-token gap.  Returns requests that finished this step."""
        t0 = time.perf_counter()

        def clock() -> float:
            return now + (time.perf_counter() - t0)

        finished: List[RequestResult] = []
        while True:
            t_adm = clock()
            adm = self.scheduler.admit_next(t_adm)
            if adm is None:
                break
            slot_idx, st = adm
            st.prefilling = True
            st.prefill_cache = self._scratch
            if self.tracer is not None:
                self.tracer.on_admit(st.request, slot_idx, t_adm)
        if self.scheduler.queue:
            # admission declined with work queued: count the stall and
            # stamp the scheduler's reserve-on-admit attribution on
            # every waiting request (the counter must not depend on the
            # tracing flag — it is the registry's stall signal)
            reason = self.scheduler.last_stall or "none"
            self._registry.inc("serve.admission_stalls", reason=reason)
            if self.tracer is not None:
                self.tracer.on_stall(
                    [r.rid for r in self.scheduler.queue], reason)

        for i in self.scheduler.active_slots():
            st = self.scheduler.slots[i]
            if st is not None and st.prefilling:
                self._advance_prefill(i, st, clock, finished)

        active = [i for i in self.scheduler.active_slots()
                  if not self.scheduler.slots[i].prefilling]
        if active:
            td = time.perf_counter()
            # the decode batch's inputs are DERIVED from scheduler state
            # every step (single source of truth): last emitted token +
            # next write position per decoding slot; empty/prefilling
            # rows ride along at (0, 0) writing into their masked region
            tokens = np.zeros(self.config.num_slots, np.int32)
            positions = np.zeros(self.config.num_slots, np.int32)
            for i in active:
                st = self.scheduler.slots[i]
                tokens[i] = st.generated[-1]
                positions[i] = st.pos
            nxt, pool_tree = self._run_decode(
                self.params, self.pool.arrays.tree(),
                jnp.asarray(self.scheduler.page_table),
                jnp.asarray(tokens), jnp.asarray(positions))
            nxt = np.asarray(nxt)
            self.pool.arrays = PoolArrays.from_tree(pool_tree)
            decode_wall = time.perf_counter() - td
            self._registry.inc("serve.decode_steps")
            # token_latency_s is the USER-visible inter-token gap: every
            # active slot advances one token per decode step, so the gap
            # IS the step wall.  The amortized per-token engine cost
            # (wall / active slots — the throughput number) is its own
            # series; conflating them would understate latency by up to
            # num_slots x.
            self._registry.observe("serve.token_latency_s", decode_wall)
            self._registry.observe("serve.token_cost_s",
                                   decode_wall / len(active))
            tnow = clock()
            n_done0 = len(finished)
            for i in active:
                st = self.scheduler.slots[i]
                tok = int(nxt[i])
                st.generated.append(tok)
                st.pos += 1
                self._registry.inc("serve.tokens_out")
                if self.tracer is not None:
                    self.tracer.on_token(st.request, tnow)
                self._maybe_finish(i, st, tok, tnow, finished)
            if self.tracer is not None and len(finished) > n_done0:
                # an eviction changed the batch composition: split the
                # survivors' decode segments so the boundary is visible
                survivors = [self.scheduler.slots[i].request.rid
                             for i in self.scheduler.active_slots()
                             if not self.scheduler.slots[i].prefilling]
                if survivors:
                    self.tracer.on_split(survivors, tnow, "evict")

        self.steps_done += 1
        self._maybe_record_numerics()
        self._registry.set_gauge("serve.queue_depth",
                                 self.scheduler.queue_depth)
        self._registry.set_gauge("serve.slot_occupancy",
                                 self.scheduler.occupancy)
        self._registry.set_gauge("serve.page_util", self.pool.utilization)
        if self.health is not None:
            self.health.observe_step(
                self.steps_done, queue_depth=self.scheduler.queue_depth,
                page_util=self.pool.utilization, t=clock())

        if self.reshard is not None:
            tier = self.reshard.observe(self.scheduler.queue_depth)
            if tier is not None:
                t_pause0 = clock()
                with self._registry.timer("serve.reshard_s"):
                    self.params = self.reshard.reshard(self.params, tier)
                t_pause1 = clock()
                self._registry.inc("serve.reshards")
                if self.tracer is not None:
                    paused = [self.scheduler.slots[i].request.rid
                              for i in self.scheduler.active_slots()
                              if not self.scheduler.slots[i].prefilling]
                    self.tracer.on_pause(paused, t_pause0, t_pause1,
                                         tier=tier)
                self._log_serve(event="reshard", tier=tier,
                                strategy=self.reshard.describe(tier),
                                now=t_pause1,
                                pause_s=t_pause1 - t_pause0,
                                queue_depth=self.scheduler.queue_depth)
        return finished

    # ---------------------------------------------------------- prefill
    def _advance_prefill(self, slot_idx: int, st, clock, finished):
        """Run ONE prefill chunk for a prefilling slot; on the last
        chunk, scatter the scratch K/V into the slot's pages, emit the
        first token, and join the decode batch."""
        req = st.request
        plen = req.prompt_len
        C = self.config.prefill_chunk
        padded = math.ceil(plen / C) * C
        s = st.chunks_done * C
        ids = np.zeros(C, np.int32)
        seg = req.prompt[s: min(s + C, plen)]
        ids[: len(seg)] = seg
        logits, st.prefill_cache = self._chunk_jit(
            self.params, jnp.asarray(ids[None]), st.prefill_cache,
            jnp.int32(s))
        st.chunks_done += 1
        st.stats.prefill_chunks += 1
        self._registry.inc("serve.prefill_chunks")
        if s + C < padded:
            if self.tracer is not None:
                self.tracer.on_chunk(req, clock(), st.chunks_done)
            return                        # more chunks: next engine step
        # first generated token: argmax at the last VALID prompt position
        # of the final chunk (padding tail positions carry garbage)
        t1 = int(np.argmax(np.asarray(logits[0, plen - 1 - s])))

        pages_row = np.full(self.scheduler.max_pages, PagePool.NULL_PAGE,
                            np.int32)
        pages_row[: len(st.pages)] = st.pages
        tree = self._run_write(self.pool.arrays.tree(),
                               jnp.asarray(pages_row),
                               st.prefill_cache[0][:, 0],
                               st.prefill_cache[1][:, 0])
        self.pool.arrays = PoolArrays.from_tree(tree)

        st.prefilling = False
        st.prefill_cache = None
        st.pos = plen
        st.generated.append(t1)
        tnow = clock()
        st.stats.first_token_t = tnow
        ttft = st.stats.ttft_s
        self._registry.observe("serve.ttft_s", ttft)
        self._registry.observe("serve.ttft_s_class", ttft,
                               slo_class=req.slo.name)
        if st.stats.queue_wait_s is not None:
            self._registry.observe("serve.queue_wait_s",
                                   st.stats.queue_wait_s)
        self._registry.inc("serve.tokens_out")
        if self.tracer is not None:
            self.tracer.on_first_token(req, slot_idx, tnow,
                                       chunk=st.chunks_done)
        if self.health is not None:
            self.health.observe_ttft(ttft, step=self.steps_done, t=tnow)
        self._log_serve(event="admit", req=req.rid,
                        slot=slot_idx, prompt_len=plen,
                        chunks=st.stats.prefill_chunks, ttft_s=ttft,
                        queue_wait_s=st.stats.queue_wait_s, now=tnow,
                        slo_class=req.slo.name,
                        queue_depth=self.scheduler.queue_depth,
                        page_util=self.pool.utilization)
        self._maybe_finish(slot_idx, st, t1, tnow, finished)

    # ----------------------------------------------------------- finish
    def _maybe_finish(self, slot_idx: int, st, tok: int, tnow: float,
                      finished):
        req = st.request
        reason = None
        if req.eos_token_id is not None and tok == req.eos_token_id:
            reason = "eos"
        elif len(st.generated) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        st.stats.done_t = tnow
        res = RequestResult(rid=req.rid, tokens=list(st.generated),
                            finished_reason=reason, stats=st.stats)
        self.scheduler.release(slot_idx)
        self._registry.inc("serve.requests_done")
        self._registry.inc("serve.requests_done_class",
                           slo_class=req.slo.name)
        if st.stats.e2e_s is not None:
            self._registry.observe("serve.e2e_s", st.stats.e2e_s)
            self._registry.observe("serve.e2e_s_class", st.stats.e2e_s,
                                   slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_finish(req, slot_idx, reason, tnow,
                                  tokens=len(res.tokens),
                                  e2e_s=st.stats.e2e_s)
        self._log_serve(
            event="done", req=req.rid, slot=slot_idx,
            reason=reason, tokens=len(res.tokens),
            ttft_s=st.stats.ttft_s, e2e_s=st.stats.e2e_s,
            tokens_per_s=res.tokens_per_s, now=tnow,
            slo_class=req.slo.name,
            slo_ttft_s=req.slo.ttft_s, slo_token_gap_s=req.slo.token_gap_s,
            queue_depth=self.scheduler.queue_depth,
            slot_occupancy=self.scheduler.occupancy,
            page_util=self.pool.utilization)
        finished.append(res)

    # -------------------------------------------------------------- run
    def run(self, requests: Sequence[Request], *, start: float = 0.0,
            on_step=None) -> List[RequestResult]:
        """Drive the engine over a request trace to completion under a
        virtual clock: arrivals come from each request's `arrival_t`,
        and time advances by the real wall cost of each engine step —
        deterministic token output, realistic latency accounting.

        ``on_step(step_index)`` (optional) runs at each step boundary
        INSIDE the timed window, so any wall time it spends (a chaos
        slow-decode injection, a host-side stall) inflates the virtual
        clock exactly like a slow engine step would — the hook the
        chaos harness drives instead of forking this loop."""
        pending = sorted(requests, key=lambda r: (r.arrival_t, r.rid))
        now = start
        results: List[RequestResult] = []
        i = 0
        step_idx = 0
        while True:
            while i < len(pending) and pending[i].arrival_t <= now + 1e-12:
                self.submit(pending[i])
                i += 1
            if not self.scheduler.active_slots() and not self.scheduler.queue:
                if i >= len(pending):
                    break
                now = max(now, pending[i].arrival_t)   # idle-skip to next
                continue
            t0 = time.perf_counter()
            if on_step is not None:
                on_step(step_idx)
            results.extend(self.step(now))
            now += time.perf_counter() - t0
            step_idx += 1
        if self.run_log is not None or self.telemetry is not None:
            n_tokens = sum(len(r.tokens) for r in results)
            elapsed = max(now - start, 1e-9)
            self._log_serve(event="report",
                            requests=len(results), tokens=n_tokens,
                            elapsed_s=elapsed, now=now,
                            tokens_per_s=n_tokens / elapsed)
        return sorted(results, key=lambda r: r.rid)

    def close(self):
        if self._owns_runlog and self.run_log is not None:
            self.run_log.close()
