"""Serving engine: continuous batching + paged KV cache over the
training stack.

The engine runs THREE jitted programs, all static-shape (TPU-shaped —
one compile each, no shape-bucket churn):

  * prefill chunk   — `models/generation.extend_cache` over a
                      [1, prefill_chunk] token block into a per-request
                      scratch cache.  Prefill is its OWN program
                      (disaggregated from decode) and advances ONE chunk
                      per engine step, interleaved with the decode
                      batch: a long prompt costs extra engine steps for
                      its own slot, never a multi-chunk stall in the
                      other requests' inter-token gap.
  * prefill write   — scatter the scratch K/V into the slot's pool pages
                      (quantizing in the int8 page mode).
  * decode step     — gather every slot's pages to dense views, run
                      `decode_step_slots` over the full slot batch with
                      per-slot positions, scatter the new token K/V back
                      into the pool, argmax.  Inactive slots ride along
                      pointing at the null page.  Under HETU_TPU_PALLAS
                      (exact fp pages + passing shape gate) the program
                      is the GATHER-FREE form instead: the Pallas
                      paged-attention kernel walks the page tables
                      directly (`models/generation.decode_step_paged`,
                      ops/pallas/paged_attention, docs/kernels.md).

Between device steps the host-side `Scheduler` admits/evicts at token
granularity and the engine stamps SLO metrics into the `obs` registry
(serve.* counters/gauges/histograms) and RunLog ``serve`` events — the
same observability spine training runs use, so `tools_obs_report.py`
reads a serving run like any other.

Decoding is greedy by default (per-request EOS, length budgets); the
production decoding subsystem layers on top, all default-off with
registered decode-program byte-identity contracts: in-graph seeded
sampling (HETU_TPU_SERVE_SAMPLE, serving/sampling.py), the radix
prefix cache (HETU_TPU_SERVE_PREFIX_CACHE, serving/prefix_cache.py —
shared prompts admit with their KV pages resident), speculative
decoding (HETU_TPU_SPEC_DECODE, serving/spec_decode.py — the decode
program becomes a batched k+1-token verify), and SLO-class preemptive
admission (HETU_TPU_SERVE_PREEMPT).  Model families: llama + gpt, via
the family dispatch in `models/generation`.

The optional `reshard` hook (`serving/reshard.LoadAdaptiveMesh`) is the
Hetis move: queue-depth tier changes re-shard the serving params through
the hot-switch ParamSlice machinery.

See docs/serving.md for the architecture and known limits.
"""
from __future__ import annotations

import dataclasses
import math
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu.models.generation import (_check_context_length,
                                        decode_step_slots, extend_cache)
from hetu_tpu.obs.health import maybe_serving_health_monitor
from hetu_tpu.obs.metrics import MetricsRegistry, get_registry
from hetu_tpu.obs.runlog import RunLog, default_runlog_path
from hetu_tpu.serving.kv_pool import PagePool, PoolArrays
from hetu_tpu.serving.request import (Request, RequestResult,
                                      RequestStats, rid_sampled)
from hetu_tpu.serving.scheduler import Scheduler
from hetu_tpu.serving.tracing import maybe_tracer
from hetu_tpu.utils.logging import get_logger

logger = get_logger("serving.engine")


def first_token_from_logits(req, logits_row, position: int, *,
                            sampling: bool) -> int:
    """The TTFT token from a final prefill chunk's logits row: argmax
    (the default), or the seeded sampler for sampling requests — the
    (seed, position) key derivation every sampling site shares.  A pure
    function of (request, logits, position): the engine's colocated
    prefill and the disaggregated prefill tier (serving/disagg.py) both
    call it, which is what makes the two paths token-identical."""
    if not (sampling and req.sampling.temperature > 0):
        return int(np.argmax(np.asarray(logits_row)))
    from hetu_tpu.serving.sampling import sample_tokens
    sp = req.sampling
    tok = sample_tokens(
        jnp.asarray(logits_row)[None],
        jnp.asarray([sp.seed & 0xFFFFFFFF], jnp.uint32),
        jnp.asarray([position], jnp.int32),
        jnp.asarray([sp.temperature], jnp.float32),
        jnp.asarray([sp.top_k], jnp.int32),
        jnp.asarray([sp.top_p], jnp.float32))
    return int(np.asarray(tok)[0])


@dataclasses.dataclass
class ServeConfig:
    """Engine shape knobs (all static: they pick the compiled programs).

    num_pages=0 sizes the pool for FULL reservation —
    num_slots * (max_len / page_size) usable pages, so admission never
    waits on pages, only on slots.  Smaller pools trade queueing delay
    for memory (the scheduler's reserve-on-admit keeps it deadlock-free
    either way)."""
    num_slots: int = 8
    page_size: int = 16
    max_len: int = 256
    prefill_chunk: int = 32
    num_pages: int = 0
    kv_quant: str = "none"      # "none" (exact, default) | "int8" | "int4"
    # MoE serving (HETU_TPU_MOE_DISPATCH, serving/experts.py): int8/int4
    # store the stacked [E, ...] expert weights resident-quantized
    # (KV-pool-style blockwise payloads + f32 scales, dequantized inside
    # the decode/prefill programs); gspmd (default) and fp32 leave the
    # params untouched.  Ignored for dense models.
    moe_dispatch: str = "gspmd"
    # -- the production decoding subsystem (all default-off: the unset
    #    programs are byte-identical to the pre-subsystem engine,
    #    enforced by the flag-identity sweep) -------------------------
    #: in-graph temperature/top-k/top-p sampling (HETU_TPU_SERVE_SAMPLE,
    #: serving/sampling.py): the decode program takes per-slot seeded
    #: PRNG keys; greedy rows stay argmax bit-for-bit
    sampling: bool = False
    #: speculative decoding (HETU_TPU_SPEC_DECODE, spec_decode.py):
    #: "none" | "ngram" | "model" — verify spec_k drafts + 1 in one
    #: batched step; "model" runs a resident-quantized small draft
    #: model (pass draft_model=/draft_params= to the engine) and
    #: verifies with the full stochastic p/q rejection rule
    spec_decode: str = "none"
    spec_k: int = 4
    #: radix prefix cache (HETU_TPU_SERVE_PREFIX_CACHE,
    #: prefix_cache.py): shared page-aligned prompt prefixes admit with
    #: their KV pages already resident (COW refcounts in kv_pool.py)
    prefix_cache: bool = False
    prefix_cache_pages: int = 0      # 0 = bounded by pool pressure only
    #: SLO-class-aware preemptive admission (HETU_TPU_SERVE_PREEMPT):
    #: under slot/page pressure a strictly-higher-priority queued
    #: request evicts-and-requeues the lowest-priority live slot
    preempt: bool = False
    #: per-tenant admission quotas (HETU_TPU_SERVE_QUOTAS,
    #: serving/request.py TenantQuota): caps each tenant's LIVE
    #: slots/pages at admission; {} (default) = quota-free — the
    #: admission path is byte-identical to the pre-tenant engine
    quotas: dict = dataclasses.field(default_factory=dict)
    #: serve-event RunLog sampling (HETU_TPU_RUNLOG_SERVE_SAMPLE): only
    #: a deterministic hashed 1-in-N of rids (request.py rid_sampled)
    #: emit admit/done/preempt events,
    #: stamped sample_weight=N (slo_report re-weights).  Registry
    #: counters stay exact.  1 (default) = every event, byte-identical
    #: RunLog to the pre-sampling engine
    serve_sample: int = 1
    # -- the fault-tolerance layer (docs/fault_tolerance.md; all
    #    default-off, all host-side policy: the compiled programs are
    #    byte-identical at any setting — registered identity contracts)
    #: per-request retry budget after a replica death
    #: (HETU_TPU_SERVE_RETRY): fail_over() requeues each in-flight
    #: request up to this many times ('replica_lost' stall reason);
    #: past the budget it terminates as 'retry_exhausted'.  0 = no
    #: retries
    retry_budget: int = 0
    #: enforce SLOClass.deadline_s (HETU_TPU_SERVE_DEADLINE): each step
    #: sweeps queued and live requests, expiring any older than its
    #: class deadline as 'deadline_exceeded'
    deadline: bool = False
    #: sustained-pressure brownout shedding (HETU_TPU_SERVE_BROWNOUT):
    #: page utilization >= brownout_page_high with >= brownout_queue_min
    #: queued for brownout_streak consecutive steps sheds the
    #: lowest-priority queued band ('brownout_shed')
    brownout: bool = False
    brownout_page_high: float = 0.95
    brownout_queue_min: int = 1
    brownout_streak: int = 4
    #: migrate the KV pool through LoadAdaptiveMesh tier changes
    #: (HETU_TPU_SERVE_KV_REPAGE, serving/reshard.py reshard_pool)
    kv_repage: bool = False

    def __post_init__(self):
        if self.max_len % self.page_size:
            raise ValueError(f"max_len {self.max_len} must be a multiple "
                             f"of page_size {self.page_size}")
        if self.max_len % self.prefill_chunk:
            # the chunk program pads prompts to a chunk multiple; an
            # uneven tail would scatter past the [.., max_len, ..]
            # scratch cache (silently dropped by XLA — refuse instead of
            # leaning on out-of-bounds semantics)
            raise ValueError(f"max_len {self.max_len} must be a multiple "
                             f"of prefill_chunk {self.prefill_chunk}")
        if self.kv_quant not in ("none", "int8", "int4"):
            raise ValueError(f"kv_quant {self.kv_quant!r} invalid; "
                             "choices: ('none', 'int8', 'int4')")
        if self.moe_dispatch not in ("gspmd", "fp32", "int8", "int4"):
            raise ValueError(
                f"moe_dispatch {self.moe_dispatch!r} invalid; choices: "
                "('gspmd', 'fp32', 'int8', 'int4')")
        if self.spec_decode not in ("none", "ngram", "model"):
            raise ValueError(
                f"spec_decode {self.spec_decode!r} invalid; choices: "
                "('none', 'ngram', 'model')")
        if self.spec_decode != "none" and self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        if self.serve_sample < 1:
            raise ValueError(f"serve_sample must be >= 1, "
                             f"got {self.serve_sample}")
        if self.retry_budget < 0:
            raise ValueError(f"retry_budget must be >= 0, "
                             f"got {self.retry_budget}")
        if not 0.0 < self.brownout_page_high <= 1.0:
            raise ValueError(f"brownout_page_high must be in (0, 1], "
                             f"got {self.brownout_page_high}")
        if self.brownout_streak < 1 or self.brownout_queue_min < 1:
            raise ValueError(
                "brownout_streak and brownout_queue_min must be >= 1, "
                f"got {self.brownout_streak}/{self.brownout_queue_min}")
        if self.num_pages == 0:
            self.num_pages = self.num_slots * (self.max_len
                                               // self.page_size)

    @property
    def lookahead(self) -> int:
        """Extra cache positions a verify step may write past the
        sequence head (0 without speculative decoding) — widens every
        page reservation (scheduler.py)."""
        return self.spec_k if self.spec_decode != "none" else 0

    @staticmethod
    def from_flags(**overrides) -> "ServeConfig":
        """Defaults from the serving flag surface (utils/flags.py:
        HETU_TPU_KV_QUANT + the serve-shape flags); explicit kwargs
        win."""
        from hetu_tpu.serving.request import parse_quotas
        from hetu_tpu.utils import flags
        vals = dict(
            num_slots=flags.int_flag("HETU_TPU_SERVE_SLOTS"),
            page_size=flags.int_flag("HETU_TPU_SERVE_PAGE"),
            max_len=flags.int_flag("HETU_TPU_SERVE_MAX_LEN"),
            prefill_chunk=flags.int_flag("HETU_TPU_SERVE_PREFILL_CHUNK"),
            num_pages=flags.int_flag("HETU_TPU_SERVE_PAGES"),
            kv_quant=flags.str_flag("HETU_TPU_KV_QUANT"),
            moe_dispatch=flags.str_flag("HETU_TPU_MOE_DISPATCH"),
            sampling=flags.bool_flag("HETU_TPU_SERVE_SAMPLE"),
            spec_decode=flags.str_flag("HETU_TPU_SPEC_DECODE"),
            spec_k=flags.int_flag("HETU_TPU_SPEC_K"),
            prefix_cache=flags.bool_flag("HETU_TPU_SERVE_PREFIX_CACHE"),
            prefix_cache_pages=flags.int_flag("HETU_TPU_SERVE_PREFIX_PAGES"),
            preempt=flags.bool_flag("HETU_TPU_SERVE_PREEMPT"),
            quotas=parse_quotas(flags.str_flag("HETU_TPU_SERVE_QUOTAS")),
            serve_sample=flags.int_flag("HETU_TPU_RUNLOG_SERVE_SAMPLE"),
            retry_budget=flags.int_flag("HETU_TPU_SERVE_RETRY"),
            deadline=flags.bool_flag("HETU_TPU_SERVE_DEADLINE"),
            brownout=flags.bool_flag("HETU_TPU_SERVE_BROWNOUT"),
            kv_repage=flags.bool_flag("HETU_TPU_SERVE_KV_REPAGE"),
        )
        vals.update(overrides)
        return ServeConfig(**vals)


class ServingEngine:
    """Continuous-batching facade over (model, params)."""

    def __init__(self, model, params, config: Optional[ServeConfig] = None,
                 *, run_log: Optional[RunLog] = None,
                 registry: Optional[MetricsRegistry] = None,
                 reshard=None, tracer=None, health=None,
                 telemetry=None, drafter=None, draft_model=None,
                 draft_params=None, cost_model=None):
        self.model = model
        self.params = params
        self.config = config or ServeConfig.from_flags()
        c = model.config
        _check_context_length(c, self.config.max_len)
        n_kv = getattr(c, "num_key_value_heads", c.num_attention_heads)
        self.pool = PagePool(
            num_layers=c.num_hidden_layers,
            num_pages=self.config.num_pages,
            page_size=self.config.page_size,
            num_kv_heads=n_kv, head_dim=c.head_dim,
            dtype=c.compute_dtype, quant=self.config.kv_quant)
        # radix prefix cache (serving/prefix_cache.py): shared prompt
        # prefixes admit with their pages already resident
        self.prefix_cache = None
        if self.config.prefix_cache:
            from hetu_tpu.serving.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(
                self.pool, max_pages=self.config.prefix_cache_pages)
        self.scheduler = Scheduler(num_slots=self.config.num_slots,
                                   pool=self.pool,
                                   max_len=self.config.max_len,
                                   prefix_cache=self.prefix_cache,
                                   lookahead=self.config.lookahead,
                                   quotas=self.config.quotas,
                                   retry_budget=self.config.retry_budget)
        # per-request cost ledger (serving/costs.py): when a CostModel
        # rides along, every done event carries analytic cost_* fields
        # (prefill/decode FLOPs, page-seconds, KV byte-seconds, wire
        # bytes) for slo_report's per-tenant cost attribution
        self.ledger = None
        if cost_model is not None:
            from hetu_tpu.serving.costs import CostLedger
            self.ledger = CostLedger(cost_model)
        # speculative decoding (serving/spec_decode.py): host drafter +
        # the batched verify program built below; `drafter=` overrides
        # the config mode with any Drafter instance.  spec_decode=
        # 'model' builds a ModelDrafter from draft_model/draft_params
        # (resident-quantized; verified with the stochastic p/q rule)
        from hetu_tpu.serving.spec_decode import make_drafter
        if drafter is not None and self.config.spec_decode == "none":
            # the reservation lookahead and the verify program are both
            # sized by the config — a drafter without them would write
            # past reservations
            raise ValueError("a custom drafter needs spec_decode set "
                             "(e.g. ServeConfig(spec_decode='ngram')) so "
                             "the verify program and page lookahead exist")
        draft_kw = ({"model": draft_model, "params": draft_params}
                    if self.config.spec_decode == "model"
                    and draft_model is not None else {})
        self.drafter = (drafter if drafter is not None
                        else make_drafter(self.config.spec_decode,
                                          **draft_kw))
        self.spec = self.drafter is not None
        #: stochastic drafters report their proposal distribution and
        #: are verified with the full p/q rejection rule in-graph
        self.spec_stochastic = bool(
            self.spec and getattr(self.drafter, "stochastic", False))
        #: per-rid preemption counts + the work counters accrued before
        #: each requeue (requests survive requeues; their SlotState —
        #: and its RequestStats — does not): folded back into the final
        #: done event so acceptance-rate/chunk accounting describes the
        #: whole run, not just the last incarnation
        self._preempt_counts = {}
        self._carried_stats = {}
        #: fault-termination results produced OUTSIDE step() — fail_over
        #: runs between steps (the run() on_step hook), so its
        #: retry-exhausted casualties park here until the next step
        #: drains them into its finished list
        self._fault_results: List[RequestResult] = []
        #: consecutive steps at brownout pressure (the shed streak)
        self._brownout_hot = 0
        #: driver-clock time at the end of the last step — the default
        #: timestamp for between-step fault events (fail_over)
        self._last_clock = 0.0
        self.reshard = reshard
        self._registry = registry if registry is not None else get_registry()
        if run_log is None:
            path = default_runlog_path(None)
            run_log = RunLog(path) if path else None
            self._owns_runlog = run_log is not None
        else:
            self._owns_runlog = False
        self.run_log = run_log
        #: timestamp basis every serve event/span declares (the engine
        #: drives a virtual DRIVER clock in run()/tests; a live server
        #: embedding the engine on wall time sets "wall" so the fleet
        #: stitcher refuses to mix the two)
        self.clock_basis = "driver"
        # the flight recorder (HETU_TPU_SERVE_TRACE) and the serving
        # health detectors (HETU_TPU_HEALTH) — both host-side only, both
        # a single None check when their flag is unset; explicit
        # instances win over the flag gates (tests, tools)
        self.tracer = tracer if tracer is not None else \
            maybe_tracer(run_log=self.run_log, registry=self._registry)
        self.health = health if health is not None else \
            maybe_serving_health_monitor(runlog=self.run_log,
                                         registry=self._registry)
        #: optional obs.aggregate.TelemetrySource: serve events ride the
        #: cluster telemetry push so tools_cluster.py sees this worker
        self.telemetry = telemetry
        self.steps_done = 0
        # numerics observatory (obs/numerics.py, HETU_TPU_NUMERICS):
        # read once at build — unset means the decode/write programs
        # below are byte-identical to the flag not existing (registered
        # identity contract).  When on, the int8 KV-page quantize sites
        # tap their exact roundtrip SNR into a stats pytree the wrapped
        # programs return alongside their outputs.
        from hetu_tpu.obs.numerics import numerics_enabled, record_every
        self._numerics = numerics_enabled()
        self._numerics_every = record_every()
        self._numerics_stats = None
        # the numerics detectors (quant_snr_collapse on kv_pages, etc.)
        # ride the same HETU_TPU_HEALTH gate as the serving monitor
        # above — without this the serving side would RECORD SNR but
        # never watch it
        from hetu_tpu.obs.health import maybe_numerics_health_monitor
        self._num_health = (maybe_numerics_health_monitor(
            runlog=self.run_log, registry=self._registry,
            source=self.telemetry) if self._numerics else None)

        # MoE: resident quantized expert weights (serving/experts.py).
        # Quantized ONCE here, dequantized inside the compiled programs
        # — the params tree the engine holds stays int8/int4 on the
        # expert share.  The reshard hook moves fp params; composing it
        # with the quantized tree would reshard int payloads it cannot
        # re-slice — refuse loudly.
        n_exp = getattr(c, "num_experts", 0) or 0
        self._moe_spec = None
        if n_exp > 0 and self.config.moe_dispatch in ("int8", "int4"):
            if self.reshard is not None:
                raise ValueError(
                    "resident-quantized MoE experts (moe_dispatch="
                    f"{self.config.moe_dispatch!r}) do not compose with "
                    "the reshard hook — use gspmd/fp32 dispatch or drop "
                    "the hook")
            from hetu_tpu.serving.experts import (expert_bytes,
                                                  quantize_expert_tree)
            bits = 8 if self.config.moe_dispatch == "int8" else 4
            self.params, self._moe_spec = quantize_expert_tree(
                params, n_exp, bits=bits)
            eb = expert_bytes(self._moe_spec)
            self._registry.set_gauge("serve.moe_expert_bytes",
                                     eb["quantized_bytes"])
            self._registry.set_gauge("serve.moe_expert_bytes_fp",
                                     eb["fp_bytes"])

        # per-request prefill scratch: a dense [L, 1, max_len] cache the
        # chunk program advances; template zeros reused (functionally)
        # for every admission
        shape = (c.num_hidden_layers, 1, self.config.max_len, n_kv,
                 c.head_dim)
        self._scratch = (jnp.zeros(shape, c.compute_dtype),
                         jnp.zeros(shape, c.compute_dtype))
        self._build_programs()

    # ------------------------------------------------------------ build
    def _use_paged_kernel(self) -> bool:
        """Route the decode program through the gather-free Pallas
        paged-attention kernel (ops/pallas/paged_attention) when the
        HETU_TPU_PALLAS surface and the kernel's shape gate allow.
        int8/int4 pages dequantize IN-KERNEL (the scales ride in as
        extra operands; int4 pages store packed nibble pairs, so the
        stored head dim is head_dim // 2).  Speculative decoding routes
        the multi-query `paged_verify` kernel instead — same pages, k+1
        causally-masked query positions per slot per launch.  Evaluated
        once at build: the decision is static, like every other program
        shape."""
        from hetu_tpu.ops.pallas import paged_attention as _pa
        from hetu_tpu.ops.pallas import resolve_route
        c = self.model.config
        S = self.config.num_slots
        hd_p = (self.pool.head_dim // 2 if self.pool.quant == "int4"
                else self.pool.head_dim)
        pool_shape = (self.config.num_pages + 1, self.config.page_size,
                      self.pool.num_kv_heads, hd_p)
        table_shape = (S, self.scheduler.max_pages)
        if self.spec:
            q_shape = (S, self.config.spec_k + 1,
                       c.num_attention_heads, c.head_dim)
            ok = _pa.verify_compatible(q_shape, pool_shape, table_shape,
                                       (S,), quant=self.pool.quant)
            return resolve_route("paged_verify", ok)
        q_shape = (S, c.num_attention_heads, c.head_dim)
        ok = _pa.compatible(q_shape, pool_shape, table_shape, (S,),
                            quant=self.pool.quant)
        return resolve_route("paged_attn", ok)

    def _build_programs(self):
        model, pool = self.model, self.pool
        self.decode_paged = self._use_paged_kernel()
        sampling_on = self.config.sampling

        def pick_token(logits, positions, sample_args):
            """Next token per slot: plain argmax (the byte-identical
            default), or the in-graph sampler when the engine was built
            with HETU_TPU_SERVE_SAMPLE (serving/sampling.py; greedy
            rows still argmax inside it)."""
            if not sampling_on:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            from hetu_tpu.serving.sampling import sample_tokens
            seeds, temps, top_ks, top_ps = sample_args
            # the emitted token's sequence position is positions + 1
            # (its input rides at `positions`) — the (seed, position)
            # key derivation every sampling site in the engine shares
            return sample_tokens(logits, seeds, positions + 1,
                                 temps, top_ks, top_ps)

        if self.decode_paged:
            from hetu_tpu.models.generation import decode_step_paged

            def decode_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                # gather-free: the kernel walks the page table directly;
                # this token's K/V are scattered inside the step (the
                # write_token scatter is folded into the program).
                # int8/int4 pools carry (k, v, k_scale, v_scale) — the
                # kernel dequantizes pages in-VMEM
                quant = len(pool_tree) == 4
                ks = pool_tree[2] if quant else None
                vs = pool_tree[3] if quant else None
                logits, *new_pools = decode_step_paged(
                    model, params, tokens, pool_tree[0], pool_tree[1],
                    table, positions, k_scale=ks, v_scale=vs,
                    kv_quant=pool.quant if quant else None)
                nxt = pick_token(logits, positions, sample_args)
                return nxt, tuple(new_pools)
        else:
            def decode_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                ck, cv = pool.gather(pool_tree, table)
                logits, _, (kt, vt) = decode_step_slots(
                    model, params, tokens, (ck, cv), positions)
                new_tree = pool.write_token(pool_tree, table, positions,
                                            kt, vt)
                nxt = pick_token(logits, positions, sample_args)
                return nxt, new_tree

        def chunk_fn(params, chunk, cache, start):
            return extend_cache(model, params, chunk, cache, start)

        def write_fn(pool_tree, pages_row, ks, vs):
            return pool.write_pages(pool_tree, pages_row, ks, vs)

        # speculative-decoding verify (serving/spec_decode.py): score
        # the last token + k drafts in one multi-query forward —
        # `verify_step_paged` (the fused Pallas kernel chain) when the
        # paged_verify route is on, the gather machinery
        # (models/generation.verify_step_slots) otherwise — scatter the
        # block's K/V, and compute the acceptance in-graph; the host
        # only reads [S, k+1] target tokens and [S] emit counts, never
        # the logits.  When the fused `sample` kernel also routes, the
        # paged forward returns last-layer HIDDEN rows and the lm_head
        # matmul + filter + draw fuse into one epilogue launch — the
        # [S, k+1, vocab] logits plane never touches HBM.
        K1 = self.config.spec_k + 1
        verify_paged = self.spec and self.decode_paged
        stochastic = self.spec_stochastic
        self.verify_fused_sample = False
        if verify_paged and not stochastic:
            from hetu_tpu.ops.pallas import resolve_route
            from hetu_tpu.ops.pallas import sample as _psample
            mc = model.config
            self.verify_fused_sample = resolve_route(
                "sample", _psample.compatible(
                    (self.config.num_slots * K1, mc.hidden_size),
                    (mc.hidden_size, mc.vocab_size)))
        fused_sample = self.verify_fused_sample

        def verify_forward(params, pool_tree, table, tokens, positions,
                           pos_grid, want_hidden):
            """-> (logits_or_hidden [S, K1, ...], new pool tree)."""
            quant = len(pool_tree) == 4
            if verify_paged:
                from hetu_tpu.models.generation import verify_step_paged
                ks = pool_tree[2] if quant else None
                vs = pool_tree[3] if quant else None
                out, *new_pools = verify_step_paged(
                    model, params, tokens, pool_tree[0], pool_tree[1],
                    table, positions, k_scale=ks, v_scale=vs,
                    kv_quant=pool.quant if quant else None,
                    return_hidden=want_hidden)
                return out, tuple(new_pools)
            from hetu_tpu.models.generation import verify_step_slots
            ck, cv = pool.gather(pool_tree, table)
            logits, _, (kc, vc) = verify_step_slots(
                model, params, tokens, (ck, cv), positions)
            new_tree = pool.write_tokens(pool_tree, table, pos_grid,
                                         kc, vc)
            return logits, new_tree

        def full_sample_args(tokens, sample_args):
            """The per-slot sampling vectors, or the all-greedy ones
            when the engine runs without HETU_TPU_SERVE_SAMPLE (the
            fused/stochastic epilogues take them unconditionally;
            temp 0 rows argmax, so greedy stays greedy)."""
            if sampling_on:
                return sample_args
            S = tokens.shape[0]
            return (jnp.zeros((S,), jnp.uint32),
                    jnp.zeros((S,), jnp.float32),
                    jnp.zeros((S,), jnp.int32),
                    jnp.zeros((S,), jnp.float32))

        if stochastic:
            def verify_fn(params, pool_tree, table, tokens, positions,
                          q_probs, *sample_args):
                from hetu_tpu.serving.spec_decode import stochastic_verify
                pos_grid = positions[:, None] + jnp.arange(
                    K1, dtype=jnp.int32)
                logits, new_tree = verify_forward(
                    params, pool_tree, table, tokens, positions,
                    pos_grid, False)
                seeds, temps, top_ks, top_ps = full_sample_args(
                    tokens, sample_args)
                targets, n_emit = stochastic_verify(
                    logits, q_probs, tokens[:, 1:], seeds, pos_grid + 1,
                    temps, top_ks, top_ps)
                return targets, n_emit, new_tree
        else:
            def verify_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                pos_grid = positions[:, None] + jnp.arange(
                    K1, dtype=jnp.int32)
                out, new_tree = verify_forward(
                    params, pool_tree, table, tokens, positions,
                    pos_grid, fused_sample)
                if fused_sample:
                    from hetu_tpu.models.generation import lm_head_weight
                    from hetu_tpu.serving.sampling import \
                        sample_hidden_grid
                    seeds, temps, top_ks, top_ps = full_sample_args(
                        tokens, sample_args)
                    targets = sample_hidden_grid(
                        out, lm_head_weight(model, params), seeds,
                        pos_grid + 1, temps, top_ks, top_ps)
                elif sampling_on:
                    from hetu_tpu.serving.sampling import \
                        sample_token_grid
                    seeds, temps, top_ks, top_ps = sample_args
                    targets = sample_token_grid(out, seeds, pos_grid + 1,
                                                temps, top_ks, top_ps)
                else:
                    targets = jnp.argmax(out, axis=-1).astype(jnp.int32)
                match = (targets[:, :-1] == tokens[:, 1:]) \
                    .astype(jnp.int32)
                n_emit = jnp.cumprod(match, axis=1).sum(axis=1) + 1  # [S]
                return targets, n_emit.astype(jnp.int32), new_tree

        # prefix-cache prime (serving/prefix_cache.py): gather a slot's
        # resident shared-prefix pages into the dense prefill scratch so
        # suffix chunks attend over them (read-only — not donated)
        def prime_fn(pool_tree, pages_row):
            return pool.gather(pool_tree, pages_row[None])

        if self._moe_spec is not None:
            # resident int experts: the programs dequantize on entry, so
            # only the transient working copy is fp (the decode step's
            # expert HBM read is the quantized payload)
            from hetu_tpu.serving.experts import dequantize_expert_tree
            spec = self._moe_spec
            base_decode_fp, base_chunk_fp = decode_fn, chunk_fn
            base_verify_fp = verify_fn

            def decode_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                return base_decode_fp(dequantize_expert_tree(params, spec),
                                      pool_tree, table, tokens, positions,
                                      *sample_args)

            def chunk_fn(params, chunk, cache, start):
                return base_chunk_fp(dequantize_expert_tree(params, spec),
                                     chunk, cache, start)

            def verify_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                return base_verify_fp(dequantize_expert_tree(params, spec),
                                      pool_tree, table, tokens, positions,
                                      *sample_args)

        if self._numerics:
            # wrap the programs that contain quantize sites in a
            # numerics collector; their stats pytree rides out as one
            # extra output (empty when KV pages are exact).  The
            # unwrapped functions above ARE the unset-flag programs —
            # byte-identity by construction.
            from hetu_tpu.obs import numerics as _numerics
            base_decode, base_write = decode_fn, write_fn
            base_verify = verify_fn

            def decode_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                with _numerics.collecting() as col:
                    out = base_decode(params, pool_tree, table, tokens,
                                      positions, *sample_args)
                    stats = col.finalize()
                return out + (stats,)

            def verify_fn(params, pool_tree, table, tokens, positions,
                          *sample_args):
                with _numerics.collecting() as col:
                    out = base_verify(params, pool_tree, table, tokens,
                                      positions, *sample_args)
                    stats = col.finalize()
                return out + (stats,)

            def write_fn(pool_tree, pages_row, ks, vs):
                with _numerics.collecting() as col:
                    tree = base_write(pool_tree, pages_row, ks, vs)
                    stats = col.finalize()
                return tree, stats

        # the pool tree is donated: the KV pool is the engine's dominant
        # allocation and it flows through every step — without donation
        # XLA would copy the whole pool to update one token per slot
        # (the engine always reassigns self.pool.arrays from the
        # returned tree, so the donated input is never reused).  With
        # speculative decoding on, the verify program IS the decode-step
        # program (there is no single-token decode to build).
        if self.spec:
            self._decode_jit = None
            self._verify_jit = jax.jit(verify_fn, donate_argnums=(1,))
        else:
            self._decode_jit = jax.jit(decode_fn, donate_argnums=(1,))
            self._verify_jit = None
        self._chunk_jit = jax.jit(chunk_fn)
        self._write_jit = jax.jit(write_fn, donate_argnums=(0,))
        self._prime_jit = (jax.jit(prime_fn)
                           if self.prefix_cache is not None else None)

    # ---------------------------------------------------- numerics taps
    def _run_decode(self, *args):
        """Dispatch the decode program, peeling the numerics stats
        output when the observatory wrapped it."""
        out = self._decode_jit(*args)
        if self._numerics:
            nxt, tree, stats = out
            self._note_numerics(stats)
            return nxt, tree
        return out

    def _run_verify(self, *args):
        """Dispatch the spec-decode verify program (same numerics
        peel)."""
        out = self._verify_jit(*args)
        if self._numerics:
            targets, n_emit, tree, stats = out
            self._note_numerics(stats)
            return targets, n_emit, tree
        return out

    def _run_write(self, *args):
        out = self._write_jit(*args)
        if self._numerics:
            tree, stats = out
            self._note_numerics(stats)
            return tree
        return out

    def _note_numerics(self, stats):
        if stats:
            self._numerics_stats = stats   # latest wins until recorded

    def _maybe_record_numerics(self):
        """Every HETU_TPU_NUMERICS_EVERY engine steps, host-fetch the
        latest stats pytree and fan it out through the one numerics
        sink (RunLog record + registry gauges + telemetry)."""
        if (not self._numerics or self._numerics_stats is None
                or self.steps_done % self._numerics_every):
            return
        from hetu_tpu.obs import numerics as _numerics
        try:
            host = jax.device_get(self._numerics_stats)
        except Exception:   # telemetry never kills an engine step
            self._numerics_stats = None
            return
        self._numerics_stats = None
        _numerics.record(host, step=self.steps_done,
                         registry=self._registry, runlog=self.run_log)
        if self._num_health is not None:
            self._num_health.observe(self.steps_done, host)

    def warmup(self):
        """Compile all three programs so the first request's TTFT is not
        a compile.  The dummy decode/write still target the null page
        (zero table/row), so pool CONTENT is untouched — but the pool
        trees are donated through the calls, so the returned trees must
        be committed back (discarding them would leave self.pool.arrays
        pointing at deleted buffers on donating backends)."""
        S, C = self.config.num_slots, self.config.prefill_chunk
        table = jnp.zeros((S, self.scheduler.max_pages), jnp.int32)
        toks = jnp.zeros(S, jnp.int32)
        pos = jnp.zeros(S, jnp.int32)
        sample_args = self._sample_args([]) if self.config.sampling else ()
        if self.spec:
            toks2 = jnp.zeros((S, self.config.spec_k + 1), jnp.int32)
            extra = ()
            if self.spec_stochastic:
                extra = (jnp.full(
                    (S, self.config.spec_k,
                     self.model.config.vocab_size),
                    1.0 / self.model.config.vocab_size, jnp.float32),)
            nxt, _, tree = self._run_verify(
                self.params, self.pool.arrays.tree(), table, toks2, pos,
                *extra, *sample_args)
        else:
            nxt, tree = self._run_decode(
                self.params, self.pool.arrays.tree(), table, toks, pos,
                *sample_args)
        self.pool.arrays = PoolArrays.from_tree(tree)
        lg, cache = self._chunk_jit(self.params,
                                    jnp.zeros((1, C), jnp.int32),
                                    self._scratch, jnp.int32(0))
        row = jnp.zeros(self.scheduler.max_pages, jnp.int32)
        tree = self._run_write(self.pool.arrays.tree(), row,
                               cache[0][:, 0], cache[1][:, 0])
        self.pool.arrays = PoolArrays.from_tree(tree)
        if self._prime_jit is not None:
            jax.block_until_ready(
                self._prime_jit(self.pool.arrays.tree(), row))
        jax.block_until_ready(nxt)
        return self

    # ----------------------------------------------------------- intake
    def submit(self, req: Request, now: Optional[float] = None):
        if req.sampling.temperature > 0 and not self.config.sampling:
            raise ValueError(
                f"request {req.rid} asks for sampling (temperature "
                f"{req.sampling.temperature}) but the engine was built "
                "greedy-only — set HETU_TPU_SERVE_SAMPLE=1 / "
                "ServeConfig(sampling=True)")
        if now is not None:
            req.arrival_t = now
        self.scheduler.submit(req)
        self._registry.inc("serve.requests_submitted")
        self._registry.inc("serve.requests_submitted_class",
                           slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_submit(req)

    def note_remote_submit(self, req: Request,
                           now: Optional[float] = None):
        """Account a request whose PREFILL runs on a remote tier
        (serving/disagg.py): the submission counters and the tracer's
        queued span open here — on the decode replica that will own the
        request — but the request does NOT enter the scheduler queue
        (it admits via `adopt_prefilled` when its KV shipment lands, or
        re-enters through `submit` on colocation fallback)."""
        if req.sampling.temperature > 0 and not self.config.sampling:
            raise ValueError(
                f"request {req.rid} asks for sampling (temperature "
                f"{req.sampling.temperature}) but the engine was built "
                "greedy-only — set HETU_TPU_SERVE_SAMPLE=1 / "
                "ServeConfig(sampling=True)")
        if now is not None:
            req.arrival_t = now
        self._registry.inc("serve.requests_submitted")
        self._registry.inc("serve.requests_submitted_class",
                           slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_submit(req)

    def adopt_prefilled(self, req: Request, ks, vs, t1: int,
                        now: float) -> bool:
        """Adopt a prefill-tier KV shipment (serving/disagg.py): admit
        `req` straight into a free slot (`admit_direct` — it never
        queues), scatter the shipped scratch K/V into its pages through
        the SAME write program colocated prefill uses, seed the stream
        with the shipped first token, and join the decode batch.  The
        shipment carries the full [L, max_len, n_kv, hd] scratch the
        prefill tier computed with the identical chunk program, so pool
        content — and therefore every subsequent decode token — is
        byte-identical to the single-engine run.  False = no slot/
        reservation/quota headroom right now; the caller retries next
        step (the shipment stays pending, the dedupe seq unburned)."""
        adm = self.scheduler.admit_direct(req, now)
        if adm is None:
            reason = self.scheduler.last_stall or "none"
            self._registry.inc("serve.admission_stalls", reason=reason)
            if self.tracer is not None:
                self.tracer.on_stall([req.rid], reason)
            return False
        slot_idx, st = adm
        if self.ledger is not None:
            self.ledger.on_admit(req.rid, len(st.pages), now)
        if self.tracer is not None:
            self.tracer.on_admit(req, slot_idx, now, shared_tokens=0)
        pages_row = np.full(self.scheduler.max_pages, PagePool.NULL_PAGE,
                            np.int32)
        pages_row[: len(st.pages)] = st.pages
        tree = self._run_write(self.pool.arrays.tree(),
                               jnp.asarray(pages_row),
                               jnp.asarray(ks), jnp.asarray(vs))
        self.pool.arrays = PoolArrays.from_tree(tree)
        st.prefilling = False
        st.pos = req.prompt_len
        st.generated.append(int(t1))
        st.stats.first_token_t = now
        ttft = st.stats.ttft_s
        self._registry.observe("serve.ttft_s", ttft)
        self._registry.observe("serve.ttft_s_class", ttft,
                               slo_class=req.slo.name)
        if st.stats.queue_wait_s is not None:
            self._registry.observe("serve.queue_wait_s",
                                   st.stats.queue_wait_s)
        self._registry.inc("serve.tokens_out")
        self._registry.inc("serve.disagg_adoptions")
        if self.tracer is not None:
            self.tracer.on_first_token(req, slot_idx, now, chunk=0)
        if self.health is not None:
            self.health.observe_ttft(ttft, step=self.steps_done, t=now)
        if self._sampled(req.rid):
            self._log_serve(event="admit", req=req.rid,
                            slot=slot_idx, prompt_len=req.prompt_len,
                            chunks=0, ttft_s=ttft,
                            queue_wait_s=st.stats.queue_wait_s, now=now,
                            slo_class=req.slo.name, tenant=req.tenant,
                            shared_tokens=0, disagg=True,
                            queue_depth=self.scheduler.queue_depth,
                            page_util=self.pool.utilization,
                            **self._weight_fields())
        # a max_new=1 request finishes at adoption: park its result with
        # the between-step fault results; the next step() drains them
        self._maybe_finish(slot_idx, st, int(t1), now,
                           self._fault_results)
        return True

    def _sampled(self, rid: int) -> bool:
        """Does `rid` emit per-request serve events?  Deterministic
        hashed 1-in-N (HETU_TPU_RUNLOG_SERVE_SAMPLE, request.py
        `rid_sampled`) — the same requests are sampled on every replay,
        and N=1 (the default) keeps the RunLog byte-identical to the
        pre-sampling engine.  Registry counters are never sampled."""
        return rid_sampled(rid, self.config.serve_sample)

    def _weight_fields(self) -> dict:
        """The sample_weight stamp for sampled per-request events (only
        when sampling is actually on — the N=1 record shape is
        unchanged)."""
        n = self.config.serve_sample
        return {"sample_weight": n} if n > 1 else {}

    def _log_serve(self, **fields):
        """One serve event to every attached sink: the RunLog and (when
        a TelemetrySource rides along) the cluster telemetry push.
        Every record declares its ``clock`` basis (driver|wall — the
        engine drives a virtual driver clock; see obs/spans.py) so the
        fleet stitcher can refuse mixed-basis inputs."""
        fields.setdefault("clock", self.clock_basis)
        rec = None
        if self.run_log is not None:
            rec = self.run_log.log("serve", **fields)
        if self.telemetry is not None:
            if rec is None:
                rec = dict(fields, kind="serve", t=time.time())
            self.telemetry.note_event(rec)

    # ------------------------------------------------------------- step
    def step(self, now: float) -> List[RequestResult]:
        """One engine iteration at driver time `now`: admit every
        admissible queued request (reservation only), advance each
        PREFILLING slot by exactly ONE chunk, then one decode step over
        the slots whose prefill is complete.  One-chunk-per-step is the
        disaggregation contract: a long prompt adds engine steps for its
        own slot, never a multi-chunk stall to the decode batch's
        inter-token gap.  Returns requests that finished this step."""
        t0 = time.perf_counter()

        def clock() -> float:
            return now + (time.perf_counter() - t0)

        finished: List[RequestResult] = []
        if self._fault_results:
            finished.extend(self._fault_results)
            self._fault_results.clear()
        if self.config.deadline:
            # before admissions: an expired queued request must not
            # grab a slot on the step it dies
            self._expire_deadlines(clock(), finished)
        while True:
            t_adm = clock()
            adm = self.scheduler.admit_next(t_adm)
            if adm is None:
                # SLO-class preemption (HETU_TPU_SERVE_PREEMPT): a
                # stalled strictly-higher-priority head may evict the
                # lowest-priority live slot and retry the admission
                if (self.config.preempt and self.scheduler.queue
                        and self._try_preempt(clock())):
                    continue
                break
            slot_idx, st = adm
            st.prefilling = True
            if self.ledger is not None:
                self.ledger.on_admit(st.request.rid, len(st.pages), t_adm)
            self._start_prefill(slot_idx, st, t_adm)
            if self.tracer is not None:
                self.tracer.on_admit(st.request, slot_idx, t_adm,
                                     shared_tokens=st.shared_tokens)
        if self.scheduler.queue:
            # admission declined with work queued: count the stall and
            # stamp the scheduler's reserve-on-admit attribution on
            # every waiting request (the counter must not depend on the
            # tracing flag — it is the registry's stall signal)
            reason = self.scheduler.last_stall or "none"
            self._registry.inc("serve.admission_stalls", reason=reason)
            if self.tracer is not None:
                self.tracer.on_stall(
                    [r.rid for r in self.scheduler.queue], reason)

        for i in self.scheduler.active_slots():
            st = self.scheduler.slots[i]
            if st is not None and st.prefilling:
                self._advance_prefill(i, st, clock, finished)

        active = [i for i in self.scheduler.active_slots()
                  if not self.scheduler.slots[i].prefilling]
        if active:
            td = time.perf_counter()
            # the decode batch's inputs are DERIVED from scheduler state
            # every step (single source of truth): last emitted token +
            # next write position per decoding slot; empty/prefilling
            # rows ride along at (0, 0) writing into their masked region
            S = self.config.num_slots
            positions = np.zeros(S, np.int32)
            for i in active:
                positions[i] = self.scheduler.slots[i].pos
            sample_args = (self._sample_args(active)
                           if self.config.sampling else ())
            if self.spec:
                emitted = self._spec_decode_step(active, positions,
                                                 sample_args)
            else:
                tokens = np.zeros(S, np.int32)
                for i in active:
                    tokens[i] = self.scheduler.slots[i].generated[-1]
                nxt, pool_tree = self._run_decode(
                    self.params, self.pool.arrays.tree(),
                    self._decode_table(active),
                    jnp.asarray(tokens), jnp.asarray(positions),
                    *sample_args)
                nxt = np.asarray(nxt)
                self.pool.arrays = PoolArrays.from_tree(pool_tree)
                emitted = {i: [int(nxt[i])] for i in active}
            decode_wall = time.perf_counter() - td
            self._registry.inc("serve.decode_steps")
            # token_latency_s is the USER-visible inter-token gap: every
            # active slot advances >= one token per decode step, so the
            # gap IS the step wall.  The amortized per-token engine cost
            # (wall / tokens emitted — the throughput number) is its own
            # series; conflating them would understate latency by up to
            # num_slots x.
            n_emitted = sum(len(v) for v in emitted.values())
            self._registry.observe("serve.token_latency_s", decode_wall)
            self._registry.observe("serve.token_cost_s",
                                   decode_wall / max(n_emitted, 1))
            tnow = clock()
            n_done0 = len(finished)
            for i in active:
                st = self.scheduler.slots[i]
                for tok in emitted[i]:
                    st.generated.append(tok)
                    st.pos += 1
                    self._registry.inc("serve.tokens_out")
                    if self.tracer is not None:
                        self.tracer.on_token(st.request, tnow)
                    self._maybe_finish(i, st, tok, tnow, finished)
                    if self.scheduler.slots[i] is None:
                        break            # finished: drop surplus drafts
            if self.tracer is not None and len(finished) > n_done0:
                # an eviction changed the batch composition: split the
                # survivors' decode segments so the boundary is visible
                survivors = [self.scheduler.slots[i].request.rid
                             for i in self.scheduler.active_slots()
                             if not self.scheduler.slots[i].prefilling]
                if survivors:
                    self.tracer.on_split(survivors, tnow, "evict")

        self.steps_done += 1
        self._maybe_record_numerics()
        self._registry.set_gauge("serve.queue_depth",
                                 self.scheduler.queue_depth)
        self._registry.set_gauge("serve.slot_occupancy",
                                 self.scheduler.occupancy)
        self._registry.set_gauge("serve.page_util", self.pool.utilization)
        for t in self.config.quotas:
            # quota gauges: each quota'd tenant's live usage, so a
            # registry snapshot shows who is pinned at their cap
            self._registry.set_gauge("serve.tenant_slots",
                                     self.scheduler.tenant_slots.get(t, 0),
                                     tenant=t)
            self._registry.set_gauge("serve.tenant_pages",
                                     self.scheduler.tenant_pages.get(t, 0),
                                     tenant=t)
        if self.health is not None:
            self.health.observe_step(
                self.steps_done, queue_depth=self.scheduler.queue_depth,
                page_util=self.pool.utilization, t=clock())
        if self.config.brownout:
            self._maybe_brownout(clock(), finished)

        if self.reshard is not None:
            tier = self.reshard.observe(self.scheduler.queue_depth)
            if tier is not None:
                t_pause0 = clock()
                with self._registry.timer("serve.reshard_s"):
                    self.params = self.reshard.reshard(self.params, tier)
                    if self.config.kv_repage:
                        # the KV pool rides the same hot switch
                        # (HETU_TPU_SERVE_KV_REPAGE): in-flight requests
                        # keep their cache across the tier change
                        self.pool.arrays = self.reshard.reshard_pool(
                            self.pool.arrays, tier)
                        self._registry.inc("serve.kv_repages")
                t_pause1 = clock()
                self._registry.inc("serve.reshards")
                if self.tracer is not None:
                    paused = [self.scheduler.slots[i].request.rid
                              for i in self.scheduler.active_slots()
                              if not self.scheduler.slots[i].prefilling]
                    self.tracer.on_pause(paused, t_pause0, t_pause1,
                                         tier=tier)
                self._log_serve(event="reshard", tier=tier,
                                strategy=self.reshard.describe(tier),
                                now=t_pause1,
                                pause_s=t_pause1 - t_pause0,
                                queue_depth=self.scheduler.queue_depth,
                                **({"kv_repage": True}
                                   if self.config.kv_repage else {}))
        self._last_clock = clock()
        return finished

    # ----------------------------------------------------------- faults
    def _finish_faulted(self, req, now: float, finished, *, reason: str,
                        event: str, tokens, st=None, slot=None):
        """Terminate `req` with a fault outcome (`deadline_exceeded`,
        `brownout_shed`, `retry_exhausted`): the _maybe_finish
        bookkeeping — carried-stats folding, ledger cost, counters, a
        sampled serve event — for a request the model did not finish.
        `st`/`slot` identify a live incarnation (whose ledger entry
        closes); queued casualties pass neither and cost nothing."""
        stats = st.stats if st is not None else RequestStats(
            arrival_t=req.arrival_t)
        stats.done_t = now
        stats.preemptions = self._preempt_counts.pop(req.rid, 0)
        stats.retries = self.scheduler.retries.pop(req.rid, 0)
        carried = self._carried_stats.pop(req.rid, None)
        if carried is not None:
            stats.spec_proposed += carried["spec_proposed"]
            stats.spec_accepted += carried["spec_accepted"]
            stats.prefill_chunks += carried["prefill_chunks"]
        res = RequestResult(rid=req.rid, tokens=list(tokens),
                            finished_reason=reason, stats=stats)
        self._registry.inc(f"serve.{reason}")
        self._registry.inc(f"serve.{reason}_class",
                           slo_class=req.slo.name)
        cost = {}
        if self.ledger is not None and st is not None:
            cost = self.ledger.finish(
                req.rid, now, prompt_len=req.prompt_len,
                shared_tokens=stats.shared_prefix_tokens,
                tokens_out=len(res.tokens))
        if self._sampled(req.rid):
            self._log_serve(
                event=event, req=req.rid, reason=reason,
                tokens=len(res.tokens), e2e_s=stats.e2e_s, now=now,
                slo_class=req.slo.name, tenant=req.tenant,
                retries=stats.retries, preemptions=stats.preemptions,
                queue_depth=self.scheduler.queue_depth,
                **({"slot": slot} if slot is not None else {}),
                **cost, **self._weight_fields())
        finished.append(res)
        return res

    def fail_over(self, now: Optional[float] = None) -> dict:
        """The serving replica dies and a recovery replica takes over
        on the spot (the chaos `engine_kill` injection point, called
        between steps from the run() on_step hook): every in-flight
        request loses its slot, pages, and partial output.  A request
        with retry budget left (HETU_TPU_SERVE_RETRY) re-enters the
        queue behind a `replica_lost` stall span and re-prefills on
        re-admission (cheap under a warm radix prefix cache); greedy
        argmax and the (seed, position)-keyed sampler are pure
        functions of the prompt, so the replayed stream is
        token-identical to the undisturbed run — the same purity the
        preempt path already relies on.  Over-budget requests
        terminate as `retry_exhausted`, surfacing through the next
        step()'s results.  Params, pool, and compiled programs
        survive (the recovery replica inherits them); what is tested
        is the REQUEST-state recovery.  Returns
        ``{"requeued": [rids], "exhausted": [rids]}``."""
        now = self._last_clock if now is None else now
        requeued: List[int] = []
        exhausted: List[int] = []
        self._registry.inc("serve.failovers")
        for i in list(self.scheduler.active_slots()):
            st = self.scheduler.slots[i]
            req = st.request
            if (self.scheduler.retries.get(req.rid, 0)
                    < self.config.retry_budget):
                # the accrued work counters survive the requeue (the
                # preempt carry discipline); the ledger bills the
                # discarded incarnation — it re-runs on re-admission
                carried = self._carried_stats.setdefault(
                    req.rid, {"spec_proposed": 0, "spec_accepted": 0,
                              "prefill_chunks": 0})
                carried["spec_proposed"] += st.stats.spec_proposed
                carried["spec_accepted"] += st.stats.spec_accepted
                carried["prefill_chunks"] += st.stats.prefill_chunks
                if self.ledger is not None:
                    self.ledger.on_preempt(req.rid, now,
                                           ctx_start=st.shared_tokens,
                                           tokens_cached=st.pos)
                self.scheduler.requeue_lost(i)
                self._registry.inc("serve.replica_requeues")
                self._registry.inc("serve.replica_requeues_class",
                                   slo_class=req.slo.name)
                if self.tracer is not None:
                    self.tracer.on_replica_lost(req, i, now)
                if self._sampled(req.rid):
                    self._log_serve(
                        event="retry", req=req.rid, slot=i, now=now,
                        attempt=self.scheduler.retries[req.rid] + 1,
                        slo_class=req.slo.name, tenant=req.tenant,
                        tokens_discarded=len(st.generated),
                        **self._weight_fields())
                requeued.append(req.rid)
            else:
                if self.tracer is not None:
                    self.tracer.on_finish(
                        req, i, "retry_exhausted", now,
                        tokens=len(st.generated),
                        e2e_s=now - float(req.arrival_t), evicted=True)
                tokens = list(st.generated)
                self.scheduler.release(i)
                self._finish_faulted(req, now, self._fault_results,
                                     reason="retry_exhausted",
                                     event="evict", tokens=tokens,
                                     st=st, slot=i)
                exhausted.append(req.rid)
        self._log_serve(event="failover", now=now,
                        requeued=len(requeued),
                        exhausted=len(exhausted),
                        queue_depth=self.scheduler.queue_depth)
        return {"requeued": requeued, "exhausted": exhausted}

    def _expire_deadlines(self, now: float, finished):
        """Terminate every queued or live request older than its SLO
        class deadline (HETU_TPU_SERVE_DEADLINE) as `deadline_exceeded`
        — a real terminal outcome: traced, costed, counted, and
        returned through run() like any finish."""
        for req in [r for r in self.scheduler.queue
                    if r.slo.deadline_s is not None
                    and now - r.arrival_t > r.slo.deadline_s]:
            if not self.scheduler.drop_queued(req):
                continue
            if self.tracer is not None:
                self.tracer.on_expire(req, now,
                                      e2e_s=now - float(req.arrival_t))
            self._finish_faulted(req, now, finished,
                                 reason="deadline_exceeded",
                                 event="expired", tokens=[])
        for i in list(self.scheduler.active_slots()):
            st = self.scheduler.slots[i]
            req = st.request
            d = req.slo.deadline_s
            if d is None or now - req.arrival_t <= d:
                continue
            if self.tracer is not None:
                self.tracer.on_expire(req, now,
                                      tokens=len(st.generated),
                                      e2e_s=now - float(req.arrival_t))
            tokens = list(st.generated)
            self.scheduler.release(i)
            self._finish_faulted(req, now, finished,
                                 reason="deadline_exceeded",
                                 event="expired", tokens=tokens,
                                 st=st, slot=i)

    def _maybe_brownout(self, now: float, finished):
        """Sustained-pressure shedding (HETU_TPU_SERVE_BROWNOUT): page
        utilization >= brownout_page_high with >= brownout_queue_min
        queued for brownout_streak consecutive steps sheds the
        LOWEST-priority queued band as `brownout_shed` (the preempt
        priority order: smaller SLOClass.priority = less important),
        metered through the health monitor when one is attached.
        Deterministic by construction — driven only by pool and queue
        state, never the wall clock."""
        c = self.config
        if not (self.pool.utilization >= c.brownout_page_high
                and self.scheduler.queue_depth >= c.brownout_queue_min):
            self._brownout_hot = 0
            return
        self._brownout_hot += 1
        if self._brownout_hot < c.brownout_streak:
            return
        self._brownout_hot = 0
        lowest = min(r.slo.priority for r in self.scheduler.queue)
        shed = [r for r in self.scheduler.queue
                if r.slo.priority == lowest]
        for req in shed:
            if not self.scheduler.drop_queued(req):
                continue
            if self.tracer is not None:
                self.tracer.on_shed(req, now)
            self._finish_faulted(req, now, finished,
                                 reason="brownout_shed", event="shed",
                                 tokens=[])
        if self.health is not None:
            self.health.note_brownout(self.steps_done, shed=len(shed),
                                      page_util=self.pool.utilization,
                                      t=now)

    # --------------------------------------------------------- sampling
    def _sample_args(self, active):
        """Per-slot sampling-parameter vectors for the jitted programs
        (inactive rows ride along greedy at seed 0)."""
        S = self.config.num_slots
        seeds = np.zeros(S, np.uint32)
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.zeros(S, np.float32)
        for i in active:
            sp = self.scheduler.slots[i].request.sampling
            seeds[i] = sp.seed & 0xFFFFFFFF
            temps[i] = sp.temperature
            top_ks[i] = sp.top_k
            top_ps[i] = sp.top_p
        return (jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps))

    def _decode_table(self, active):
        """Page-table input for the decode batch: only decoding slots'
        rows are real; prefilling/empty rows are pinned to the null
        page.  The scheduler's table is populated at ADMISSION, so a
        still-prefilling slot's row already points at live pages — and
        under the radix prefix cache its first page is a COW-shared
        prefix page.  The ride-along (token 0, position 0) write for
        such a row must land in the null page, not in `table[slot][0]`
        row 0, or it silently corrupts position 0 of the shared prefix
        for every reader."""
        table = np.zeros_like(self.scheduler.page_table)
        for i in active:
            table[i] = self.scheduler.page_table[i]
        return jnp.asarray(table)

    # ------------------------------------------------------ spec decode
    def _spec_decode_step(self, active, positions, sample_args):
        """One speculative decode step over the active slots: draft k
        tokens per slot on the host, verify all k+1 in ONE batched
        forward, accept by sample-then-match — or by the full
        stochastic p/q rejection rule when the drafter reports its
        proposal distribution (serving/spec_decode.py).  Returns
        {slot: emitted tokens} (>= 1 per active slot)."""
        S, k = self.config.num_slots, self.config.spec_k
        w = getattr(self.drafter, "window", None)
        tokens = np.zeros((S, k + 1), np.int32)
        q_probs = (np.zeros((S, k, self.model.config.vocab_size),
                            np.float32)
                   if self.spec_stochastic else None)
        for i in active:
            st = self.scheduler.slots[i]
            # hand the drafter only the trailing window it reads —
            # O(window) per step, not O(prompt + generated)
            if w:
                from_prompt = max(0, w - len(st.generated))
                ctx = (st.request.prompt[st.request.prompt_len
                                         - from_prompt:].tolist()
                       + st.generated[-w:])
            else:
                ctx = st.request.prompt.tolist() + st.generated
            tokens[i, 0] = st.generated[-1]
            if q_probs is not None:
                sp = st.request.sampling
                tokens[i, 1:], q_probs[i] = \
                    self.drafter.propose_with_probs(
                        ctx, k, seed=sp.seed & 0xFFFFFFFF,
                        start_pos=int(positions[i]) + 1)
            else:
                tokens[i, 1:] = self.drafter.propose(ctx, k)
        extra = ((jnp.asarray(q_probs),) if q_probs is not None else ())
        targets, n_emit, pool_tree = self._run_verify(
            self.params, self.pool.arrays.tree(),
            self._decode_table(active),
            jnp.asarray(tokens), jnp.asarray(positions), *extra,
            *sample_args)
        targets = np.asarray(targets)
        n_emit = np.asarray(n_emit)
        self.pool.arrays = PoolArrays.from_tree(pool_tree)
        emitted = {}
        for i in active:
            n = int(n_emit[i])
            emitted[i] = [int(t) for t in targets[i, :n]]
            st = self.scheduler.slots[i]
            st.stats.spec_proposed += k
            st.stats.spec_accepted += n - 1
            self._registry.inc("serve.spec_proposed", value=k)
            self._registry.inc("serve.spec_accepted", value=n - 1)
            self._registry.observe("serve.spec_emitted", float(n))
        return emitted

    # ------------------------------------------------------- preemption
    def _try_preempt(self, now: float) -> bool:
        """Evict-and-requeue the lowest-priority live slot when the
        stalled queue head outranks it (HETU_TPU_SERVE_PREEMPT).
        Returns True when a slot was freed (the caller retries
        admission)."""
        head = self.scheduler.queue[0]
        victim = self.scheduler.preempt_victim(head.slo.priority)
        if victim is None:
            return False
        st = self.scheduler.slots[victim]
        req = st.request
        self._preempt_counts[req.rid] = \
            self._preempt_counts.get(req.rid, 0) + 1
        carried = self._carried_stats.setdefault(
            req.rid, {"spec_proposed": 0, "spec_accepted": 0,
                      "prefill_chunks": 0})
        carried["spec_proposed"] += st.stats.spec_proposed
        carried["spec_accepted"] += st.stats.spec_accepted
        carried["prefill_chunks"] += st.stats.prefill_chunks
        if self.ledger is not None:
            # the victim's computed-but-discarded work is part of what
            # the request truly cost (it re-runs on re-admission)
            self.ledger.on_preempt(req.rid, now,
                                   ctx_start=st.shared_tokens,
                                   tokens_cached=st.pos)
        self.scheduler.preempt(victim)
        self._registry.inc("serve.preemptions")
        self._registry.inc("serve.preemptions_class",
                           slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_preempt(req, victim, now, by=head.rid)
        if self._sampled(req.rid):
            self._log_serve(event="preempt", req=req.rid, slot=victim,
                            by=head.rid, by_class=head.slo.name,
                            slo_class=req.slo.name, tenant=req.tenant,
                            now=now,
                            tokens_discarded=len(st.generated),
                            queue_depth=self.scheduler.queue_depth,
                            **self._weight_fields())
        return True

    def _first_token(self, req, logits_row, position: int) -> int:
        """The TTFT token from the final prefill chunk's logits — the
        shared pure helper, keyed by this engine's sampling config."""
        return first_token_from_logits(req, logits_row, position,
                                       sampling=self.config.sampling)

    # ---------------------------------------------------------- prefill
    def _start_prefill(self, slot_idx: int, st, now: float):
        """Attach the prefill scratch to a freshly admitted slot.  With
        a radix-cache hit the scratch is PRIMED: the shared pages
        gather into positions [0, shared_tokens) (exact in the fp page
        mode — the bytes written at caching time), so suffix chunks
        attend over the resident prefix and prefill FLOPs drop to the
        unshared suffix."""
        if st.shared_tokens:
            row = np.full(self.scheduler.max_pages, PagePool.NULL_PAGE,
                          np.int32)
            shared_pages = st.shared_tokens // self.pool.page_size
            row[:shared_pages] = st.pages[:shared_pages]
            st.prefill_cache = self._prime_jit(self.pool.arrays.tree(),
                                               jnp.asarray(row))
            self._registry.inc("serve.prefix_hits")
            self._registry.inc("serve.prefix_shared_tokens",
                               value=st.shared_tokens)
        else:
            st.prefill_cache = self._scratch
            if self.prefix_cache is not None:
                self._registry.inc("serve.prefix_misses")
        if self.prefix_cache is not None:
            self._registry.set_gauge("serve.prefix_cache_pages",
                                     self.prefix_cache.num_pages)

    def _advance_prefill(self, slot_idx: int, st, clock, finished):
        """Run ONE prefill chunk for a prefilling slot; on the last
        chunk, scatter the scratch K/V into the slot's pages, emit the
        first token, and join the decode batch.  A radix-cache hit
        starts chunking at the shared boundary (`st.shared_tokens` —
        the primed prefix is already in the scratch) and never
        re-writes the shared pages."""
        req = st.request
        plen = req.prompt_len
        C = self.config.prefill_chunk
        base = st.shared_tokens
        padded = base + math.ceil((plen - base) / C) * C
        s = base + st.chunks_done * C
        ids = np.zeros(C, np.int32)
        seg = req.prompt[s: min(s + C, plen)]
        ids[: len(seg)] = seg
        logits, st.prefill_cache = self._chunk_jit(
            self.params, jnp.asarray(ids[None]), st.prefill_cache,
            jnp.int32(s))
        st.chunks_done += 1
        st.stats.prefill_chunks += 1
        self._registry.inc("serve.prefill_chunks")
        if s + C < padded:
            if self.tracer is not None:
                self.tracer.on_chunk(req, clock(), st.chunks_done)
            return                        # more chunks: next engine step
        # first generated token: at the last VALID prompt position of
        # the final chunk (padding tail positions carry garbage) —
        # argmax, or the seeded sampler for sampling requests (same
        # key derivation as the decode program: position plen)
        t1 = self._first_token(req, logits[0, plen - 1 - s], plen)

        # scatter only the FRESHLY prefilled pages; shared-prefix pages
        # already hold these tokens' K/V (they are what the scratch was
        # primed from) and are read-only to this slot (COW) — their row
        # entries point at the null page so the write lands harmlessly
        pages_row = np.full(self.scheduler.max_pages, PagePool.NULL_PAGE,
                            np.int32)
        pages_row[: len(st.pages)] = st.pages
        pages_row[: base // self.pool.page_size] = PagePool.NULL_PAGE
        tree = self._run_write(self.pool.arrays.tree(),
                               jnp.asarray(pages_row),
                               st.prefill_cache[0][:, 0],
                               st.prefill_cache[1][:, 0])
        self.pool.arrays = PoolArrays.from_tree(tree)
        if self.prefix_cache is not None:
            # index the finished prompt: full page-blocks not yet
            # cached adopt this request's pages (incref — the slot
            # keeps its own reference and releases it on finish)
            self.prefix_cache.insert(req.prompt, st.pages, clock())

        st.prefilling = False
        st.prefill_cache = None
        st.pos = plen
        st.generated.append(t1)
        tnow = clock()
        st.stats.first_token_t = tnow
        ttft = st.stats.ttft_s
        self._registry.observe("serve.ttft_s", ttft)
        self._registry.observe("serve.ttft_s_class", ttft,
                               slo_class=req.slo.name)
        if st.stats.queue_wait_s is not None:
            self._registry.observe("serve.queue_wait_s",
                                   st.stats.queue_wait_s)
        self._registry.inc("serve.tokens_out")
        if self.tracer is not None:
            self.tracer.on_first_token(req, slot_idx, tnow,
                                       chunk=st.chunks_done)
        if self.health is not None:
            self.health.observe_ttft(ttft, step=self.steps_done, t=tnow)
        if self._sampled(req.rid):
            self._log_serve(event="admit", req=req.rid,
                            slot=slot_idx, prompt_len=plen,
                            chunks=st.stats.prefill_chunks, ttft_s=ttft,
                            queue_wait_s=st.stats.queue_wait_s, now=tnow,
                            slo_class=req.slo.name, tenant=req.tenant,
                            shared_tokens=st.shared_tokens,
                            queue_depth=self.scheduler.queue_depth,
                            page_util=self.pool.utilization,
                            **self._weight_fields())
        self._maybe_finish(slot_idx, st, t1, tnow, finished)

    # ----------------------------------------------------------- finish
    def _maybe_finish(self, slot_idx: int, st, tok: int, tnow: float,
                      finished):
        req = st.request
        reason = None
        if req.eos_token_id is not None and tok == req.eos_token_id:
            reason = "eos"
        elif len(st.generated) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        st.stats.done_t = tnow
        res = RequestResult(rid=req.rid, tokens=list(st.generated),
                            finished_reason=reason, stats=st.stats)
        self.scheduler.release(slot_idx)
        self._registry.inc("serve.requests_done")
        self._registry.inc("serve.requests_done_class",
                           slo_class=req.slo.name)
        if st.stats.e2e_s is not None:
            self._registry.observe("serve.e2e_s", st.stats.e2e_s)
            self._registry.observe("serve.e2e_s_class", st.stats.e2e_s,
                                   slo_class=req.slo.name)
        if self.tracer is not None:
            self.tracer.on_finish(req, slot_idx, reason, tnow,
                                  tokens=len(res.tokens),
                                  e2e_s=st.stats.e2e_s)
        st.stats.preemptions = self._preempt_counts.pop(req.rid, 0)
        st.stats.retries = self.scheduler.retries.pop(req.rid, 0)
        carried = self._carried_stats.pop(req.rid, None)
        if carried is not None:
            # work spent before each preemption belongs to this run
            st.stats.spec_proposed += carried["spec_proposed"]
            st.stats.spec_accepted += carried["spec_accepted"]
            st.stats.prefill_chunks += carried["prefill_chunks"]
        cost = {}
        if self.ledger is not None:
            cost = self.ledger.finish(
                req.rid, tnow, prompt_len=req.prompt_len,
                shared_tokens=st.stats.shared_prefix_tokens,
                tokens_out=len(res.tokens))
        if self._sampled(req.rid):
            self._log_serve(
                event="done", req=req.rid, slot=slot_idx,
                reason=reason, tokens=len(res.tokens),
                ttft_s=st.stats.ttft_s, e2e_s=st.stats.e2e_s,
                tokens_per_s=res.tokens_per_s, now=tnow,
                slo_class=req.slo.name, tenant=req.tenant,
                slo_ttft_s=req.slo.ttft_s,
                slo_token_gap_s=req.slo.token_gap_s,
                spec_proposed=st.stats.spec_proposed,
                spec_accepted=st.stats.spec_accepted,
                shared_prefix_tokens=st.stats.shared_prefix_tokens,
                prompt_len=req.prompt_len,
                preemptions=st.stats.preemptions,
                queue_depth=self.scheduler.queue_depth,
                slot_occupancy=self.scheduler.occupancy,
                page_util=self.pool.utilization,
                **({"retries": st.stats.retries}
                   if st.stats.retries else {}),
                **cost, **self._weight_fields())
        finished.append(res)

    # -------------------------------------------------------------- run
    def run(self, requests: Sequence[Request], *, start: float = 0.0,
            on_step=None) -> List[RequestResult]:
        """Drive the engine over a request trace to completion under a
        virtual clock: arrivals come from each request's `arrival_t`,
        and time advances by the real wall cost of each engine step —
        deterministic token output, realistic latency accounting.

        ``on_step(step_index)`` (optional) runs at each step boundary
        INSIDE the timed window, so any wall time it spends (a chaos
        slow-decode injection, a host-side stall) inflates the virtual
        clock exactly like a slow engine step would — the hook the
        chaos harness drives instead of forking this loop."""
        pending = sorted(requests, key=lambda r: (r.arrival_t, r.rid))
        now = start
        results: List[RequestResult] = []
        i = 0
        step_idx = 0
        while True:
            while i < len(pending) and pending[i].arrival_t <= now + 1e-12:
                self.submit(pending[i])
                i += 1
            if not self.scheduler.active_slots() and not self.scheduler.queue:
                if i >= len(pending):
                    break
                now = max(now, pending[i].arrival_t)   # idle-skip to next
                continue
            t0 = time.perf_counter()
            if on_step is not None:
                # chaos hooks fire here (maybe_chaos_serving /
                # maybe_slow_step); give between-step fault events a
                # current driver timestamp
                self._last_clock = max(self._last_clock, now)
                on_step(step_idx)
            results.extend(self.step(now))
            now += time.perf_counter() - t0
            step_idx += 1
        if self.run_log is not None or self.telemetry is not None:
            n_tokens = sum(len(r.tokens) for r in results)
            elapsed = max(now - start, 1e-9)
            self._log_serve(event="report",
                            requests=len(results), tokens=n_tokens,
                            elapsed_s=elapsed, now=now,
                            tokens_per_s=n_tokens / elapsed)
        return sorted(results, key=lambda r: r.rid)

    def close(self):
        if self._owns_runlog and self.run_log is not None:
            self.run_log.close()
