"""Per-request serving cost ledger: analytic FLOPs / bytes / page-seconds
attribution.

The Galvatron line (PAPERS.md) stands on calibrated analytic cost models
instead of hardware timers; this module applies the same discipline to
PER-REQUEST serving cost so a fleet run can answer "what did tenant X's
traffic actually consume?" without a profiler.  Every number is derived
from the same closed-form models the bench records already use:

    prefill/decode FLOPs   2N matmul FLOPs per token + 4*L*hidden per
                           cached context position (bench.py
                           `_hardware_free_serving`'s ``flops_tok``),
                           summed in closed form over the positions the
                           request actually computed — shared prefix
                           tokens (radix cache hits) cost nothing
    KV page-seconds        pages held x residency seconds, accumulated
                           across preemption epochs (a preempted request
                           re-pays for its re-admission residency)
    resident KV byte-secs  page-seconds x page_size x
                           `kv_pool.kv_bytes_per_token` (the one
                           analytic byte model for cache footprint)
    wire bytes             (prompt + generated tokens) x the per-token
                           wire price (int32 token ids by default)

`CostLedger` is the host-side accumulator the engine and the fleet
simulator both drive: `on_admit`/`on_release` bracket residency epochs,
`finish` closes the ledger entry and returns the ``cost_*`` fields that
ride on the ``serve`` done event — `serving/slo_report.py` (the ONE
serving RunLog reader) aggregates them per tenant.  No jax anywhere:
pure float arithmetic, safe in the 10^6-request sim hot loop.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from hetu_tpu.serving.kv_pool import kv_bytes_per_token

#: the ``cost_*`` fields a costed done event carries (schema doc —
#: obs/runlog.py references this tuple; slo_report sums exactly these)
COST_FIELDS = ("cost_prefill_flops", "cost_decode_flops", "cost_page_s",
               "cost_kv_byte_s", "cost_wire_bytes")


@dataclasses.dataclass(frozen=True)
class CostModel:
    """The per-token prices (pure counts, no time): what one computed
    token / one resident page costs.  Frozen — one model prices every
    request of a run identically."""
    #: matmul FLOPs per computed token (2 * N_params)
    flops_per_token: float
    #: attention FLOPs per computed token per cached context position
    #: (qk + pv = 4 * L * hidden — bench.py's ``flops_tok`` slope)
    attn_flops_per_ctx: float
    #: cache bytes one token position occupies (kv_pool byte model)
    kv_bytes_per_token: float
    #: tokens per KV page (prices page-seconds into byte-seconds)
    page_size: int
    #: wire bytes per prompt/generated token (int32 ids = 4)
    wire_bytes_per_token: float = 4.0

    @staticmethod
    def from_model_dims(*, num_params: float, num_layers: int,
                        hidden_size: int, num_kv_heads: int, head_dim: int,
                        page_size: int, kv_mode: str = "fp32",
                        wire_bytes_per_token: float = 4.0) -> "CostModel":
        """Price from model dimensions — the same inputs bench.py's
        serving record uses, so ledger FLOPs and bench FLOPs can never
        disagree on the formula."""
        return CostModel(
            flops_per_token=2.0 * float(num_params),
            attn_flops_per_ctx=4.0 * num_layers * hidden_size,
            kv_bytes_per_token=kv_bytes_per_token(
                num_layers, num_kv_heads, head_dim, kv_mode),
            page_size=page_size,
            wire_bytes_per_token=wire_bytes_per_token)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    # ------------------------------------------------------ closed forms
    def compute_flops(self, ctx_start: int, n_tokens: int) -> float:
        """FLOPs to compute `n_tokens` consecutive positions whose
        attention contexts are ctx_start, ctx_start+1, ...: the 2N
        matmuls plus the arithmetic-series attention term."""
        if n_tokens <= 0:
            return 0.0
        ctx_sum = n_tokens * ctx_start + n_tokens * (n_tokens - 1) / 2.0
        return (self.flops_per_token * n_tokens
                + self.attn_flops_per_ctx * ctx_sum)


@dataclasses.dataclass
class _Acct:
    """One request's open ledger entry."""
    pages: int = 0
    epoch_t0: Optional[float] = None
    page_s: float = 0.0
    preempt_flops: float = 0.0    # prefill work discarded by preemptions


class CostLedger:
    """Accumulates per-request residency across admission epochs and
    prices the finished request.  Drive it with the scheduler's
    admit/release timeline; `finish` pops the entry (the ledger holds
    only LIVE requests — bounded memory at 10^6 requests)."""

    def __init__(self, model: CostModel):
        self.model = model
        self._open: Dict[int, _Acct] = {}
        #: totals across finished requests (the invariant-check summary)
        self.finished = 0

    def on_admit(self, rid: int, n_pages: int, now: float):
        acct = self._open.setdefault(rid, _Acct())
        acct.pages = n_pages
        acct.epoch_t0 = now

    def on_release(self, rid: int, now: float):
        """Close the current residency epoch (finish OR preemption)."""
        acct = self._open.get(rid)
        if acct is None or acct.epoch_t0 is None:
            return
        acct.page_s += acct.pages * (now - acct.epoch_t0)
        acct.epoch_t0 = None

    def on_preempt(self, rid: int, now: float, *, ctx_start: int,
                   tokens_cached: int):
        """A preemption discards the victim's computed-but-unfinished
        work; the re-run pays again, so the DISCARDED FLOPs are part of
        what the request truly cost."""
        self.on_release(rid, now)
        acct = self._open.get(rid)
        if acct is not None:
            acct.preempt_flops += self.model.compute_flops(
                ctx_start, max(0, tokens_cached - ctx_start))

    def finish(self, rid: int, now: float, *, prompt_len: int,
               shared_tokens: int, tokens_out: int) -> Dict[str, Any]:
        """Close the entry and return the ``cost_*`` done-event fields.
        ``shared_tokens`` (radix-cache resident prefix) never ran, so it
        costs no prefill FLOPs — cache hits are visible as cost savings."""
        self.on_release(rid, now)
        acct = self._open.pop(rid, _Acct())
        m = self.model
        prefill = m.compute_flops(shared_tokens,
                                  prompt_len - shared_tokens)
        decode = m.compute_flops(prompt_len, tokens_out)
        self.finished += 1
        return {
            "cost_prefill_flops": prefill + acct.preempt_flops,
            "cost_decode_flops": decode,
            "cost_page_s": acct.page_s,
            "cost_kv_byte_s": acct.page_s * m.page_size
            * m.kv_bytes_per_token,
            "cost_wire_bytes": (prompt_len + tokens_out)
            * m.wire_bytes_per_token,
        }

    @property
    def open_count(self) -> int:
        return len(self._open)


def aggregate_costs(rows) -> Optional[Dict[str, Any]]:
    """Sum the ``cost_*`` fields over per-request report rows (sample
    weights applied), grouped per tenant + a fleet total.  None when no
    row carries a ledger — cost-free runs keep their report shape."""
    tenants: Dict[str, Dict[str, float]] = {}
    total = {k: 0.0 for k in COST_FIELDS}
    seen = False
    for r in rows:
        if r.get(COST_FIELDS[0]) is None:
            continue
        seen = True
        w = float(r.get("sample_weight") or 1.0)
        t = str(r.get("tenant") or "default")
        bucket = tenants.setdefault(t, {k: 0.0 for k in COST_FIELDS})
        for k in COST_FIELDS:
            v = float(r.get(k) or 0.0) * w
            bucket[k] += v
            total[k] += v
    if not seen:
        return None
    return {"by_tenant": {t: dict(v) for t, v in sorted(tenants.items())},
            "total": total}
